"""Pallas TPU kernels: fused dequant-matmul over int8 / packed-int4 weights.

Decode throughput is weight-bandwidth-bound: every generated token
re-reads every matmul weight (ops/quant.py's module docstring). The
quantized formats halve / quarter the bytes *stored*, and XLA usually
fuses the dequant multiply into the matmul's operand read — but "usually"
is a fusion-heuristic promise, not a contract: a materialized
full-precision dequant copy silently restores the bf16 byte count and
erases the entire point of the format. These kernels make the contract
explicit: the packed weight is the operand the kernel streams from HBM
(int8 bytes for ``{"q","scale"}``, nibble-packed bytes for
``{"q4","scale"}``), and the unpack + pure-shift dequant happens on the
VMEM-resident tile inside the kernel body. The weight travels HBM→VMEM
exactly once per matmul, at its packed width.

Kernel shape (both formats): grid (M/bm, N/bn, K/bk), K innermost so the
f32 accumulator tile persists in VMEM scratch across the contraction
(initialized at k==0, scaled + written at the last k block). The weight
is never padded or copied — block sizes are chosen to divide its true
dims (``_plan_blocks``); only the activation pads its row count (cheap:
activations are a few KB against MBs of weights).

int4 layout note: ``pack_int4`` interleaves rows (byte k holds row 2k in
its low nibble, 2k+1 in its high), so an in-kernel unpack to the dense
[K, N] layout would need a sublane interleave (stack + reshape) that
Mosaic lowers poorly. Instead the *activation* deinterleaves outside the
kernel — ``x_even = x[..., 0::2]``, ``x_odd = x[..., 1::2]`` — and the
kernel computes ``x_even @ lo + x_odd @ hi`` with ``lo``/``hi``
sign-extended from the packed byte by pure shifts. Same result, zero
reshapes on the weight path, and the packed operand streams as-is. An
odd contraction width pads one zero *activation* column, matching the
zero row ``pack_int4`` added.

Flag-gated like the attention kernels (``use_pallas_decode``): callers
pass ``use_pallas=True`` into ``ops.quant.matmul``, which dispatches
here when the weight leaf is quantized and the shape is supported
(``fused_supported``), and ``interpret=True`` runs the same kernels on
CPU for the tier-1 byte-parity pins (tests/test_pallas.py,
tests/test_quant.py). See docs/kernels.md for the full inventory.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_SUBLANE = 8
# Per-step VMEM working-set budget for the whole-K fast path (one x
# block + one weight block; Pallas double-buffers, scratch/out ride on
# top). Conservative against the ~16 MiB TensorCore VMEM.
_QMM_VMEM_BUDGET = 3 << 20


def _pick_tile(dim: int, candidates: tuple[int, ...]) -> int | None:
    """Largest candidate dividing ``dim`` exactly — the weight is never
    padded (padding would copy the packed operand, defeating the
    stream-once contract)."""
    for c in candidates:
        if dim % c == 0:
            return c
    return None


def _plan_blocks(
    M: int, K: int, N: int, x_itemsize: int, w_itemsize: int
) -> tuple[int, int, int] | None:
    """(bm, bk, bn) for an [M, K] @ [K, N] blocked matmul, or None when
    no block assignment divides the weight dims (caller falls back to
    the XLA path). ``K`` is the *stored* contraction width (packed rows
    for int4)."""
    bn = _pick_tile(N, (512, 256, 128))
    if bn is None:
        if N > 2048:
            return None
        bn = N
    bm = min(256, -(-M // _SUBLANE) * _SUBLANE)
    # Whole-K keeps one dot per (i, j) program — no partial-sum
    # reassociation vs the XLA path — whenever the working set fits.
    if bm * K * x_itemsize + K * bn * w_itemsize <= _QMM_VMEM_BUDGET:
        bk = K
    else:
        bk = _pick_tile(K, (2048, 1024, 512, 256, 128))
        if bk is None:
            if K > 8192:
                return None
            bk = K
    return bm, bk, bn


def _qmm_int8_kernel(
    x_ref,  # VMEM [bm, bk] activation block (f32/bf16)
    w_ref,  # VMEM [bk, bn] int8 weight block — streamed packed
    s_ref,  # VMEM [1, bn] f32 per-output-channel scales
    o_ref,  # VMEM [bm, bn]
    acc_ref,  # VMEM [bm, bn] f32 scratch, persists across the k grid dim
    *,
    compute_dtype,
):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Dequant is deferred: the int8 block upcasts in VMEM and the scale
    # multiplies the accumulator once at the end (scales are per output
    # channel, so they commute with the K sum).
    acc_ref[:] += jax.lax.dot_general(
        x_ref[...],
        w_ref[...].astype(compute_dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[:] = (acc_ref[:] * s_ref[...]).astype(o_ref.dtype)


def _qmm_int4_kernel(
    xe_ref,  # VMEM [bm, bk] even-position activation block
    xo_ref,  # VMEM [bm, bk] odd-position activation block
    p_ref,  # VMEM [bk, bn] packed int4 weight block — streamed packed
    s_ref,  # VMEM [1, bn] f32 scales
    o_ref,  # VMEM [bm, bn]
    acc_ref,  # VMEM [bm, bn] f32 scratch
    *,
    compute_dtype,
):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Pure-shift nibble dequant on the VMEM-resident tile: sign-extend
    # the low nibble (shift up, arithmetic shift back) and the high
    # nibble (arithmetic shift alone) — the same arithmetic as
    # ops.quant.unpack_int4, minus its row interleave (the activation
    # halves absorb it, see module docstring).
    p32 = p_ref[...].astype(jnp.int32)
    lo = ((p32 << 28) >> 28).astype(compute_dtype)
    hi = (p32 >> 4).astype(compute_dtype)
    acc_ref[:] += jax.lax.dot_general(
        xe_ref[...], lo, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + jax.lax.dot_general(
        xo_ref[...], hi, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[:] = (acc_ref[:] * s_ref[...]).astype(o_ref.dtype)


def _out_dtype(x: jnp.ndarray, preferred_element_type):
    return (
        preferred_element_type
        if preferred_element_type is not None
        else x.dtype
    )


def _pad_rows(x2: jnp.ndarray, bm: int) -> tuple[jnp.ndarray, int]:
    M = x2.shape[0]
    Mp = -(-M // bm) * bm
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))
    return x2, Mp


@functools.partial(
    jax.jit, static_argnames=("preferred_element_type", "interpret")
)
def matmul_int8(
    x: jnp.ndarray,  # [..., K] activations
    q: jnp.ndarray,  # [K, N] int8
    scale: jnp.ndarray,  # [1, N] f32
    preferred_element_type=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """``x @ (q * scale)`` with the int8 weight streamed packed and
    dequantized in-kernel. Returns [..., N]."""
    K, N = q.shape
    lead = x.shape[:-1]
    x2 = x.reshape(-1, K)
    M = x2.shape[0]
    bm, bk, bn = _plan_blocks(M, K, N, x2.dtype.itemsize, 1)
    x2, Mp = _pad_rows(x2, bm)
    out = pl.pallas_call(
        functools.partial(_qmm_int8_kernel, compute_dtype=x.dtype),
        grid=(Mp // bm, N // bn, K // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct(
            (Mp, N), _out_dtype(x, preferred_element_type)
        ),
        interpret=interpret,
    )(x2, q, scale.reshape(1, N).astype(jnp.float32))
    return out[:M].reshape(lead + (N,))


@functools.partial(
    jax.jit, static_argnames=("preferred_element_type", "interpret")
)
def matmul_int4(
    x: jnp.ndarray,  # [..., K] activations (K = true contraction width)
    q4: jnp.ndarray,  # [ceil(K/2), N] int8 nibble-packed
    scale: jnp.ndarray,  # [1, N] f32
    preferred_element_type=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """``x @ dequant(q4)`` with the nibble-packed weight streamed as-is
    and unpacked in-kernel by pure shifts. Returns [..., N]."""
    K2, N = q4.shape
    K = x.shape[-1]
    lead = x.shape[:-1]
    x2 = x.reshape(-1, K)
    if K != 2 * K2:
        # Odd true width: pack_int4 padded one zero row; the matching
        # zero activation column keeps the halves aligned.
        x2 = jnp.pad(x2, ((0, 0), (0, 2 * K2 - K)))
    xe = x2[:, 0::2]  # rows 2k of the unpacked weight
    xo = x2[:, 1::2]  # rows 2k+1
    M = x2.shape[0]
    bm, bk, bn = _plan_blocks(M, K2, N, 2 * x2.dtype.itemsize, 1)
    xe, Mp = _pad_rows(xe, bm)
    xo, _ = _pad_rows(xo, bm)
    half_spec = pl.BlockSpec((bm, bk), lambda i, j, k: (i, k))
    out = pl.pallas_call(
        functools.partial(_qmm_int4_kernel, compute_dtype=x.dtype),
        grid=(Mp // bm, N // bn, K2 // bk),
        in_specs=[
            half_spec,
            half_spec,
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct(
            (Mp, N), _out_dtype(x, preferred_element_type)
        ),
        interpret=interpret,
    )(xe, xo, q4, scale.reshape(1, N).astype(jnp.float32))
    return out[:M].reshape(lead + (N,))


def fused_supported(x, w) -> bool:
    """True iff the fused kernel covers this (activation, weight) pair:
    a flat (non-layer-stacked) quantized weight whose dims admit an
    unpadded block assignment. The caller (ops.quant.matmul) falls back
    to the XLA dequant-fusion path otherwise — same math, weaker
    streaming guarantee."""
    from adversarial_spec_tpu.ops.quant import is_quantized, is_quantized_int4

    if is_quantized(w):
        q = w["q"]
    elif is_quantized_int4(w):
        q = w["q4"]
    else:
        return False
    if q.ndim != 2 or x.ndim < 1 or x.size == 0:
        return False
    M = 1
    for d in x.shape[:-1]:
        M *= d
    return (
        _plan_blocks(M, q.shape[0], q.shape[1], x.dtype.itemsize, 1)
        is not None
    )


def quant_matmul(
    x: jnp.ndarray,
    w: dict,
    preferred_element_type=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Format dispatch for a quantized dict leaf (caller has already
    checked ``fused_supported``)."""
    from adversarial_spec_tpu.ops.quant import is_quantized_int4

    if is_quantized_int4(w):
        return matmul_int4(
            x,
            w["q4"],
            w["scale"],
            preferred_element_type=preferred_element_type,
            interpret=interpret,
        )
    return matmul_int8(
        x,
        w["q"],
        w["scale"],
        preferred_element_type=preferred_element_type,
        interpret=interpret,
    )
