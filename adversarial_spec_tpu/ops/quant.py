"""Weight-only int8 quantization.

Decode throughput on TPU is HBM-bandwidth-bound: every generated token
re-reads all matmul weights. Storing those weights int8 (per-output-channel
symmetric scales) halves the bytes read per token vs bf16 — the dequant
multiply fuses into the matmul's operand read under XLA, so the MXU still
computes in bf16/f32.

Representation: a quantized matmul weight is a dict leaf
``{"q": int8 [..., in, out], "scale": f32 [..., 1, out]}`` — dict (not a
custom pytree node) so the sharding rules, loaders, and tree utilities need
no new node types; the transformer's ``matmul`` helper dispatches on it.

Only matmul weights quantize (wq/wk/wv/wo/w_gate/w_up/w_down, lm_head,
and the tied-embedding transposed head copy lm_head_t); embeddings and
norms stay full precision (gather tables and scale vectors are
bandwidth-trivial and precision-sensitive).
"""

from __future__ import annotations

import jax.numpy as jnp

QUANTIZABLE = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head", "lm_head_t"}
)


def quantize_int8(w: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Symmetric per-output-channel int8 over the contraction (-2) axis."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    return {"q": q, "scale": scale.astype(jnp.float32)}


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q", "scale"}


def matmul(x: jnp.ndarray, w, preferred_element_type=None) -> jnp.ndarray:
    """x @ w for plain or int8-quantized weights (dequant fused by XLA)."""
    if is_quantized(w):
        y = jnp.matmul(
            x,
            w["q"].astype(x.dtype),
            preferred_element_type=preferred_element_type,
        )
        scale = w["scale"][..., 0, :]
        return y * (
            scale if preferred_element_type is not None else scale.astype(x.dtype)
        )
    return jnp.matmul(x, w, preferred_element_type=preferred_element_type)


def quantize_params(params: dict, names=QUANTIZABLE) -> dict:
    """Quantize matmul weights in a (possibly nested) param pytree.

    Works on the layer-stacked layout: per-layer scales fall out of the
    keepdims amax over the contraction axis.
    """

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if k in names and not is_quantized(v):
                out[k] = quantize_int8(v)
            elif isinstance(v, dict):
                out[k] = walk(v)
            else:
                out[k] = v
        return out

    return walk(params)
