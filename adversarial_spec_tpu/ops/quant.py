"""Weight-only quantization: int8 and packed int4.

Decode throughput on TPU is HBM-bandwidth-bound: every generated token
re-reads all matmul weights. Storing those weights int8 (per-output-channel
symmetric scales) halves the bytes read per token vs bf16 — the dequant
multiply fuses into the matmul's operand read under XLA, so the MXU still
computes in bf16/f32. int4 halves it again (two weights per byte, packed
along the contraction axis) — the format that makes a multi-model
opponent POOL resident on one chip (engine/weightres.py): four int4
checkpoints weigh what one bf16 checkpoint does.

Representation: a quantized matmul weight is a dict leaf — int8
``{"q": int8 [..., in, out], "scale": f32 [..., 1, out]}``, int4
``{"q4": int8 [..., ceil(in/2), out], "scale": f32 [..., 1, out]}``
(each ``q4`` byte packs rows ``2k`` in its low nibble and ``2k+1`` in
its high nibble; an odd contraction axis pads one zero row, sliced back
off at dequant against the activation's true width). Dicts (not custom
pytree nodes) so the sharding rules, loaders, and tree utilities need
no new node types; the transformer's ``matmul`` helper dispatches on
the key set. The unpack is pure shift arithmetic
(sign-extend-low-nibble / arithmetic-shift-high-nibble), so it traces
into the jitted forwards and XLA fuses the dequant into the operand
read — the in-kernel dequant the parity tests pin against dense fp.

Only matmul weights quantize (wq/wk/wv/wo/w_gate/w_up/w_down, lm_head,
and the tied-embedding transposed head copy lm_head_t); embeddings and
norms stay full precision (gather tables and scale vectors are
bandwidth-trivial and precision-sensitive).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

QUANTIZABLE = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head", "lm_head_t"}
)

# The registry's ``quant`` vocabulary lives jax-free in
# engine/registry.py (QUANT_FORMATS); this module implements the
# non-empty formats.


def quantize_int8(w: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Symmetric per-output-channel int8 over the contraction (-2) axis."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    return {"q": q, "scale": scale.astype(jnp.float32)}


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """Pack int8 values in [-8, 7] two-per-byte along the contraction
    (-2) axis: row ``2k`` in the low nibble, ``2k+1`` in the high. An
    odd row count pads one zero row (``unpack_int4`` slices it back off
    against the caller's true width)."""
    rows = q.shape[-2]
    if rows % 2:
        pad = [(0, 0)] * q.ndim
        pad[-2] = (0, 1)
        q = jnp.pad(q, pad)
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    # Two's-complement nibble packing: the low nibble keeps lo's bits,
    # hi shifts into the high nibble ([-8, 7] << 4 stays within int8).
    return (lo & jnp.int8(0x0F)) | jnp.left_shift(hi, 4).astype(jnp.int8)


def unpack_int4(packed: jnp.ndarray, rows: int) -> jnp.ndarray:
    """Inverse of :func:`pack_int4`: int8 values back out of the
    nibbles (``rows`` = the true contraction width; a padded zero row
    is sliced off). Pure shift arithmetic — traces into jitted
    forwards, so the dequant fuses into the matmul's operand read."""
    # Sign-extend the low nibble (shift up, arithmetic shift back);
    # the high nibble sign-extends by arithmetic right shift alone.
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    hi = jnp.right_shift(packed, 4)
    q = jnp.stack([lo, hi], axis=-2)  # [..., R/2, 2, out]
    q = q.reshape(q.shape[:-3] + (q.shape[-3] * 2, q.shape[-1]))
    return q[..., :rows, :]


def quantize_int4(w: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Symmetric per-output-channel packed int4 over the contraction
    (-2) axis (range [-7, 7]: symmetric, so dequant is one multiply)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale), -7, 7
    ).astype(jnp.int8)
    return {"q4": pack_int4(q), "scale": scale.astype(jnp.float32)}


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q", "scale"}


def is_quantized_int4(leaf) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q4", "scale"}


def dequantize(leaf, dtype=jnp.float32, rows: int | None = None) -> jnp.ndarray:
    """Materialize a quantized dict leaf back to a dense array (tests,
    oracles — the serving path never calls this; its dequant fuses
    inside :func:`matmul`).

    ``rows`` is the true contraction width for int4 leaves (the packed
    form cannot record it: an odd width padded one zero row at pack
    time). Without it an odd-width int4 leaf dequantizes to the padded
    shape — pass the original weight's ``shape[-2]`` to slice exactly.
    """
    if is_quantized(leaf):
        return leaf["q"].astype(dtype) * leaf["scale"].astype(dtype)
    if is_quantized_int4(leaf):
        if rows is None:
            rows = leaf["q4"].shape[-2] * 2
        scale = leaf["scale"].astype(dtype)
        return unpack_int4(leaf["q4"], rows).astype(dtype) * scale
    return jnp.asarray(leaf, dtype)


def matmul(
    x: jnp.ndarray,
    w,
    preferred_element_type=None,
    *,
    use_pallas: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """x @ w for plain, int8-, or int4-quantized weights.

    Default path: XLA's dequant fusion — the unpack/scale multiply is
    elementwise on the matmul operand, so XLA *usually* folds it into
    the operand read. ``use_pallas=True`` routes supported quantized
    shapes through the fused Pallas kernels (ops/pallas_quant.py), which
    make the stream-packed-once contract explicit instead of relying on
    the fusion heuristic; unsupported shapes (layer-stacked weights,
    dims with no unpadded block assignment) silently keep the XLA path —
    same math either way (docs/kernels.md pins the parity).
    ``interpret=True`` runs those kernels in Pallas interpret mode (the
    CPU-parity harness; flag-gated exactly like ``use_pallas_decode``).
    """
    if use_pallas and (is_quantized(w) or is_quantized_int4(w)):
        from adversarial_spec_tpu.ops import pallas_quant

        if pallas_quant.fused_supported(x, w):
            return pallas_quant.quant_matmul(
                x,
                w,
                preferred_element_type=preferred_element_type,
                interpret=interpret,
            )
    if is_quantized_int4(w):
        q = unpack_int4(w["q4"], x.shape[-1])
        y = jnp.matmul(
            x,
            q.astype(x.dtype),
            preferred_element_type=preferred_element_type,
        )
        scale = w["scale"][..., 0, :]
        return y * (
            scale if preferred_element_type is not None else scale.astype(x.dtype)
        )
    if is_quantized(w):
        y = jnp.matmul(
            x,
            w["q"].astype(x.dtype),
            preferred_element_type=preferred_element_type,
        )
        scale = w["scale"][..., 0, :]
        return y * (
            scale if preferred_element_type is not None else scale.astype(x.dtype)
        )
    return jnp.matmul(x, w, preferred_element_type=preferred_element_type)


def has_quantized_weights(params) -> bool:
    """True iff any leaf of the param pytree is a quantized dict —
    the auto-enable predicate for the fused Pallas matmul path (a
    full-precision checkpoint has nothing to dequantize)."""
    leaves = jax.tree.leaves(
        params,
        is_leaf=lambda n: is_quantized(n) or is_quantized_int4(n),
    )
    return any(
        is_quantized(leaf) or is_quantized_int4(leaf) for leaf in leaves
    )


def quantize_params(params: dict, names=QUANTIZABLE, fmt: str = "int8") -> dict:
    """Quantize matmul weights in a (possibly nested) param pytree.

    ``fmt`` selects the storage format (``"int8"`` or ``"int4"``).
    Works on the layer-stacked layout: per-layer scales fall out of the
    keepdims amax over the contraction axis.
    """
    if fmt not in ("int8", "int4"):
        raise ValueError(
            f"unknown weight quantization format {fmt!r}; known: int8, int4"
        )
    one = quantize_int8 if fmt == "int8" else quantize_int4

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if (
                k in names
                and not is_quantized(v)
                and not is_quantized_int4(v)
            ):
                out[k] = one(v)
            elif isinstance(v, dict):
                out[k] = walk(v)
            else:
                out[k] = v
        return out

    return walk(params)
