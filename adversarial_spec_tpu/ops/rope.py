"""Rotary position embeddings.

Half-rotation (NeoX/Llama) layout: features are split into two halves that
rotate together — the layout HF Llama/Mistral/Gemma/Qwen checkpoints use, so
loaded weights need no permutation.
"""

from __future__ import annotations

import jax.numpy as jnp


def _llama3_scale(freqs: jnp.ndarray, scaling) -> jnp.ndarray:
    """Llama-3.1/3.2 frequency-dependent NTK scaling.

    Long-wavelength (low-frequency) components are stretched by ``factor``;
    short wavelengths are kept; the band between ``low_freq_factor`` and
    ``high_freq_factor`` (in units of original_max/wavelength) interpolates
    smoothly. Matches HF ``rope_type="llama3"``.
    """
    factor, low, high, original_max = scaling
    wavelen = 2.0 * jnp.pi / freqs
    ratio = original_max / wavelen
    smooth = jnp.clip((ratio - low) / (high - low), 0.0, 1.0)
    return jnp.where(
        ratio < low,
        freqs / factor,
        (1.0 - smooth) * freqs / factor + smooth * freqs,
    )


def rope_angles(
    positions: jnp.ndarray,
    head_dim: int,
    theta: float,
    scaling: tuple[float, float, float, float] | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for integer positions.

    positions: [...]; returns cos/sin of shape [..., head_dim//2], f32.
    ``scaling``: optional llama-3 rope scaling as (factor, low_freq_factor,
    high_freq_factor, original_max_seq_len); None = unscaled.
    """
    half = head_dim // 2
    freqs = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    if scaling is not None:
        freqs = _llama3_scale(freqs, scaling)
    ang = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotate feature pairs (x1, x2) = (x[..:half], x[half:..]).

    x: [B, S, H, D]; cos/sin: [B, S, D//2] (broadcast over heads).
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[..., None, :]  # [B, S, 1, D/2]
    s = sin[..., None, :]
    rot1 = x1 * c - x2 * s
    rot2 = x2 * c + x1 * s
    return jnp.concatenate([rot1, rot2], axis=-1).astype(x.dtype)
