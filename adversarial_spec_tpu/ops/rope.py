"""Rotary position embeddings.

Half-rotation (NeoX/Llama) layout: features are split into two halves that
rotate together — the layout HF Llama/Mistral/Gemma/Qwen checkpoints use, so
loaded weights need no permutation.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_angles(
    positions: jnp.ndarray, head_dim: int, theta: float
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for integer positions.

    positions: [...]; returns cos/sin of shape [..., head_dim//2], f32.
    """
    half = head_dim // 2
    freqs = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    ang = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotate feature pairs (x1, x2) = (x[..:half], x[half:..]).

    x: [B, S, H, D]; cos/sin: [B, S, D//2] (broadcast over heads).
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[..., None, :]  # [B, S, 1, D/2]
    s = sin[..., None, :]
    rot1 = x1 * c - x2 * s
    rot2 = x2 * c + x1 * s
    return jnp.concatenate([rot1, rot2], axis=-1).astype(x.dtype)
