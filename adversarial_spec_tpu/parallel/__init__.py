"""parallel subpackage."""
