"""Parallelism: mesh construction, sharding rules, collectives, ring attention.

TPU-native replacement for the reference's "distributed backend" — which is
HTTPS fan-out to remote APIs (SURVEY §2.3: no NCCL/MPI/Gloo, nothing to wrap).
Here the backend is XLA collectives over ICI driven by sharding annotations:
pick a mesh, annotate params/activations, let GSPMD insert all-gathers/
reduce-scatters/ppermutes (the scaling-book recipe).
"""

from adversarial_spec_tpu.parallel.mesh import (
    MeshAxes,
    make_mesh,
    mesh_shape_from_spec,
)
from adversarial_spec_tpu.parallel.sharding import (
    param_sharding_rules,
    shard_params,
    cache_sharding,
)

__all__ = [
    "MeshAxes",
    "make_mesh",
    "mesh_shape_from_spec",
    "param_sharding_rules",
    "shard_params",
    "cache_sharding",
]
