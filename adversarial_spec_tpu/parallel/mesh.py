"""Device mesh construction.

Axis convention (used by every sharding rule in the framework):

- ``dp`` — data/batch parallel: opponents of a debate round are rows of one
  batch; dp splits rows across mesh slices (the TPU-native replacement for
  the reference's thread-per-opponent fan-out, SURVEY §2.3).
- ``tp`` — tensor parallel: attention heads / FFN columns (Megatron-style,
  collectives inserted by GSPMD over ICI).
- ``sp`` — sequence/context parallel: long-context ring attention
  (parallel/ring.py) shards the sequence axis across ICI neighbors.

Multi-host: ``jax.distributed.initialize`` is invoked when the runtime env
indicates a multi-process job; ``jax.devices()`` then spans all hosts and
the same mesh code covers v5e-1 through multi-host v5p pods (DCN between
slices is handled by XLA's collective lowering, not by this code).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

DP, TP, SP = "dp", "tp", "sp"
MeshAxes = (DP, TP, SP)


def compat_shard_map(f, *, mesh, in_specs, out_specs, check=False):
    """``shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map`` (replication check spelled
    ``check_vma``); older ones only have the experimental module
    (``check_rep``). Callers that can't assume a pinned jax go through
    this shim instead of picking one spelling.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )


def maybe_initialize_distributed() -> None:
    """Bring up the multi-host runtime when launched as one process per
    host. Safe no-op otherwise.

    Launch contract (one process per host):

        JAX_COORDINATOR_ADDRESS=host0:1234   # process 0's address
        JAX_NUM_PROCESSES=N
        JAX_PROCESS_ID=i                     # 0..N-1, unique per process

    ``jax.distributed.initialize()`` only auto-detects managed clusters
    (SLURM, Cloud TPU metadata); for the generic env-var launch above it
    requires explicit arguments, so this passes them through. Exercised
    for real by the two-process CPU smoke test
    (tests/test_multihost.py), so the v5p-16 multi-host config is not
    first debugged on scarce hardware.

    The idempotence check must NOT touch the backend (jax.process_count /
    jax.devices would initialize XLA and make distributed.initialize
    illegal), so it inspects the distributed client state directly.
    """
    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not coordinator:
        if os.environ.get("JAX_NUM_PROCESSES") or os.environ.get(
            "JAX_PROCESS_ID"
        ):
            # Half a launch contract: this host would silently run
            # single-process while its peers block at the coordinator
            # barrier forever. Fail fast with the cause.
            raise RuntimeError(
                "multi-host launch: JAX_NUM_PROCESSES/JAX_PROCESS_ID are "
                "set but JAX_COORDINATOR_ADDRESS is not; set all three"
            )
        return
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        already = is_init()
    else:  # older jax: peek at the global client
        from jax._src import distributed as _dist

        already = _dist.global_state.client is not None
    if already:
        return
    num = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    if (num is None) != (pid is None):
        # Fail fast with the actual cause — falling through to cluster
        # auto-detect would hang the other hosts at the coordinator
        # barrier or die with an opaque error.
        missing = "JAX_PROCESS_ID" if pid is None else "JAX_NUM_PROCESSES"
        raise RuntimeError(
            f"multi-host launch: JAX_COORDINATOR_ADDRESS is set but "
            f"{missing} is not; set both JAX_NUM_PROCESSES and "
            f"JAX_PROCESS_ID (or neither, for managed clusters)"
        )
    if num is not None:
        try:
            num_i, pid_i = int(num), int(pid)
        except ValueError:
            raise RuntimeError(
                f"multi-host launch: JAX_NUM_PROCESSES={num!r} / "
                f"JAX_PROCESS_ID={pid!r} must be integers"
            ) from None
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_i,
            process_id=pid_i,
        )
    else:
        # Managed-cluster path: let jax's cluster plugins fill the rest.
        jax.distributed.initialize(coordinator_address=coordinator)


def mesh_shape_from_spec(
    mesh_spec: dict[str, int] | None, n_devices: int | None = None
) -> dict[str, int]:
    """Normalize a registry mesh spec {axis: size} to a full {dp,tp,sp}.

    Unspecified axes default to 1; leftover devices go to dp so a spec like
    {"tp": 2} on 8 devices yields dp=4, tp=2, sp=1. A spec that pins dp
    EXPLICITLY may describe a SUBMESH (dp·tp·sp < device count): the mesh
    is built on the LEADING devices, so a small model can run on one chip
    of a slice. (Placing several submesh entries on DISJOINT chips is not
    implemented — every submesh starts at device 0; pass ``devices`` to
    make_mesh for manual placement.)
    """
    n = n_devices if n_devices is not None else len(jax.devices())
    spec = dict(mesh_spec or {})
    unknown = set(spec) - set(MeshAxes)
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)}; use {MeshAxes}")
    tp = int(spec.get(TP, 1))
    sp = int(spec.get(SP, 1))
    if DP not in spec and n % (tp * sp) != 0:
        raise ValueError(
            f"mesh tp={tp} sp={sp} does not divide device count {n}"
        )
    dp = int(spec.get(DP, n // (tp * sp)))
    total = dp * tp * sp
    if total > n or (DP not in spec and total != n):
        raise ValueError(
            f"mesh dp*tp*sp = {total} != device count {n}"
        )
    return {DP: dp, TP: tp, SP: sp}


def make_mesh(
    mesh_spec: dict[str, int] | None = None,
    devices: list | None = None,
) -> Mesh:
    """Create the {dp, tp, sp} mesh over the available devices.

    TP is placed on the fastest-varying axis of the device array so
    tensor-parallel collectives ride adjacent ICI links.
    """
    devs = devices if devices is not None else jax.devices()
    shape = mesh_shape_from_spec(mesh_spec, n_devices=len(devs))
    total = shape[DP] * shape[SP] * shape[TP]
    arr = np.asarray(devs[:total]).reshape(shape[DP], shape[SP], shape[TP])
    return Mesh(arr, (DP, SP, TP))
