"""Ring attention: causal attention with the sequence axis sharded over ICI.

Long-context subsystem (SURVEY §5 "long-context — ABSENT in the reference,
required new subsystem here"): when a 16k+-token spec exceeds what one
chip's HBM comfortably holds for prefill, the sequence axis is sharded over
the ``sp`` mesh axis and attention runs as a ring: each device computes
attention of its local query block against the K/V block it currently
holds, accumulates online-softmax statistics (running max / normalizer /
weighted values — the flash-attention recurrence), and passes its K/V block
to its ring neighbor with ``ppermute``. After ``sp`` hops every query block
has seen every key block, with peak memory O(S/sp) and the K/V transfers
riding neighbor ICI links.

Causality is enforced at two granularities: whole blocks are skipped when
the key block is entirely in the future (compute still runs — SPMD needs
identical programs — but is masked), and the diagonal block applies the
in-block triangular mask. Per-row ``kv_start`` bounds additionally mask
left-pad slots, so the same code serves padded batches.

``ring_attention_local`` is the per-device body, reused by the
sequence-parallel model prefill (parallel/sp.py) which runs its own
shard_map; ``ring_attention`` wraps it for standalone global-array use.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from adversarial_spec_tpu.parallel.mesh import SP, compat_shard_map


def _block_attend(
    q: jnp.ndarray,  # [B, Sq, H, D] f32
    k: jnp.ndarray,  # [B, Sk, Hkv, D]
    v: jnp.ndarray,  # [B, Sk, Hkv, D]
    mask: jnp.ndarray,  # [B, Sq, Sk] bool — True = attend
    m: jnp.ndarray,  # [B, H, Sq] running max
    l: jnp.ndarray,  # [B, H, Sq] running normalizer
    acc: jnp.ndarray,  # [B, Sq, H, D] running weighted values
    scale: float,
    attn_softcap: float = 0.0,
):
    """One flash-attention accumulation step over a K/V block."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    g = H // Hkv
    qg = q.reshape(B, Sq, Hkv, g, D)
    s = jnp.einsum(
        "bshgd,bthd->bhgst", qg, k, preferred_element_type=jnp.float32
    ) * scale  # [B, Hkv, g, Sq, Sk]
    s = s.reshape(B, H, Sq, k.shape[1])
    if attn_softcap > 0.0:
        s = jnp.tanh(s / attn_softcap) * attn_softcap
    # -inf (not finfo.min): a fully-masked row must yield EXACT zeros —
    # finfo.min would make it a uniform average over however many keys
    # this run happened to process (hop-count-dependent garbage). The
    # m/alpha guards below keep -inf NaN-free; same contract as the
    # Pallas kernels (ops/flash_common.py) and attention().
    s = jnp.where(mask[:, None, :, :], s, -jnp.inf)

    m_new = jnp.maximum(m, s.max(axis=-1))
    # Guard fully-masked rows: keep m finite so exp() stays 0, not NaN.
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    p = jnp.exp(s - m_safe[..., None])  # [B, H, Sq, Sk]
    l_new = l * alpha + p.sum(axis=-1)
    pg = p.reshape(B, Hkv, g, Sq, -1)
    delta = jnp.einsum("bhgst,bthd->bshgd", pg, v.astype(jnp.float32))
    delta = delta.reshape(B, Sq, H, D)
    acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + delta
    return m_new, l_new, acc_new


def ring_hops(sp: int, block: int, window, causal: bool):
    """Number of ring hops that can possibly contribute.

    Causal + sliding window W: hop h hands device idx the K block from
    src = idx - h (mod sp); non-wrapped blocks sit h·block slots behind
    the query block, and every (query, key) pair in hop h is outside the
    window once (h-1)·block + 1 >= W — the SAME bound on every device, so
    the trip count shrinks uniformly and ppermutes stay matched. Wrapped
    blocks are entirely in the future and already masked. Returns a
    Python int when ``window`` is static (fori_loop keeps a static trip
    count), a traced scalar when it is traced (gemma2's per-layer
    alternation inside scan — lowers to a uniform while_loop).
    """
    if not causal:
        return sp
    if isinstance(window, int):
        if window <= 0:
            return sp
        return min(sp, (window + block - 2) // block + 1)
    return jnp.where(
        window > 0,
        jnp.minimum(sp, (window + block - 2) // block + 1),
        sp,
    )


def ring_attention_local(
    qb: jnp.ndarray,  # [B, S_loc, H, D] — this device's query block
    kb: jnp.ndarray,  # [B, S_loc, Hkv, D] — this device's K block
    vb: jnp.ndarray,
    sp: int,
    causal: bool = True,
    kv_start: jnp.ndarray | None = None,  # [B] first valid global slot
    attn_softcap: float = 0.0,
    scale: float | None = None,
    window: jnp.ndarray | int = 0,  # sliding window in slots; 0 = global
    axis_name: str = SP,
) -> jnp.ndarray:
    """Per-device ring attention body (call inside shard_map over sp).

    ``window`` may be a traced scalar (per-layer alternation inside a
    scan): key slots below q_slot - window + 1 are masked. Sliding-window
    layers EARLY-OUT of the ring after ``ring_hops`` hops — the remaining
    blocks are fully outside every query's window on every device, so the
    trip count shrinks uniformly (SPMD-safe) instead of masking sp-1 hops
    of dead compute at 16k contexts.
    """
    idx = jax.lax.axis_index(axis_name)
    B, Sq, H, D = qb.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    m = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Sq), jnp.float32)
    acc = jnp.zeros((B, Sq, H, D), jnp.float32)
    rows = jnp.arange(Sq)[:, None]
    cols = jnp.arange(Sq)[None, :]

    def step(h, carry):
        m, l, acc, kb, vb = carry
        # After h hops, we hold the block originally on device idx-h.
        src = (idx - h) % sp
        if causal:
            diag = rows >= cols
            full = jnp.ones((Sq, Sq), bool)
            empty = jnp.zeros((Sq, Sq), bool)
            block_mask = jnp.where(
                src == idx, diag, jnp.where(src < idx, full, empty)
            )
        else:
            block_mask = jnp.ones((Sq, Sq), bool)
        mask = jnp.broadcast_to(block_mask[None], (B, Sq, Sq))
        key_slot = src * Sq + cols  # [Sq(q), Sq(k)]-broadcastable key slots
        if kv_start is not None:
            mask = mask & (key_slot[None] >= kv_start[:, None, None])
        # Sliding window (traced-scalar friendly): q at global slot
        # idx*Sq+row sees keys in (q_slot - window, q_slot].
        q_slot = idx * Sq + rows
        win_mask = (window <= 0) | (key_slot > q_slot - window)
        mask = mask & win_mask[None]
        m, l, acc = _block_attend(
            qb.astype(jnp.float32),
            kb,
            vb,
            mask,
            m,
            l,
            acc,
            scale,
            attn_softcap=attn_softcap,
        )
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return m, l, acc, kb, vb

    hops = ring_hops(sp, Sq, window, causal)
    m, l, acc, _, _ = jax.lax.fori_loop(0, hops, step, (m, l, acc, kb, vb))
    l_safe = jnp.maximum(l, 1e-30)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(qb.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, S, H, D] — S is the GLOBAL sequence length
    k: jnp.ndarray,  # [B, S, Hkv, D]
    v: jnp.ndarray,  # [B, S, Hkv, D]
    mesh: Mesh,
    causal: bool = True,
    kv_start: jnp.ndarray | None = None,  # [B]
    attn_softcap: float = 0.0,
) -> jnp.ndarray:
    """Causal attention with sequence sharded over the mesh's ``sp`` axis.

    Inputs/outputs are global arrays; shard_map splits them into per-device
    sequence blocks and the ring runs ``sp`` ppermute hops.
    """
    sp = mesh.shape[SP]
    S = q.shape[1]
    if S % sp != 0:
        raise ValueError(f"sequence {S} not divisible by sp={sp}")

    spec = P(None, SP, None, None)
    if kv_start is None:

        def local(qb, kb, vb):
            return ring_attention_local(
                qb, kb, vb, sp, causal=causal, attn_softcap=attn_softcap
            )

        in_specs = (spec, spec, spec)
        args = (q, k, v)
    else:

        def local(qb, kb, vb, ks):
            return ring_attention_local(
                qb,
                kb,
                vb,
                sp,
                causal=causal,
                kv_start=ks,
                attn_softcap=attn_softcap,
            )

        in_specs = (spec, spec, spec, P(None))
        args = (q, k, v, kv_start)

    return compat_shard_map(
        local,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec,
    )(*args)
