"""Parameter and cache sharding rules (Megatron-style TP via GSPMD).

The model code (models/transformer.py) is mesh-oblivious; parallelism is
expressed entirely by placing params/cache with NamedShardings and letting
GSPMD propagate through the jitted forward:

- ``wq/wk/wv`` and ``w_gate/w_up`` are column-sharded over ``tp`` (each
  device owns a slice of heads / FFN columns);
- ``wo`` and ``w_down`` are row-sharded over ``tp`` — GSPMD inserts the
  all-reduce (psum over ICI) after their matmuls;
- the KV cache shards its head axis over ``tp`` and batch over ``dp``;
- embeddings/norms are replicated; ``lm_head`` is column-sharded so the
  final logits are vocab-sharded until sampling.

This is the "NCCL-equivalent" seam of the framework (SURVEY §2.3): the
collectives exist only as XLA lowerings of these annotations.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from adversarial_spec_tpu.parallel.mesh import DP, TP

# Pytree path suffix → PartitionSpec. Layer-stacked params carry a leading
# n_layers dim (never sharded).
_PARAM_RULES: dict[str, P] = {
    "embed": P(),
    "final_norm": P(),
    "lm_head": P(None, TP),
    "lm_head_t": P(None, TP),
    "attn_norm": P(None, None),
    "ffn_norm": P(None, None),
    "post_attn_norm": P(None, None),
    "post_ffn_norm": P(None, None),
    "wq": P(None, None, TP),
    "wk": P(None, None, TP),
    "wv": P(None, None, TP),
    "bq": P(None, TP),
    "bk": P(None, TP),
    "bv": P(None, TP),
    "wo": P(None, TP, None),
    "w_gate": P(None, None, TP),
    "w_up": P(None, None, TP),
    "w_down": P(None, TP, None),
}


def _dict_names(path) -> list[str]:
    return [
        str(e.key) for e in path if isinstance(e, jax.tree_util.DictKey)
    ]


def param_sharding_rules(path) -> P:
    names = _dict_names(path)
    if not names:
        raise ValueError(f"cannot name pytree path {path}")
    name = names[-1]
    # Quantized weights are dict leaves under the weight's name
    # (ops/quant.py): int8 {"q", "scale"}, int4 {"q4", "scale"}. "q"
    # and "q4" shard like the weight (int4 packing halves the
    # contraction axis — the axis ASSIGNMENT is unchanged); "scale"
    # ([..., 1, out]) keeps only the output-axis sharding — its kept
    # contraction axis has size 1 and must stay unsharded.
    if name in ("q", "q4", "scale") and len(names) >= 2:
        parent = _PARAM_RULES.get(names[-2])
        if parent is not None:
            if name in ("q", "q4"):
                return parent
            spec = list(parent)
            spec[-2] = None
            return P(*spec)
    if name not in _PARAM_RULES:
        raise KeyError(f"no sharding rule for param {name!r}")
    return _PARAM_RULES[name]


def param_shardings(mesh: Mesh, params) -> dict:
    """NamedSharding pytree matching ``params``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(mesh, param_sharding_rules(path)),
        params,
    )


def shard_params(mesh: Mesh, params):
    """Place a host/any-device param pytree onto the mesh per the rules."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x: jax.device_put(
            x, NamedSharding(mesh, param_sharding_rules(path))
        ),
        params,
    )


def make_device_put(mesh: Mesh, dtype):
    """Loader hook: place each tensor as it is read (bounded host RAM).

    Host buffers go straight to their sharded placement — no intermediate
    copy on the default device.
    """
    import jax.numpy as jnp
    import ml_dtypes

    np_dtype = np.dtype(
        {jnp.bfloat16: ml_dtypes.bfloat16}.get(dtype, np.dtype(dtype))
    )

    def put(path_names: tuple, arr):
        # Callers pass either plain-string tuples (load_hf_checkpoint) or
        # jax tree paths of DictKey entries (materialize_params' random
        # branch) — normalize both, else every rule lookup misses and all
        # params land replicated (OOM at 70B/tp=8).
        name = getattr(path_names[-1], "key", path_names[-1])
        spec = _PARAM_RULES.get(name, P())
        if isinstance(arr, np.ndarray) and arr.dtype != np_dtype:
            arr = arr.astype(np_dtype)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return put


def cache_sharding(mesh: Mesh) -> NamedSharding:
    """KV cache [L, B, H_kv, S, D]: batch over dp, heads over tp."""
    return NamedSharding(mesh, P(None, DP, TP, None, None))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Token/batch arrays [B, ...]: rows over dp."""
    return NamedSharding(mesh, P(DP))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
