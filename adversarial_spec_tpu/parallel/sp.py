"""Sequence-parallel (long-context) prefill: the whole transformer forward
with the sequence axis sharded over ``sp`` — composable with tensor
parallelism over ``tp``.

BASELINE config 5 is a 16k-context PRD against a TP=8 70B judge; at that
shape prefill needs BOTH axes at once. Inside one shard_map over the full
mesh:

- the prompt is split into ``sp`` contiguous blocks (embeddings, QKV
  projections, FFNs run on local blocks; attention is a K/V ring over the
  sp axis — parallel/ring.py);
- weights enter tp-sharded per the Megatron rules (parallel/sharding.py):
  this is a manual-collective region, so the body works on a "shard view"
  of the config (heads/FFN columns divided by tp) and the row-parallel
  matmuls all-reduce explicitly (``psum_axis`` in the shared layer tail);
- last-position logits are vocab-sharded under tp (column-parallel
  lm_head) and all-gather only at the very end.

Activation and attention memory are O(S/sp) per device; K/V ring traffic
rides sp-neighbor ICI links and the TP all-reduces ride the tp axis.

The resulting KV cache comes back sequence-sharded (heads tp-sharded);
the caller reshards to the decode layout (batch over dp, heads over tp).

Sliding-window families are supported: each layer's window (including
gemma-2's alternating pattern) is applied as a mask inside the ring.
Constraints (v1): the padded length must divide sp;
n_heads/n_kv_heads/ffn_dim/vocab must divide tp.
"""

from __future__ import annotations

import math
from dataclasses import replace
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from adversarial_spec_tpu.models.config import ModelConfig
from adversarial_spec_tpu.models.transformer import (
    _attn_out_and_ffn,
    _lm_head_logits,
    _project_qkv,
    rms_norm,
)
from adversarial_spec_tpu.ops.rope import rope_angles
from adversarial_spec_tpu.parallel.mesh import SP, TP, compat_shard_map
from adversarial_spec_tpu.parallel.ring import ring_attention_local
from adversarial_spec_tpu.parallel.sharding import param_sharding_rules


def _param_in_specs(params):
    """Per-leaf PartitionSpecs for shard_map: the tp placements from the
    Megatron rules (sp/dp never appear on weights)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: param_sharding_rules(path), params
    )


@partial(jax.jit, static_argnames=("cfg", "mesh"))
def sp_prefill(
    params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, S] left-padded, S % sp == 0
    pad_lens: jnp.ndarray,  # [B]
    mesh: Mesh,
):
    """Sequence-parallel (× tensor-parallel) prefill over the full prompt.

    Returns (last_logits [B, vocab] f32, cache {"k","v": [L, B, Hkv, S, D]}
    sequence-sharded over sp and head-sharded over tp).

    Sliding-window families work too: the per-layer window (including
    gemma-2's alternating pattern) is applied as a mask inside the ring —
    every hop still runs (SPMD uniformity), distant blocks contribute
    zeros.
    """
    sp = mesh.shape[SP]
    tp = mesh.shape[TP]
    B, S = tokens.shape
    if S % sp != 0:
        raise ValueError(f"padded length {S} not divisible by sp={sp}")
    if tp > 1 and (
        cfg.n_heads % tp
        or cfg.n_kv_heads % tp
        or cfg.ffn_dim % tp
        or cfg.vocab_size % tp
    ):
        raise ValueError(
            f"tp={tp} must divide n_heads={cfg.n_heads}, "
            f"n_kv_heads={cfg.n_kv_heads}, ffn_dim={cfg.ffn_dim}, "
            f"vocab={cfg.vocab_size}"
        )

    # The body sees LOCAL shards: express the per-device shapes as a
    # shard-view config (full head_dim/dim; heads and FFN columns split).
    local_cfg = (
        replace(
            cfg,
            n_heads=cfg.n_heads // tp,
            n_kv_heads=cfg.n_kv_heads // tp,
            ffn_dim=cfg.ffn_dim // tp,
        )
        if tp > 1
        else cfg
    )
    psum_axis = TP if tp > 1 else None

    def local(tokens_l, pad_lens_rep, params_l):
        # tokens_l: [B, S/sp]; params_l: tp-local weight shards.
        idx = jax.lax.axis_index(SP)
        S_loc = tokens_l.shape[1]
        base = idx * S_loc
        positions = jnp.maximum(
            base + jnp.arange(S_loc, dtype=jnp.int32)[None, :]
            - pad_lens_rep[:, None],
            0,
        )
        cos, sin = rope_angles(
            positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
        )

        x = params_l["embed"][tokens_l]  # embed is tp-replicated
        if cfg.scale_embeddings:
            x = (x.astype(jnp.float32) * math.sqrt(cfg.dim)).astype(x.dtype)

        layer_ids = jnp.arange(cfg.n_layers)

        def layer_body(x, scanned):
            lp, layer_id = scanned
            h = rms_norm(
                x, lp["attn_norm"], cfg.rms_eps, cfg.norm_scale_plus_one
            )
            q, k, v = _project_qkv(lp, local_cfg, h, B, S_loc, cos, sin)
            if cfg.sliding_window > 0 and cfg.sliding_window_pattern > 1:
                # Gemma-2: alternate windowed / global layers.
                window = jnp.where(
                    layer_id % cfg.sliding_window_pattern == 0,
                    cfg.sliding_window,
                    0,
                )
            else:
                window = cfg.sliding_window
            out = ring_attention_local(
                q,
                k.astype(jnp.float32),
                v.astype(jnp.float32),
                sp,
                causal=True,
                kv_start=pad_lens_rep,
                attn_softcap=cfg.attn_softcap,
                scale=cfg.attn_scale,
                window=window,
            )
            x = _attn_out_and_ffn(
                x, out, lp, local_cfg, B, S_loc, psum_axis=psum_axis
            )
            return x, (k, v)

        x, (k_all, v_all) = jax.lax.scan(
            layer_body, x, (params_l["layers"], layer_ids)
        )
        # Scan stacks token-major [L, B, S_loc, H, D]; the cache contract
        # is heads-major [L, B, H, S_loc, D] (models/transformer.py).
        k_all = jnp.swapaxes(k_all, 2, 3)
        v_all = jnp.swapaxes(v_all, 2, 3)

        # Last-position logits: the shared lm-head tail (final norm +
        # tied/untied projection + softcap — one source of truth with the
        # dense path), computed on every sp block for SPMD uniformity,
        # zeroed except on the last block, psum'd over sp. Under tp the
        # lm_head is column-parallel; softcap is elementwise so it
        # commutes with the vocab all-gather.
        logits_local = _lm_head_logits(
            params_l, cfg, x, lm_head_last_only=True
        )[:, 0]
        if tp > 1 and not cfg.tied_embeddings:
            logits_local = jax.lax.all_gather(
                logits_local, TP, axis=1, tiled=True
            )
        logits_local = jnp.where(idx == sp - 1, logits_local, 0.0)
        logits = jax.lax.psum(logits_local, SP)
        return logits, k_all, v_all

    seq_spec = P(None, SP)
    cache_spec = P(None, None, TP, SP, None)  # [L, B, Hkv(tp), S(sp), D]
    logits, k_all, v_all = compat_shard_map(
        local,
        mesh=mesh,
        in_specs=(seq_spec, P(None), _param_in_specs(params)),
        out_specs=(P(None, None), cache_spec, cache_spec),
    )(tokens, pad_lens, params)
    return logits, {"k": k_all, "v": v_all}


def reshard_cache_for_decode(
    cache, mesh: Mesh, total_len: int, kv_dtype: str = ""
):
    """Sequence-sharded prefill cache → decode layout: gather the sequence
    axis, pad to ``total_len`` slots, shard batch over dp / heads over tp.

    ``kv_dtype="int8"``: quantize the gathered cache into the int8
    decode layout (models/transformer.py:init_cache). The ring attention
    itself ran on full-precision K/V — sp prefill quantizes at this
    boundary, where the dense path quantizes at each prefill write
    (prompt-token KV values are identical either way; prefill-attention
    reads differ in the int8 rounding, in sp's favor)."""
    from adversarial_spec_tpu.parallel.sharding import cache_sharding

    S = cache["k"].shape[3]
    out = {}
    for name, arr in cache.items():
        arr = jax.device_put(arr, cache_sharding(mesh))  # gathers sp
        if total_len > S:
            pad = [(0, 0)] * arr.ndim
            pad[3] = (0, total_len - S)
            arr = jnp.pad(arr, pad)
        out[name] = arr
    if kv_dtype == "int8":
        from adversarial_spec_tpu.models.transformer import _quantize_kv

        k8, ks = _quantize_kv(out["k"])
        v8, vs = _quantize_kv(out["v"])
        out = {"k": k8, "v": v8, "ks": ks, "vs": vs}
    return out
