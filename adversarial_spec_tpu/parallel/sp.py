"""Sequence-parallel (long-context) prefill: the whole transformer forward
with the sequence axis sharded over the ``sp`` mesh axis.

BASELINE config 5 is a 16k-context PRD; at that length a single chip's
prefill is attention-memory-bound. Here the prompt is split into ``sp``
contiguous blocks (one per device): embeddings, QKV projections, and FFNs
run on local blocks only, and attention runs as a ring
(parallel/ring.py::ring_attention_local — ppermute of K/V blocks around
the ICI ring with online-softmax accumulation). Activation and attention
memory are O(S/sp) per device; the only cross-device traffic is the K/V
ring (plus whatever collectives GSPMD inserts for tp-sharded weights).

The resulting KV cache comes back sequence-sharded; the caller reshards
it to the decode layout (batch over dp) — decode is token-at-a-time and
has no sequence axis worth sharding.

Constraints (v1): global attention only (no sliding window — Llama-style
families; windowed families raise), and the padded length must divide sp.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from adversarial_spec_tpu.models.config import ModelConfig
from adversarial_spec_tpu.models.transformer import (
    _attn_out_and_ffn,
    _lm_head_logits,
    _project_qkv,
    rms_norm,
)
from adversarial_spec_tpu.ops.rope import rope_angles
from adversarial_spec_tpu.parallel.mesh import SP
from adversarial_spec_tpu.parallel.ring import ring_attention_local


@partial(jax.jit, static_argnames=("cfg", "mesh"))
def sp_prefill(
    params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, S] left-padded, S % sp == 0
    pad_lens: jnp.ndarray,  # [B]
    mesh: Mesh,
):
    """Sequence-parallel prefill over the full prompt.

    Returns (last_logits [B, vocab] f32, cache {"k","v": [L, B, S, Hkv, D]}
    sequence-sharded over sp).
    """
    if cfg.sliding_window > 0:
        raise NotImplementedError(
            "sequence-parallel prefill supports global attention only; "
            f"family with sliding_window={cfg.sliding_window} must prefill "
            "chunked on one device"
        )
    sp = mesh.shape[SP]
    B, S = tokens.shape
    if S % sp != 0:
        raise ValueError(f"padded length {S} not divisible by sp={sp}")

    def local(tokens_l, pad_lens_rep, params_rep):
        # tokens_l: [B, S/sp] — this device's contiguous block.
        idx = jax.lax.axis_index(SP)
        S_loc = tokens_l.shape[1]
        base = idx * S_loc
        positions = jnp.maximum(
            base + jnp.arange(S_loc, dtype=jnp.int32)[None, :]
            - pad_lens_rep[:, None],
            0,
        )
        cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

        x = params_rep["embed"][tokens_l]
        if cfg.scale_embeddings:
            x = (x.astype(jnp.float32) * math.sqrt(cfg.dim)).astype(x.dtype)

        def layer_body(x, lp):
            h = rms_norm(
                x, lp["attn_norm"], cfg.rms_eps, cfg.norm_scale_plus_one
            )
            q, k, v = _project_qkv(lp, cfg, h, B, S_loc, cos, sin)
            out = ring_attention_local(
                q,
                k.astype(jnp.float32),
                v.astype(jnp.float32),
                sp,
                causal=True,
                kv_start=pad_lens_rep,
                attn_softcap=cfg.attn_softcap,
                scale=cfg.attn_scale,
            )
            x = _attn_out_and_ffn(x, out, lp, cfg, B, S_loc)
            return x, (k, v)

        x, (k_all, v_all) = jax.lax.scan(
            layer_body, x, params_rep["layers"]
        )

        # Last-position logits exist only on the last device; other
        # devices compute on their block and the caller's psum keeps SPMD
        # shapes uniform (their contribution is zeroed).
        logits_local = _lm_head_logits(
            params_rep, cfg, x, lm_head_last_only=True
        )[:, 0]
        logits_local = jnp.where(idx == sp - 1, logits_local, 0.0)
        logits = jax.lax.psum(logits_local, SP)
        return logits, k_all, v_all

    seq_spec = P(None, SP)
    cache_spec = P(None, None, SP, None, None)  # [L, B, S(sp), Hkv, D]
    logits, k_all, v_all = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(seq_spec, P(None), P()),
        out_specs=(P(None, None), cache_spec, cache_spec),
        check_vma=False,
    )(tokens, pad_lens, params)
    return logits, {"k": k_all, "v": v_all}


def reshard_cache_for_decode(cache, mesh: Mesh, total_len: int):
    """Sequence-sharded prefill cache → decode layout: gather the sequence
    axis, pad to ``total_len`` slots, shard batch over dp / heads over tp."""
    from adversarial_spec_tpu.parallel.sharding import cache_sharding

    S = cache["k"].shape[2]
    out = {}
    for name, arr in cache.items():
        arr = jax.device_put(arr, cache_sharding(mesh))  # gathers sp
        if total_len > S:
            pad = [(0, 0)] * arr.ndim
            pad[2] = (0, total_len - S)
            arr = jnp.pad(arr, pad)
        out[name] = arr
    return out
