"""Resilience subsystem: fault taxonomy, circuit breakers, chaos injection.

A TPU-native serving stack fails in ways HTTP never does — OOM mid-decode,
device loss, preemption — and the reference's whole failure story
(per-model retry with backoff, graceful round degradation) only covers the
debate seam. This package gives every layer a shared vocabulary and policy:

- ``faults``    — the structured taxonomy (`FaultKind`) and the single
                  ``classify()`` every seam uses, plus process-wide fault
                  counters for tracing.
- ``breaker``   — per-model circuit breakers (closed/open/half-open with
                  probe-on-recovery) consulted by ``debate.core.run_round``
                  so persistently failing opponents are skipped, not
                  retried 3x every round.
- ``injector``  — first-class fault injection at the generate /
                  scheduler-chunk / KV-alloc / checkpoint-load seams,
                  configured via ``--chaos`` or ``ADVSPEC_CHAOS`` — chaos
                  testing as a supported mode, not a monkeypatch.

Fault *isolation* lives where the state lives: ``engine/scheduler.py``
evicts only the affected slot (partial tokens + ``fault_kind`` on its
``SchedResult``) and keeps the rest of the batch decoding.
"""

from adversarial_spec_tpu.resilience.faults import FaultKind, classify

__all__ = ["FaultKind", "classify"]
