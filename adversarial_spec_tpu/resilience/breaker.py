"""Per-model circuit breakers: skip persistently failing opponents.

The reference retries every failing model 3x with backoff *every round*
(models.py:46-47) — fine for HTTP 429s, wasteful for a TPU opponent whose
checkpoint server is down or whose mesh OOMs deterministically: each
round burns the full retry budget re-proving the same failure. A breaker
remembers.

State machine (classic three-state):

    CLOSED --[threshold consecutive failures]--> OPEN
    OPEN   --[cooldown elapsed]---------------> HALF_OPEN (one probe)
    HALF_OPEN --[probe succeeds]--------------> CLOSED
    HALF_OPEN --[probe fails]-----------------> OPEN (cooldown restarts)

``debate.core.run_round`` consults ``allow(model)`` before grouping
requests: a model whose breaker is open is degraded immediately (an
errored ModelResponse, zero engine calls, zero retry budget) and
re-admitted via the half-open probe after ``cooldown_s``. Transitions are
counted for the Tracer / ``--json`` report.

The default registry is process-global (the CLI configures it from
``--breaker-*`` flags); tests build their own with a fake clock.
"""

from __future__ import annotations

import os
import threading
import time

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.resilience import lockdep as lockdep_mod
from adversarial_spec_tpu.resilience.faults import FaultKind

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# A half-open probe that fails with a NON-TRANSIENT fault (FaultKind.BUG
# — a deterministic error no amount of waiting clears) re-opens HARD:
# the cooldown scales by this factor, so the registry stops burning one
# failed probe per cooldown on a model that cannot recover by itself,
# while still re-probing eventually (a redeploy does fix bugs).
HARD_OPEN_FACTOR = 8.0


def replica_key(replica: str, model: str) -> str:
    """The breaker key for one (replica, model) pair — the fleet
    router's generalization of the per-model breaker: replica r0
    failing a model must not ban the model on r1, and a model failing
    everywhere still opens each pair (plus the debate layer's bare
    per-model breaker). The registry is keyed by opaque strings, so
    pairs and bare models coexist in one registry."""
    return f"{replica}::{model}"


class CircuitBreaker:
    """Breaker for ONE model. Not thread-safe on its own — the registry
    serializes access (one lock for all breakers keeps the hot path to a
    single acquire)."""

    def __init__(
        self,
        *,
        threshold: int = 3,
        cooldown_s: float = 30.0,
        clock=time.monotonic,
        name: str = "",
    ):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.name = name  # model id, for transition events
        self.state = CLOSED
        self.failures = 0  # consecutive failures while closed
        self.opened_at: float | None = None
        self.last_fault: FaultKind | None = None
        # Set when a half-open probe failed NON-transiently: the next
        # re-probe waits HARD_OPEN_FACTOR cooldowns (see module note).
        self.hard_open = False
        self._probe_inflight = False
        self._probe_started = 0.0
        # Monotonic per-target-state transition counts (telemetry source
        # of truth) plus a bounded (from, to) log for debugging flaps.
        self.transition_counts: dict[str, int] = {}
        self.transitions: list[tuple[str, str]] = []

    def _set(self, state: str) -> None:
        if state != self.state:
            self.transition_counts[state] = (
                self.transition_counts.get(state, 0) + 1
            )
            self.transitions.append((self.state, state))
            del self.transitions[:-64]
            # Transitions are EVENTS now, not just counters: the flight
            # recorder shows when a model tripped relative to the steps
            # around it (docs/resilience.md).
            obs_mod.emit(
                obs_mod.BreakerEvent(
                    model=self.name, frm=self.state, to=state
                )
            )
            if obs_mod.config().enabled:
                obs_mod.hot.breaker(state).inc()
            self.state = state

    def effective_cooldown(self) -> float:
        """The wait before the next half-open probe: the configured
        cooldown, scaled up when the LAST probe failed non-transiently
        (a BUG does not heal by waiting — probe rarely, not never)."""
        return self.cooldown_s * (HARD_OPEN_FACTOR if self.hard_open else 1.0)

    def allow(self) -> bool:
        """May this model be queried right now? Transitions OPEN →
        HALF_OPEN when the cooldown has elapsed; in HALF_OPEN exactly one
        probe is outstanding at a time."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._clock() - (self.opened_at or 0.0) >= self.effective_cooldown():
                self._set(HALF_OPEN)
                self._probe_inflight = True
                self._probe_started = self._clock()
                return True
            return False
        # HALF_OPEN: one probe at a time — but a probe whose outcome was
        # never recorded (caller crashed mid-round) must not ban the
        # model forever, so a probe older than the cooldown is presumed
        # lost and a new one is admitted.
        if self._probe_inflight:
            if self._clock() - self._probe_started < self.cooldown_s:
                return False
        self._probe_inflight = True
        self._probe_started = self._clock()
        return True

    def record_success(self) -> None:
        self._probe_inflight = False
        self.failures = 0
        self.last_fault = None
        self.hard_open = False
        self._set(CLOSED)

    def record_failure(self, kind: FaultKind = FaultKind.BUG) -> None:
        self._probe_inflight = False
        self.last_fault = kind
        if self.state == HALF_OPEN:
            # Failed probe: straight back to OPEN, cooldown restarts.
            # A TRANSIENT probe fault (OOM, preemption, timeout) may
            # clear by itself, so the normal cooldown re-probes; a
            # NON-transient one (FaultKind.BUG — deterministic) opens
            # HARD: re-probing every cooldown would burn one failed
            # request per cycle proving the same bug, so the next probe
            # waits HARD_OPEN_FACTOR cooldowns instead.
            self.hard_open = not kind.transient
            self.opened_at = self._clock()
            self.failures = 0
            self._set(OPEN)
            return
        self.failures += 1
        if self.failures >= self.threshold:
            self.opened_at = self._clock()
            self.failures = 0
            self._set(OPEN)


class BreakerRegistry:
    """All models' breakers + shared policy knobs."""

    def __init__(
        self,
        *,
        threshold: int = 3,
        cooldown_s: float = 30.0,
        clock=time.monotonic,
        enabled: bool = True,
    ):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.enabled = enabled
        self._clock = clock
        self._lock = lockdep_mod.make_lock("BreakerRegistry._lock")
        self._breakers: dict[str, CircuitBreaker] = {}

    def configure(
        self,
        *,
        threshold: int | None = None,
        cooldown_s: float | None = None,
        enabled: bool | None = None,
    ) -> None:
        """Retune policy; applies to existing breakers too (operators
        adjust a live process via the CLI flags)."""
        with self._lock:
            if threshold is not None:
                self.threshold = max(1, int(threshold))
            if cooldown_s is not None:
                self.cooldown_s = float(cooldown_s)
            if enabled is not None:
                self.enabled = bool(enabled)
            for b in self._breakers.values():
                b.threshold = self.threshold
                b.cooldown_s = self.cooldown_s

    def breaker(self, model: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(model)
            if b is None:
                b = CircuitBreaker(
                    threshold=self.threshold,
                    cooldown_s=self.cooldown_s,
                    clock=self._clock,
                    name=model,
                )
                self._breakers[model] = b
            return b

    def allow(self, model: str) -> bool:
        if not self.enabled:
            return True
        b = self.breaker(model)
        with self._lock:
            return b.allow()

    def record(self, model: str, ok: bool, kind: FaultKind | None = None) -> None:
        if not self.enabled:
            return
        b = self.breaker(model)
        with self._lock:
            if ok:
                b.record_success()
            else:
                b.record_failure(kind or FaultKind.BUG)

    def cooldown_remaining(self, model: str) -> float:
        b = self.breaker(model)
        with self._lock:
            if b.state != OPEN or b.opened_at is None:
                return 0.0
            return max(
                0.0,
                b.effective_cooldown() - (self._clock() - b.opened_at),
            )

    def states(self) -> dict[str, dict]:
        """Per-model snapshot for the ``--json`` resilience report."""
        with self._lock:
            return {
                model: {
                    "state": b.state,
                    "consecutive_failures": b.failures,
                    "last_fault": b.last_fault.value if b.last_fault else None,
                }
                for model, b in self._breakers.items()
            }

    def counters(self) -> dict[str, float]:
        """Aggregate transition counts, Tracer-counter shaped. Backed by
        the monotonic per-breaker counters, not the bounded debug log —
        a model flapping hundreds of times must not undercount."""
        out: dict[str, float] = {}
        with self._lock:
            for b in self._breakers.values():
                for to, n in b.transition_counts.items():
                    key = f"breaker.to_{to}"
                    out[key] = out.get(key, 0.0) + n
        return out

    # -- cross-process persistence (session resume) ------------------------
    # The CLI runs ONE round per process; without persistence every round
    # would restart with fresh (closed) breakers and the skip policy
    # would never fire in the shipped deployment. The snapshot rides on
    # SessionState and is restored on --resume. opened_at is a monotonic
    # timestamp, meaningless across processes, so OPEN circuits persist
    # their REMAINING cooldown instead.

    def snapshot_for_resume(self) -> dict:
        with self._lock:
            out = {}
            for model, b in self._breakers.items():
                if b.state == CLOSED and b.failures == 0:
                    continue  # default state: nothing worth persisting
                remaining = 0.0
                if b.state in (OPEN, HALF_OPEN) and b.opened_at is not None:
                    remaining = max(
                        0.0,
                        b.effective_cooldown() - (self._clock() - b.opened_at),
                    )
                out[model] = {
                    # A probe that never reported is presumed lost: a
                    # HALF_OPEN circuit resumes as OPEN with no cooldown
                    # left, so the next round re-probes immediately.
                    "state": OPEN if b.state == HALF_OPEN else b.state,
                    "failures": b.failures,
                    "cooldown_remaining": remaining,
                    "hard": b.hard_open,
                    "last_fault": b.last_fault.value if b.last_fault else None,
                }
            return out

    def restore(self, snapshot: dict) -> None:
        for model, data in (snapshot or {}).items():
            b = self.breaker(model)
            with self._lock:
                b.failures = int(data.get("failures", 0))
                last = data.get("last_fault")
                b.last_fault = FaultKind(last) if last else None
                b.hard_open = bool(data.get("hard", False))
                if data.get("state") == OPEN:
                    # Not a transition (no counter): resumed state.
                    b.state = OPEN
                    remaining = float(data.get("cooldown_remaining", 0.0))
                    b.opened_at = self._clock() - (
                        b.effective_cooldown() - remaining
                    )


# -- default process registry ---------------------------------------------

_default: BreakerRegistry | None = None
_default_lock = lockdep_mod.make_lock("breaker._default_lock")


def default_registry() -> BreakerRegistry:
    """The process-wide registry (env-tunable defaults; CLI flags win)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = BreakerRegistry(
                threshold=int(os.environ.get("ADVSPEC_BREAKER_THRESHOLD", 3)),
                cooldown_s=float(
                    os.environ.get("ADVSPEC_BREAKER_COOLDOWN", 30.0)
                ),
            )
        return _default


def reset_default_registry() -> None:
    """Test hook: drop all breaker state."""
    global _default
    with _default_lock:
        _default = None
