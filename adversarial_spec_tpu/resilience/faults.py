"""Structured fault taxonomy + the single ``classify()`` used everywhere.

Before this module, transiency was decided by string-marker lists copied
per call site (engine/tpu.py kept its own tuple); now every seam —
engine chat, scheduler slot eviction, breaker accounting — speaks one
vocabulary:

=============  ==========  =================================================
kind           transient   typical producers
=============  ==========  =================================================
OOM            yes         RESOURCE_EXHAUSTED, HBM exhaustion mid-decode
DEVICE_LOST    yes         UNAVAILABLE, dead ICI tunnel, OUT_OF_RANGE
PREEMPTED      yes         PREEMPTED/ABORTED (maintenance, spot reclaim)
TIMEOUT        yes         DEADLINE_EXCEEDED, wall-clock budget expiry
SHED           no          serve-daemon POLICY refusals (quota/drain load
                           shed) — the model did nothing wrong: never
                           retried, never fed to its circuit breaker
BUG            no          everything else — retrying a TypeError is noise
=============  ==========  =================================================

Transient faults are retried (debate backoff, scheduler retry-once);
BUG is surfaced immediately. SHED is the serving layer speaking, not
the model: ``run_round`` resolves it as an error WITHOUT recording a
breaker failure (a drain storm must not open every opponent's circuit
— found by the SIGTERM drain drill). Injected faults
(resilience/injector.py) carry their kind as an attribute so
classification is exact, not textual.

The module also owns the process-wide fault counters: every classified
fault is ``record()``-ed under ``<seam>.<kind>`` and the CLI drains
``snapshot()`` into the Tracer counters / ``--json`` report.
"""

from __future__ import annotations

import re
import threading
from enum import Enum

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.resilience import lockdep as lockdep_mod


class FaultKind(str, Enum):
    """What failed, independent of which layer noticed."""

    OOM = "oom"
    DEVICE_LOST = "device_lost"
    PREEMPTED = "preempted"
    TIMEOUT = "timeout"
    SHED = "shed"
    BUG = "bug"

    @property
    def transient(self) -> bool:
        """Whether a retry has any chance of succeeding. A SHED is a
        deliberate policy answer — retrying into a draining/over-quota
        daemon is noise, the client's retry_after_s is the contract."""
        return self not in (FaultKind.BUG, FaultKind.SHED)


# Ordered, lowercase substring markers: first matching kind wins. OOM is
# checked first ("resource_exhausted" messages often also say the device
# was unavailable while dying); BUG is the no-match default.
_MARKERS: tuple[tuple[FaultKind, tuple[str, ...]], ...] = (
    # Serve-layer policy refusals first: their messages are ours
    # (serve/sched.py stamps "shed (<reason>):" / "drained:") and must
    # never be mistaken for a device fault by the later markers.
    (FaultKind.SHED, ("shed (", "drained:")),
    (
        FaultKind.OOM,
        ("resource_exhausted", "out of memory", "outofmemory"),
    ),
    (FaultKind.PREEMPTED, ("preempted", "preemption", "aborted")),
    (
        FaultKind.DEVICE_LOST,
        ("unavailable", "device lost", "data_loss", "out_of_range"),
    ),
    (FaultKind.TIMEOUT, ("deadline_exceeded", "timed out", "timeout")),
)

# "OOM" only as an uppercase standalone token: a lowercase substring
# match would classify any message containing room/zoom/bloom as a
# transient OOM and burn retries on permanent bugs.
_OOM_TOKEN = re.compile(r"\bOOM\b")


def classify_message(msg: str) -> FaultKind:
    """Classify from an error STRING (e.g. a ``Completion.error`` that
    crossed the engine boundary and lost its exception object)."""
    if _OOM_TOKEN.search(msg):
        return FaultKind.OOM
    low = msg.lower()
    for kind, markers in _MARKERS:
        if any(m in low for m in markers):
            return kind
    return FaultKind.BUG


def classify(exc: BaseException) -> FaultKind:
    """One classification for every seam.

    Injected faults carry ``fault_kind`` and classify exactly; known
    Python types short-circuit; everything else falls back to the
    message markers (XLA/PJRT surface gRPC-style status codes in text).
    """
    kind = getattr(exc, "fault_kind", None)
    if isinstance(kind, FaultKind):
        return kind
    if isinstance(exc, TimeoutError):
        return FaultKind.TIMEOUT
    if isinstance(exc, MemoryError):
        return FaultKind.OOM
    return classify_message(f"{type(exc).__name__}: {exc}")


def is_transient(exc: BaseException) -> bool:
    return classify(exc).transient


# -- process-wide fault counters ------------------------------------------
# Keyed "<seam>.<kind>" (e.g. "scheduler_chunk.oom"). A module-level
# registry rather than plumbing a Tracer through every engine layer: the
# engine/scheduler sit several calls below the CLI's tracer, and faults
# are rare enough that a lock per event is free.

_lock = lockdep_mod.make_lock("faults._lock")
_counts: dict[str, int] = {}


def record(kind: FaultKind, seam: str) -> None:
    with _lock:
        key = f"{seam}.{kind.value}"
        _counts[key] = _counts.get(key, 0) + 1
    # Mirror into the observability registry: every classified fault is
    # a labeled counter too (the Prometheus-facing shape of the same
    # fact; the scheduler adds eviction-context FaultEvents separately).
    if obs_mod.config().enabled:
        obs_mod.hot.fault(seam, kind.value).inc()


def snapshot() -> dict[str, int]:
    with _lock:
        return dict(_counts)


def reset() -> None:
    with _lock:
        _counts.clear()
