"""First-class fault injection — chaos testing as a supported mode.

The test suite used to simulate failures by monkeypatching ``generate``;
that covers the debate seam but cannot reach inside a live scheduler
drain, and it is not something an operator can switch on. This module
puts permanent, near-zero-cost hooks at the four seams where TPU serving
actually breaks:

==================  =====================================================
seam                fires just before
==================  =====================================================
``generate``        a model group's decode dispatch (engine/tpu.py)
``scheduler_chunk`` each ContinuousBatcher decode chunk
``kv_alloc``        page reservation at admission (engine/scheduler.py)
``kv_swap``         each tier-block promotion into an admission's pages
                    (engine/scheduler.py — the tiered-KV swap path)
``weight_swap``     each weight promotion of a host-demoted model back
                    into HBM (engine/tpu.py) — a fault here aborts the
                    swap with the host entry untouched: only the
                    admission waiting on the swap degrades, the
                    residency ledger stays conservation-clean, and the
                    aborted swap is a declared WeightEvent
                    (``tools/chaos_run.py --weight-swap`` is the drill)
``checkpoint_load`` parameter materialization (engine/tpu.py)
``crash``           each round-journal fsync append (debate/journal.py)
                    — the write-ahead durability path: a fault here is
                    a record that never became durable, and the round
                    must survive it (journal failure is contained, the
                    kill-chaos harness proves the stronger SIGKILL
                    variant)
``replica``         each fleet group dispatch (fleet/router.py) — a
                    fault here is a replica-level failure the
                    per-(replica, model) breakers absorb: the request
                    re-routes, the pair's circuit counts the hit, and
                    no process dies (the SIGKILL variant lives in
                    ``tools/chaos_run.py --replica-kill``)
==================  =====================================================

Configure with ``--chaos`` on the CLI or ``ADVSPEC_CHAOS`` in the
environment. Spec grammar (comma-separated rules)::

    kind@seam[:p=0.5][:after=N][:times=N][:slot=K]

    oom@scheduler_chunk:after=1:times=1:slot=1
    device_lost@generate:p=0.25
    bug@kv_alloc:times=1

``after=N`` skips the first N hits of the seam; ``times=N`` caps total
fires (0 = unlimited); ``p`` is the per-hit fire probability (seeded via
``ADVSPEC_CHAOS_SEED`` / ``--chaos-seed`` for reproducible chaos);
``slot`` targets a scheduler slot for eviction (scheduler seams only).

Injected exceptions are ``InjectedFault`` — they carry their ``FaultKind``
as an attribute (exact classification) *and* the matching status-code
marker in their message, so they exercise the same string paths real
XLA/PJRT faults take.
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field

from adversarial_spec_tpu.resilience import lockdep as lockdep_mod
from adversarial_spec_tpu.resilience.faults import FaultKind

SEAMS = (
    "generate",
    "scheduler_chunk",
    "kv_alloc",
    "kv_swap",
    "weight_swap",
    "checkpoint_load",
    "crash",
    "replica",
)

# Marker text per kind: mirrors what PJRT/XLA put in real messages so the
# textual classify() path agrees with the attribute path.
_KIND_MESSAGES = {
    FaultKind.OOM: "RESOURCE_EXHAUSTED: injected OOM",
    FaultKind.DEVICE_LOST: "UNAVAILABLE: injected device loss",
    FaultKind.PREEMPTED: "ABORTED: injected preemption",
    FaultKind.TIMEOUT: "DEADLINE_EXCEEDED: injected timeout",
    # The serve layer's typed-refusal shape (serve/sched.py stamps
    # "shed (<reason>):") so the textual classify() path agrees.
    FaultKind.SHED: "shed (injected): synthetic load-shed refusal",
    FaultKind.BUG: "injected programming error",
}


class InjectedFault(RuntimeError):
    """A synthetic fault raised at a chaos seam."""

    def __init__(self, kind: FaultKind, seam: str, slot: int | None = None):
        super().__init__(f"{_KIND_MESSAGES[kind]} at seam {seam!r} (chaos)")
        self.fault_kind = kind
        self.seam = seam
        self.slot = slot


@dataclass
class FaultRule:
    """One armed fault: what to raise, where, and when."""

    kind: FaultKind
    seam: str
    p: float = 1.0  # per-hit fire probability
    after: int = 0  # skip the first N hits of this seam
    times: int = 0  # max total fires (0 = unlimited)
    slot: int | None = None  # scheduler slot to evict (scheduler seams)
    hits: int = field(default=0, compare=False)
    fires: int = field(default=0, compare=False)

    def __post_init__(self) -> None:
        if self.seam not in SEAMS:
            raise ValueError(
                f"unknown chaos seam {self.seam!r}; known: {', '.join(SEAMS)}"
            )


def parse_chaos_spec(spec: str) -> list[FaultRule]:
    """``kind@seam[:opt=val]...`` (comma-separated) → rules.

    Raises ValueError with an actionable message on any malformed piece —
    a typo'd chaos flag must fail loudly, not silently not inject.
    """
    rules = []
    for part in (p.strip() for p in spec.split(",")):
        if not part:
            continue
        head, _, opts = part.partition(":")
        kind_s, sep, seam = head.partition("@")
        if not sep or not seam:
            raise ValueError(
                f"bad chaos rule {part!r}: expected kind@seam[:opt=val]"
            )
        try:
            kind = FaultKind(kind_s.strip().lower())
        except ValueError:
            known = ", ".join(k.value for k in FaultKind)
            raise ValueError(
                f"unknown fault kind {kind_s!r}; known: {known}"
            ) from None
        kw: dict = {}
        if opts:
            for opt in opts.split(":"):
                key, sep, val = opt.partition("=")
                if not sep:
                    raise ValueError(f"bad chaos option {opt!r} in {part!r}")
                key = key.strip()
                try:
                    if key == "p":
                        kw["p"] = float(val)
                    elif key in ("after", "times", "slot"):
                        kw[key] = int(val)
                    else:
                        raise ValueError
                except ValueError:
                    raise ValueError(
                        f"bad chaos option {opt!r} in {part!r} "
                        "(known: p=<float>, after=<int>, times=<int>, "
                        "slot=<int>)"
                    ) from None
        rules.append(FaultRule(kind=kind, seam=seam.strip(), **kw))
    return rules


class FaultInjector:
    """Holds armed rules; ``check(seam)`` raises when one fires."""

    def __init__(self, rules=(), seed: int | None = None):
        self.rules: list[FaultRule] = list(rules)
        self._rng = random.Random(seed)
        self._lock = lockdep_mod.make_lock("FaultInjector._lock")
        self.fired: dict[str, int] = {}  # "<seam>.<kind>" -> fire count
        self.seam_hits: dict[str, int] = {}  # seam -> hook invocations

    @property
    def enabled(self) -> bool:
        return bool(self.rules)

    def check(self, seam: str, slot: int | None = None) -> None:
        """Raise InjectedFault if an armed rule for ``seam`` fires."""
        with self._lock:
            self.seam_hits[seam] = self.seam_hits.get(seam, 0) + 1
            for rule in self.rules:
                if rule.seam != seam:
                    continue
                rule.hits += 1
                if rule.hits <= rule.after:
                    continue
                if rule.times and rule.fires >= rule.times:
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    continue
                rule.fires += 1
                key = f"{seam}.{rule.kind.value}"
                self.fired[key] = self.fired.get(key, 0) + 1
                raise InjectedFault(
                    rule.kind, seam, slot=rule.slot if rule.slot is not None else slot
                )


# -- active injector -------------------------------------------------------

_active: FaultInjector | None = None
_active_lock = lockdep_mod.make_lock("injector._active_lock")


def active() -> FaultInjector:
    """The process injector; first use materializes ``ADVSPEC_CHAOS``."""
    global _active
    with _active_lock:
        if _active is None:
            spec = os.environ.get("ADVSPEC_CHAOS", "")
            seed_env = os.environ.get("ADVSPEC_CHAOS_SEED")
            _active = FaultInjector(
                parse_chaos_spec(spec) if spec else (),
                seed=int(seed_env) if seed_env else None,
            )
        return _active


def install(injector: FaultInjector | None) -> None:
    """Replace the process injector (CLI ``--chaos``; tests)."""
    global _active
    with _active_lock:
        _active = injector


def reset() -> None:
    """Test hook: drop the injector (next ``active()`` re-reads env)."""
    install(None)


def fire(seam: str, slot: int | None = None) -> None:
    """The hook call sites use. Near-free when chaos is off: one global
    read and one attribute check."""
    inj = active()
    if inj.rules:
        inj.check(seam, slot)
