"""Runtime lock-order sanitizer (lockdep) — the dynamic complement of
graftlint's GL-LOCK family (tools/graftlint/rules/locking.py).

The static rules prove lock discipline over the call graph they can
see; callbacks, consumer seams, and injected providers are exactly the
edges a conservative analysis cannot follow. ``TrackedLock`` /
``TrackedRLock`` are drop-in wrappers that maintain one per-process
acquisition-order graph keyed by lock *name* (the lock class, in
kernel-lockdep terms — every ``ServeScheduler._lock`` instance feeds
the same node): the FIRST time thread T acquires B while holding A, an
A→B edge is recorded together with the acquiring stack, and if B
already reaches A in the graph the inversion is reported immediately —
no actual deadlock (two threads parked forever) has to occur for the
cycle to be caught.

Enablement: ``make_lock``/``make_rlock`` return RAW ``threading``
primitives when lockdep is off (``ADVSPEC_LOCKDEP`` unset/0 and no
``configure(enabled=True)``) — production pays zero bookkeeping, not
even a wrapper attribute load. Tier-1's conftest and every chaos drill
force it ON, so the whole suite runs as a deadlock detector.

On violation: raise ``LockOrderViolation`` (``raise_on_violation``) or
record it (default — the drills and the suite-wide teardown assert
inspect ``violations()``), emit a ``LockEvent`` through the flight
recorder, and trigger an auto-dump so the JSONL keeps both stacks.

Telemetry: per-lock hold/wait wall histograms
(``advspec_lock_hold_seconds{lock}`` / ``advspec_lock_wait_seconds``)
through ``obs.hot`` — contention shows up as a fat wait column long
before it becomes a stall. The obs subsystem's own locks are created
with ``metrics=False``: observing a histogram takes the metrics
registry lock, so the registry lock must never observe itself (a
thread-local re-entrancy latch guards the same hazard dynamically).
"""

from __future__ import annotations

import os
import threading
import time
import traceback


class LockOrderViolation(RuntimeError):
    """A lock-order inversion: acquiring ``edge[1]`` while holding
    ``edge[0]`` closes a cycle in the acquisition-order graph. The
    message names both stacks — the acquiring one and the first stack
    that recorded the opposite-direction path."""

    def __init__(self, message: str, edge: tuple[str, str]):
        super().__init__(message)
        self.edge = edge


# -- process-wide state -----------------------------------------------------

# Raw (untracked) lock: guards the graph/violation ledgers below. It is
# only ever acquired with the re-entrancy latch set, so tracked-lock
# bookkeeping can never recurse into it.
_meta = threading.Lock()
_edges: dict[str, set[str]] = {}  # A -> {B}: B was acquired holding A
_edge_stacks: dict[tuple[str, str], str] = {}  # first-observed stack
_edge_sites: dict[tuple[str, str], str] = {}  # "held A at ..." one-liner
_violations: list[LockOrderViolation] = []

_enabled: bool | None = None  # None = follow the environment
_raise_on_violation = False


class _Local(threading.local):
    def __init__(self) -> None:
        # Acquisition stack: [lock, acquire_t, reentry_count] records.
        self.held: list[list] = []
        # Re-entrancy latch: >0 while inside lockdep's own bookkeeping
        # (graph mutation, metric observe, event emission) — tracked
        # locks acquired there pass straight through to the primitive.
        self.latch = 0


_tls = _Local()


def env_enabled() -> bool:
    """The process default for the sanitizer (``ADVSPEC_LOCKDEP``)."""
    return os.environ.get("ADVSPEC_LOCKDEP", "0") not in ("", "0")


def enabled() -> bool:
    return env_enabled() if _enabled is None else _enabled


def configure(
    *, enabled: bool | None = None, raise_on_violation: bool | None = None
) -> None:
    """Override the env default (tests, drills, ``--lockdep``). Only
    affects locks created AFTER the call — ``make_lock`` decides
    tracked-vs-raw at construction time so the disabled path stays
    zero-cost."""
    global _enabled, _raise_on_violation
    if enabled is not None:
        _enabled = bool(enabled)
    if raise_on_violation is not None:
        _raise_on_violation = bool(raise_on_violation)


def raise_on_violation() -> bool:
    return _raise_on_violation


def reset() -> None:
    """Clear the acquisition-order graph and the violation ledger (per
    test / per drill — edges must not leak across unrelated lock
    instances that happen to share a name)."""
    with _meta:
        _edges.clear()
        _edge_stacks.clear()
        _edge_sites.clear()
        _violations.clear()


def violations() -> list[LockOrderViolation]:
    with _meta:
        return list(_violations)


def order_edges() -> dict[str, tuple[str, ...]]:
    """Snapshot of the observed acquisition-order graph (lock name ->
    locks acquired while holding it) — the runtime twin of the
    hierarchy GL-LOCK-ORDER emits into ``--json``."""
    with _meta:
        return {a: tuple(sorted(bs)) for a, bs in sorted(_edges.items())}


def held_names() -> tuple[str, ...]:
    """The current thread's held tracked locks, outermost first."""
    return tuple(rec[0].name for rec in _tls.held)


# -- graph maintenance ------------------------------------------------------


def _find_path(src: str, dst: str) -> list[str] | None:
    """A path src -> ... -> dst in the edge graph (caller holds _meta)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _own_frames() -> str:
    """The acquiring stack, trimmed of lockdep's own frames."""
    frames = traceback.format_stack()
    return "".join(
        f for f in frames if "/lockdep.py" not in f.replace("\\", "/")
    )


def _record_edge(held_name: str, new_name: str) -> None:
    """Record held_name -> new_name; detect the cycle it may close.
    Caller has the re-entrancy latch set."""
    key = (held_name, new_name)
    if key in _edge_stacks:  # fast path: seen pairs are one dict probe
        return
    with _meta:
        if key in _edge_stacks:
            return
        back = _find_path(new_name, held_name)
        stack = _own_frames()
        _edge_stacks[key] = stack
        _edge_sites[key] = f"{held_name} -> {new_name}"
        _edges.setdefault(held_name, set()).add(new_name)
        if back is None:
            return
        # Adding held->new closed new -> ... -> held: an inversion.
        first_edge = (back[0], back[1])
        other = _edge_stacks.get(first_edge, "<unrecorded>")
        cycle = " -> ".join([held_name, new_name] + back[1:])
        msg = (
            f"lock-order inversion: acquiring {new_name!r} while "
            f"holding {held_name!r} closes the cycle [{cycle}]\n"
            f"--- this acquisition ({held_name} -> {new_name}):\n"
            f"{stack}"
            f"--- first recorded opposite edge "
            f"({first_edge[0]} -> {first_edge[1]}):\n{other}"
        )
        violation = LockOrderViolation(msg, key)
        _violations.append(violation)
    _emit_violation(violation)
    if _raise_on_violation:
        raise violation


def _emit_violation(violation: LockOrderViolation) -> None:
    """LockEvent + auto-dump; best-effort (a telemetry failure must
    never mask the violation itself)."""
    try:
        from .. import obs as obs_mod

        if obs_mod.config().enabled:
            a, b = violation.edge
            obs_mod.emit(
                obs_mod.events.LockEvent(
                    op="violation", lock=b, held=a, edge=f"{a}->{b}"
                )
            )
            obs_mod.autodump("lockdep")
    except Exception:
        pass


# -- the wrappers -----------------------------------------------------------


class TrackedLock:
    """Drop-in ``threading.Lock`` that feeds the acquisition-order
    graph and the hold/wait histograms. ``name`` is the lock class:
    every instance of ``ServeScheduler._lock`` shares one graph node."""

    _reentrant = False

    def __init__(self, name: str, *, metrics: bool = True):
        self.name = name
        self._metrics = metrics
        self._lk = threading.RLock() if self._reentrant else threading.Lock()
        self._hold_h = None  # cached histogram handles (obs.reset
        self._wait_h = None  # zeroes in place; handles stay live)

    # threading.Condition(lock) uses exactly this pair.
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        tls = _tls
        if tls.latch:
            return self._lk.acquire(blocking, timeout)
        t0 = time.perf_counter()
        ok = self._lk.acquire(blocking, timeout)
        if not ok:
            return False
        try:
            self._note_acquired(time.perf_counter() - t0)
        except LockOrderViolation:
            self._lk.release()
            raise
        return True

    def release(self) -> None:
        tls = _tls
        if tls.latch:
            self._lk.release()
            return
        held = tls.held
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                rec = held[i]
                rec[2] -= 1
                if rec[2] == 0:
                    del held[i]
                    self._observe_hold(time.perf_counter() - rec[1])
                break
        self._lk.release()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__} {self.name!r}>"

    # -- bookkeeping --------------------------------------------------

    def _note_acquired(self, wait: float) -> None:
        tls = _tls
        held = tls.held
        if self._reentrant:
            for rec in held:
                if rec[0] is self:
                    rec[2] += 1  # re-entry: no edge, no second record
                    return
        if held:
            top = held[-1][0]
            if top is not self and top.name != self.name:
                tls.latch += 1
                try:
                    _record_edge(top.name, self.name)
                finally:
                    tls.latch -= 1
        held.append([self, time.perf_counter(), 1])
        self._observe_wait(wait)

    def _observe_wait(self, wait: float) -> None:
        if not self._metrics:
            return
        tls = _tls
        tls.latch += 1
        try:
            from .. import obs as obs_mod

            if not obs_mod.config().enabled:
                return  # gate every observe, not just the handle mint
            h = self._wait_h
            if h is None:
                h = self._wait_h = obs_mod.hot.lock_wait(self.name)
            h.observe(wait)
        except Exception:
            pass
        finally:
            tls.latch -= 1

    def _observe_hold(self, hold: float) -> None:
        if not self._metrics:
            return
        tls = _tls
        tls.latch += 1
        try:
            from .. import obs as obs_mod

            if not obs_mod.config().enabled:
                return  # gate every observe, not just the handle mint
            h = self._hold_h
            if h is None:
                h = self._hold_h = obs_mod.hot.lock_hold(self.name)
            h.observe(hold)
        except Exception:
            pass
        finally:
            tls.latch -= 1


class TrackedRLock(TrackedLock):
    """Drop-in ``threading.RLock``: same-instance re-entry is counted,
    never an edge (the router's retirement surgery re-enters its own
    ``_mlock`` by design — that is what the RLock is for)."""

    _reentrant = True

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        if self._lk.acquire(blocking=False):
            self._lk.release()
            return False
        return True


def make_lock(name: str, *, metrics: bool = True):
    """A ``threading.Lock`` (lockdep off — zero added cost) or a
    ``TrackedLock`` (lockdep on). The one construction seam every
    declared lock in the package routes through."""
    if enabled():
        return TrackedLock(name, metrics=metrics)
    return threading.Lock()


def make_rlock(name: str, *, metrics: bool = True):
    if enabled():
        return TrackedRLock(name, metrics=metrics)
    return threading.RLock()


# -- self test --------------------------------------------------------------


def self_test() -> list[str]:
    """Prove the sanitizer is live: a synthetic two-lock inversion must
    be detected and must name both stacks (tools/lint_all.py runs this
    as a stage, mirroring graftlint ``--self-test``). Global state is
    snapshotted and restored — a self-test must not leave edges or a
    recorded violation behind."""
    global _enabled, _raise_on_violation
    problems: list[str] = []
    with _meta:
        saved = (
            {k: set(v) for k, v in _edges.items()},
            dict(_edge_stacks),
            dict(_edge_sites),
            list(_violations),
        )
    saved_cfg = (_enabled, _raise_on_violation)
    try:
        configure(enabled=True, raise_on_violation=False)
        a = TrackedLock("lockdep-selftest.A", metrics=False)
        b = TrackedLock("lockdep-selftest.B", metrics=False)
        with a:
            with b:
                pass
        before = len(violations())
        with b:
            with a:
                pass
        got = violations()[before:]
        if not got:
            problems.append(
                "lockdep self-test: synthetic A->B / B->A inversion "
                "produced no LockOrderViolation"
            )
        else:
            msg = str(got[0])
            if "this acquisition" not in msg or "opposite edge" not in msg:
                problems.append(
                    "lockdep self-test: violation does not name both "
                    f"stacks: {msg[:200]!r}"
                )
    finally:
        _enabled, _raise_on_violation = saved_cfg
        with _meta:
            _edges.clear()
            _edges.update(saved[0])
            _edge_stacks.clear()
            _edge_stacks.update(saved[1])
            _edge_sites.clear()
            _edge_sites.update(saved[2])
            _violations.clear()
            _violations.extend(saved[3])
    return problems
