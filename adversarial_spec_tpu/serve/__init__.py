"""``advspec serve`` — the overload-safe persistent serving daemon.

One CLI invocation has always been one debate round; this package is
the layer ROADMAP item 1 calls for between the engine core and
"millions of users": a long-lived process (``debate serve`` /
``python -m adversarial_spec_tpu.serve``) that runs MANY concurrent
debates against the shared per-model batchers, over a line-delimited
JSON request/stream transport on a local unix socket (the per-token
transport PR 9 deferred here). The robustness core, in dependency
order:

- **admission control** (serve/sched.py ``try_admit``): bounded
  per-tenant queues and an estimated-token-backlog cap; past either,
  an arrival storm degrades to TYPED, retry-after-carrying refusals
  (serve/protocol.py ``SHED_REASONS``) instead of latency collapse.
- **fair-share scheduling** (serve/sched.py ``ServeScheduler``): a
  stride/deficit scheduler interleaves opponent requests from
  concurrent debates into the shared engine by per-tenant token
  accounting — quotas enforced at admission and dispatch, passes
  debited with the ACTUAL tokens each completion paid (``Usage``).
- **priority tiers**: interactive vs batch-critique classes. An
  interactive arrival that out-waits its grace while a batch unit
  occupies the engine triggers policy-driven preemption — the running
  batch request's stream consumer returns False, the batcher releases
  its slot through the SAME ``_release_slot`` surgery early-cancel
  uses (partial KV salvaged into the prefix cache), and the unit
  re-queues for re-admission. Sustained overload enters a declared
  **brownout** (speculation γ lowered, batch tier paused) before any
  interactive shed.
- **graceful drain**: SIGTERM (or the ``drain`` op) stops admissions,
  lets in-flight debates finish or journal-commit (PR 10's journal
  makes a drain-deadline kill lossless), sheds the queue with typed
  ``draining`` refusals, and exits 0 with a drain report.

``tools/chaos_run.py --overload`` closes the loop (open-loop arrival
storm at kx capacity, shed-not-collapse asserted), and ``bench.py
--mode serve`` pins the capacity point + the SIGTERM drain drill
(BENCH_serve.json).

Process-wide config + stats follow the ``procconfig`` pattern shared
with ``interleave``/``spec``/``kvtier``/``fleet``; the daemon arms the
config ONCE at startup (it deliberately does not run the CLI's
per-invocation reset cascade mid-serve — see obs/trace.py's daemon
scopes). Deliberately imports no jax: the mock-engine daemon pins the
whole state machine on CPU.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from adversarial_spec_tpu.engine import procconfig

DEFAULT_QUEUE_DEPTH = 8
DEFAULT_BACKLOG_TOKENS = 65536
DEFAULT_DRAIN_DEADLINE_S = 5.0
DEFAULT_BROWNOUT_GAMMA = 2
# Brownout hysteresis: enter when the estimated backlog crosses
# enter_fraction * max_backlog_tokens, exit below exit_fraction — the
# declared degradation step BEFORE any interactive shed (a hard shed
# needs the full cap).
DEFAULT_BROWNOUT_ENTER_FRACTION = 0.75
DEFAULT_BROWNOUT_EXIT_FRACTION = 0.5


def _env_int(name: str, default: int, floor: int = 0) -> int:
    try:
        return max(floor, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _env_float(name: str, default: float, floor: float = 0.0) -> float:
    try:
        return max(floor, float(os.environ.get(name, default)))
    except ValueError:
        return default


def env_queue_depth() -> int:
    """Per-tenant outstanding-debate cap (``ADVSPEC_SERVE_QUEUE_DEPTH``)."""
    return _env_int("ADVSPEC_SERVE_QUEUE_DEPTH", DEFAULT_QUEUE_DEPTH, 1)


def env_backlog_tokens() -> int:
    """Estimated-token-backlog cap (``ADVSPEC_SERVE_BACKLOG_TOKENS``)."""
    return _env_int(
        "ADVSPEC_SERVE_BACKLOG_TOKENS", DEFAULT_BACKLOG_TOKENS, 1
    )


def env_quota_tokens() -> int:
    """Per-tenant token quota, 0 = unlimited
    (``ADVSPEC_SERVE_QUOTA_TOKENS``; refillable via the ``refill``
    protocol op)."""
    return _env_int("ADVSPEC_SERVE_QUOTA_TOKENS", 0)


def env_drain_deadline_s() -> float:
    """Graceful-drain deadline before queued work is shed
    (``ADVSPEC_SERVE_DRAIN_DEADLINE_S``)."""
    return _env_float(
        "ADVSPEC_SERVE_DRAIN_DEADLINE_S", DEFAULT_DRAIN_DEADLINE_S
    )


def env_ttft_slo_ms() -> float:
    """Interactive-tier TTFT SLO budget in milliseconds — the
    preemption policy's trigger (``ADVSPEC_SERVE_TTFT_SLO_MS``; 0 =
    preempt the moment an interactive unit waits behind batch)."""
    return _env_float("ADVSPEC_SERVE_TTFT_SLO_MS", 0.0)


@dataclass
class ServeConfig:
    """Process-wide knobs, armed once at daemon startup (or by tests)."""

    # Admission: per-tenant outstanding-debate cap and the estimated
    # token backlog past which NEW admissions shed (typed, retry-after).
    max_queue_depth: int = DEFAULT_QUEUE_DEPTH
    max_backlog_tokens: int = DEFAULT_BACKLOG_TOKENS
    # Per-tenant token quota (0 = unlimited). Enforced at admission
    # (whole debates) and at dispatch (per opponent unit: exhaustion
    # mid-round sheds the REMAINING opponents, the round still
    # commits); debited with actual Usage tokens on completion.
    tenant_quota_tokens: int = 0
    # Graceful drain: how long SIGTERM waits for in-flight debates
    # before shedding the queue and cancelling running units.
    drain_deadline_s: float = DEFAULT_DRAIN_DEADLINE_S
    # Brownout (declared degradation before interactive shed).
    brownout_enter_fraction: float = DEFAULT_BROWNOUT_ENTER_FRACTION
    brownout_exit_fraction: float = DEFAULT_BROWNOUT_EXIT_FRACTION
    brownout_gamma: int = DEFAULT_BROWNOUT_GAMMA
    # Preemption policy: an interactive unit that has waited this long
    # while a batch unit holds the engine preempts it (0 = immediately).
    # When interactive_ttft_slo_ms is set, the grace defaults to half
    # the SLO budget — preempt BEFORE the breach, not after.
    preempt_grace_s: float = 0.0
    interactive_ttft_slo_ms: float = 0.0
    # Same-model opponent units batched into one engine chat dispatch
    # (N rows of one batched decode on the real engine).
    max_dispatch_batch: int = 4
    # Debate round drivers running concurrently (worker threads).
    max_debates_in_flight: int = 32


@dataclass
class ServeStats(procconfig.StatsBase):
    """Process-wide serving counters, aggregated since daemon start.

    The shed-not-collapse ledger the overload chaos drill audits:
    ``accepted_debates`` must equal ``completed_debates`` (+ the
    journal-resumable remainder a drain left) and every refusal is in
    ``shed_debates`` — nothing is ever silently dropped.
    ``units_preempted`` counts batch units cancelled for tier pressure
    (each re-queues: ``units_readmitted``); ``shed_fraction`` is the
    headline BENCH_serve pins at the kx-capacity point."""

    accepted_debates: int = 0
    completed_debates: int = 0
    shed_debates: int = 0
    units_dispatched: int = 0
    units_completed: int = 0
    units_shed: int = 0
    units_preempted: int = 0
    units_readmitted: int = 0
    units_drained: int = 0
    brownout_entries: int = 0
    brownout_exits: int = 0
    tokens_charged: int = 0
    preempted_partial_tokens: int = 0

    def snapshot(self) -> dict:
        out = self.as_dict()
        offered = self.accepted_debates + self.shed_debates
        out["shed_fraction"] = (
            round(self.shed_debates / offered, 4) if offered else 0.0
        )
        return out


_state = procconfig.ProcState(
    ServeConfig(
        max_queue_depth=env_queue_depth(),
        max_backlog_tokens=env_backlog_tokens(),
        tenant_quota_tokens=env_quota_tokens(),
        drain_deadline_s=env_drain_deadline_s(),
        interactive_ttft_slo_ms=env_ttft_slo_ms(),
    ),
    ServeStats(),
    coerce={
        "max_queue_depth": lambda v: max(1, int(v)),
        "max_backlog_tokens": lambda v: max(1, int(v)),
        "tenant_quota_tokens": lambda v: max(0, int(v)),
        "drain_deadline_s": lambda v: max(0.0, float(v)),
        "brownout_gamma": lambda v: max(1, int(v)),
        "max_dispatch_batch": lambda v: max(1, int(v)),
        "max_debates_in_flight": lambda v: max(1, int(v)),
    },
)
_config = _state.config
stats = _state.stats


def config() -> ServeConfig:
    return _state.config


def configure(
    max_queue_depth: int | None = None,
    max_backlog_tokens: int | None = None,
    tenant_quota_tokens: int | None = None,
    drain_deadline_s: float | None = None,
    brownout_enter_fraction: float | None = None,
    brownout_exit_fraction: float | None = None,
    brownout_gamma: int | None = None,
    preempt_grace_s: float | None = None,
    interactive_ttft_slo_ms: float | None = None,
    max_dispatch_batch: int | None = None,
    max_debates_in_flight: int | None = None,
) -> ServeConfig:
    return _state.configure(
        max_queue_depth=max_queue_depth,
        max_backlog_tokens=max_backlog_tokens,
        tenant_quota_tokens=tenant_quota_tokens,
        drain_deadline_s=drain_deadline_s,
        brownout_enter_fraction=brownout_enter_fraction,
        brownout_exit_fraction=brownout_exit_fraction,
        brownout_gamma=brownout_gamma,
        preempt_grace_s=preempt_grace_s,
        interactive_ttft_slo_ms=interactive_ttft_slo_ms,
        max_dispatch_batch=max_dispatch_batch,
        max_debates_in_flight=max_debates_in_flight,
    )


def reset_stats() -> None:
    _state.reset_stats()


def snapshot() -> dict:
    """Stats + config, the ``perf.serve`` / daemon ``stats`` payload."""
    return _state.snapshot()
