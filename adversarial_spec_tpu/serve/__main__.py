"""``python -m adversarial_spec_tpu.serve`` — the daemon entrypoint.

A thin alias for ``debate serve`` (adversarial_spec_tpu/cli.py owns
the flag surface); the module form exists so harnesses can spawn the
daemon without depending on the console-script install.
"""

from __future__ import annotations

import sys

from adversarial_spec_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main(["serve", *sys.argv[1:]]))
