"""Blocking line-JSON client for the serve daemon — the harness half.

Tests, ``tools/chaos_run.py --overload``, and ``bench.py --mode
serve`` all talk to the daemon through this: one unix-socket
connection, requests pipelined freely (the open-loop storm writes its
whole burst before reading a byte), responses collected by request id
until each id's TERMINAL event arrives (serve/protocol.py).
"""

from __future__ import annotations

import socket
import time

from adversarial_spec_tpu.serve import protocol


class ServeClient:
    """One connection to one daemon. Not thread-safe (one harness
    thread per client, like the fleet worker transport)."""

    def __init__(self, socket_path: str, timeout_s: float = 30.0) -> None:
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(timeout_s)
        self.sock.connect(str(socket_path))
        self._buf = b""
        self._seq = 0
        # Events that arrived while waiting for a different id.
        self._pending: dict[str, list[dict]] = {}

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # -- framing -----------------------------------------------------------

    def send(self, obj: dict) -> str:
        """Write one request line; assigns an id when missing. Returns
        the request id."""
        if not obj.get("id"):
            self._seq += 1
            obj = {**obj, "id": f"c{self._seq:05d}"}
        self.sock.sendall(protocol.encode(obj))
        return obj["id"]

    def recv(self, timeout_s: float | None = None) -> dict | None:
        """Read one event line (None on clean EOF)."""
        if timeout_s is not None:
            self.sock.settimeout(timeout_s)
        while b"\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                return None
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return protocol.decode(line)

    # -- request/response --------------------------------------------------

    def collect(self, req_id: str, timeout_s: float = 30.0) -> list[dict]:
        """Every event for ``req_id`` through its terminal event.
        Events for OTHER ids seen along the way are buffered, so
        pipelined requests can be collected in any order."""
        got = self._pending.pop(req_id, [])
        if got and got[-1].get("event") in protocol.TERMINAL_EVENTS:
            return got
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"no terminal event for {req_id!r} within {timeout_s}s"
                )
            ev = self.recv(timeout_s=remaining)
            if ev is None:
                raise ConnectionError(
                    f"daemon closed before {req_id!r} resolved"
                )
            eid = ev.get("id", "")
            if eid == req_id:
                got.append(ev)
                if ev.get("event") in protocol.TERMINAL_EVENTS:
                    return got
            else:
                self._pending.setdefault(eid, []).append(ev)

    def call(self, obj: dict, timeout_s: float = 30.0) -> dict:
        """One request, one terminal event (streams discarded into the
        returned list's tail callers can ignore)."""
        req_id = self.send(obj)
        return self.collect(req_id, timeout_s=timeout_s)[-1]

    # -- conveniences ------------------------------------------------------

    def ping(self) -> dict:
        return self.call({"op": "ping"})

    def stats(self) -> dict:
        return self.call({"op": "stats"})

    def check(self) -> dict:
        return self.call({"op": "check"})

    def drain(self) -> dict:
        return self.call({"op": "drain"})

    def refill(self, tenant: str, tokens: int) -> dict:
        return self.call({"op": "refill", "tenant": tenant, "tokens": tokens})

    def submit_debate(
        self,
        spec: str,
        models: list[str],
        *,
        tenant: str = "t0",
        tier: str = "interactive",
        round_num: int = 1,
        session: str | None = None,
        stream: bool = False,
        max_new_tokens: int | None = None,
    ) -> str:
        """Fire-and-forget submit (the open-loop storm's primitive);
        collect the outcome later with ``collect``."""
        obj: dict = {
            "op": "debate",
            "tenant": tenant,
            "tier": tier,
            "spec": spec,
            "models": models,
            "round": round_num,
        }
        if session:
            obj["session"] = session
        if stream:
            obj["stream"] = True
        if max_new_tokens is not None:
            obj["max_new_tokens"] = max_new_tokens
        return self.send(obj)
