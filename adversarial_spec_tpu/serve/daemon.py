"""The ``advspec serve`` daemon: asyncio front, threaded debate core.

Topology (one process):

- the **asyncio loop** owns the unix socket, connection framing,
  admission decisions (fast, never blocked by the engine), and event
  fan-out back to clients;
- each accepted debate runs ``serve.driver.run_debate`` on a bounded
  **worker-thread pool** (the round driver blocks on engine results by
  design — see serve/gate.py);
- the one **engine pump thread** executes fair-order unit batches on
  the real engine.

Graceful drain (the SIGTERM contract docs/serving.md documents):

1. SIGTERM (or the ``drain`` op) → admissions close; every new
   ``debate`` answers with a typed ``draining`` shed. Dispatch
   CONTINUES.
2. In-flight debates get ``drain_deadline_s`` to finish normally
   (their completions keep journal-committing as they resolve).
3. At the deadline, queued units shed (typed, journal-resumable) and
   running units cancel through the stream-consumer seam — the same
   ``_release_slot`` surgery as every other release, so nothing
   leaks.
4. The daemon writes a drain report (stdout line + optional
   ``--drain-report`` file via the atomic-write discipline) and exits
   0. ``PR 10``'s journal makes even a post-deadline SIGKILL lossless
   for accepted work: completed opponents are durable the moment they
   resolve.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

from adversarial_spec_tpu import fleet as fleet_mod
from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu import serve as serve_mod
from adversarial_spec_tpu.serve import driver, gate, protocol
from adversarial_spec_tpu.serve.gate import EnginePump
from adversarial_spec_tpu.serve.sched import ServeScheduler


# asyncio's default StreamReader limit is 64 KiB; a debate request
# carries its whole spec inline on one line, and real specs are bigger
# than that. 16 MiB bounds a hostile line without dropping good
# clients (reader overruns answer with a typed error, not a
# disconnect).
_LINE_LIMIT = 16 * 1024 * 1024

# Per-connection transport write-buffer high-water mark past which
# best-effort ``stream`` events are SKIPPED for a non-reading client.
# Lossless by construction: every delivery carries the text-so-far (a
# superset of all earlier ones), so the next delivery the client does
# read includes everything skipped — while results/sheds are never
# dropped. Without this, an open-loop storm with stream=True would
# buffer O(n^2) bytes per opponent in the daemon: collapse-by-OOM in
# exactly the overload regime the daemon exists to survive.
_STREAM_BUFFER_HIGH_WATER = 256 * 1024


class ServeDaemon:
    """One serving instance: socket, scheduler, pump, drain machine."""

    def __init__(
        self,
        socket_path: str,
        *,
        sessions_dir: str | None = None,
        drain_report_path: str | None = None,
        report_stdout: bool = False,
    ) -> None:
        self.socket_path = str(socket_path)
        self.sessions_dir = Path(sessions_dir) if sessions_dir else None
        self.drain_report_path = drain_report_path
        # The CLI daemon prints the drain report as its final stdout
        # line (the drills parse it); in-process harness daemons keep
        # stdout clean (bench prints exactly ONE JSON line) and read
        # ``drain_report`` directly.
        self.report_stdout = report_stdout
        self.sched = ServeScheduler()
        self.pump = EnginePump(self.sched)
        self.executor = ThreadPoolExecutor(
            max_workers=serve_mod.config().max_debates_in_flight,
            thread_name_prefix="advspec-serve-debate",
        )
        self._loop: asyncio.AbstractEventLoop | None = None
        self._debate_seq = 0
        self._inflight: set[asyncio.Task] = set()
        self._writers: set[asyncio.StreamWriter] = set()
        self._draining = False
        self._drain_reason = ""
        self._done = asyncio.Event()
        self._t_start = time.monotonic()
        self.drain_report: dict | None = None
        # Built in run() when the fleet is armed with autoscale on:
        # the elasticity control loop (fleet/autoscale.py).
        self.autoscaler = None

    # -- lifecycle ---------------------------------------------------------

    async def run(self, ready: threading.Event | None = None) -> int:
        """Serve until drained. Returns 0 on a clean drain (the CLI's
        exit code)."""
        self._loop = asyncio.get_running_loop()
        self._done = asyncio.Event()
        gate.install(self.sched)
        self.pump.start()
        if fleet_mod.armed() and fleet_mod.config().autoscale:
            from adversarial_spec_tpu.fleet.autoscale import Autoscaler

            self.autoscaler = Autoscaler(fleet_mod.fleet_engine(), self.sched)
            # Couple admission capacity to LIVE membership: scale-out
            # stretches the backlog ceiling and brownout thresholds,
            # so the fleet grows BEFORE the scheduler sheds (the
            # brownout→scale-out ordering docs/serving.md documents).
            self.sched.set_capacity_provider(self.autoscaler.capacity_factor)
            self.autoscaler.start()
        try:
            self._loop.add_signal_handler(
                signal.SIGTERM, self.begin_drain, "sigterm"
            )
            self._loop.add_signal_handler(
                signal.SIGINT, self.begin_drain, "sigint"
            )
        except (NotImplementedError, RuntimeError, ValueError):
            pass  # non-main-thread loops (tests) drain via the op
        server = await asyncio.start_unix_server(
            self._on_connection, path=self.socket_path, limit=_LINE_LIMIT
        )
        if ready is not None:
            ready.set()
        try:
            await self._done.wait()
        finally:
            server.close()
            await server.wait_closed()
            # Shutdown ORDER matters (the drain drill's backlog case:
            # more accepted debates than worker threads). stop() first:
            # it force-drains the queues AND makes every later
            # submit_units resolve drained-on-arrival, so executor-
            # queued debates that start from here unwind immediately
            # instead of blocking forever on a queue nobody serves.
            # Only then wait the executor out, and uninstall the gate
            # LAST — a debate thread must never reach the raw
            # (single-threaded) engine ungated. The autoscaler stops
            # FIRST: no membership change may race the teardown (its
            # shutdown only touches mid-transition replicas; serving
            # founders belong to the fleet engine).
            if self.autoscaler is not None:
                self.autoscaler.shutdown()
            self.sched.stop()
            self.pump.join(timeout=5.0)
            self.executor.shutdown(wait=True)
            gate.uninstall()
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        self._write_drain_report()
        return 0

    def begin_drain(self, reason: str = "drain") -> None:
        """Stop admissions and schedule the deadline task (idempotent;
        callable from signal handlers and the ``drain`` op alike)."""
        if self._draining:
            return
        self._draining = True
        self._drain_reason = reason
        if self.autoscaler is not None:
            self.autoscaler.begin_drain()
        self.sched.begin_drain()
        for w in list(self._writers):
            self._send(w, {"id": "", "event": "draining", "reason": reason})
        assert self._loop is not None
        task = self._loop.create_task(self._drain_task())
        task.add_done_callback(lambda _t: None)

    async def _drain_task(self) -> None:
        cfg = serve_mod.config()
        deadline = time.monotonic() + cfg.drain_deadline_s
        while self._inflight and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        drained_units = 0
        if self._inflight:
            drained_units = self.sched.force_drain()
        # The forced errors resolve fast; give the debate threads a
        # bounded grace to unwind before reporting.
        hard = time.monotonic() + 5.0
        while self._inflight and time.monotonic() < hard:
            await asyncio.sleep(0.02)
        snap = serve_mod.snapshot()
        self.drain_report = {
            "event": "drain_report",
            "reason": self._drain_reason,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            "drained_units_at_deadline": drained_units,
            "inflight_at_exit": len(self._inflight),
            "clean_exit": not self._inflight,
            "stats": snap,
            "scheduler": self.sched.state_snapshot(),
        }
        self._done.set()

    def _write_drain_report(self) -> None:
        report = self.drain_report or {
            "event": "drain_report",
            "reason": self._drain_reason or "stopped",
            "clean_exit": True,
            "stats": serve_mod.snapshot(),
        }
        line = json.dumps(report, separators=(",", ":"), sort_keys=True)
        if self.report_stdout:
            print(line, flush=True)
        if self.drain_report_path:
            obs_mod.atomic_write_text(self.drain_report_path, line + "\n")
        # The daemon's end-of-serve event dump (the critique action's
        # end-of-round discipline): when --events-out is armed, the
        # flight recorder's ring — serve lifecycle transitions, step
        # stream, spans — lands as JSONL for tools/obs_dump.py triage.
        events_out = obs_mod.config().events_out
        if events_out:
            obs_mod.dump_events(events_out)

    # -- connection handling -----------------------------------------------

    def _send(self, writer: asyncio.StreamWriter, obj: dict) -> None:
        if writer.is_closing():
            return
        if obj.get("event") == "stream":
            # Best-effort deliveries are skipped for a client that is
            # not reading (see _STREAM_BUFFER_HIGH_WATER): each stream
            # event is the text-so-far, so the next one it reads
            # carries everything skipped. Terminal events always send.
            try:
                buffered = writer.transport.get_write_buffer_size()
            except (AttributeError, RuntimeError):
                buffered = 0
            if buffered > _STREAM_BUFFER_HIGH_WATER:
                return
        try:
            writer.write(protocol.encode(obj))
        except (ConnectionError, RuntimeError):
            pass

    def _send_threadsafe(self, writer: asyncio.StreamWriter, obj: dict) -> None:
        """Event fan-out from debate/pump threads: hop to the loop."""
        assert self._loop is not None
        try:
            self._loop.call_soon_threadsafe(self._send, writer, obj)
        except RuntimeError:
            pass  # loop already closed mid-drain

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._writers.add(writer)
        try:
            while True:
                try:
                    line = await reader.readline()
                except ConnectionError:
                    break
                except (ValueError, asyncio.LimitOverrunError):
                    # A line past _LINE_LIMIT (StreamReader surfaces
                    # the overrun as ValueError): answer typed, then
                    # close — the stream is mid-line and cannot be
                    # re-framed.
                    self._send(
                        writer,
                        protocol.error_event(
                            "",
                            [f"request line exceeds {_LINE_LIMIT} bytes"],
                        ),
                    )
                    break
                if not line:
                    break
                obj = protocol.decode(line)
                if obj is None:
                    self._send(
                        writer, protocol.error_event("", ["not JSON"])
                    )
                    continue
                problems = protocol.validate_request(obj)
                if problems:
                    self._send(
                        writer,
                        protocol.error_event(
                            str(obj.get("id") or ""), problems
                        ),
                    )
                    continue
                self._dispatch_op(obj, writer)
                await writer.drain()
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except RuntimeError:
                pass

    def _dispatch_op(self, obj: dict, writer: asyncio.StreamWriter) -> None:
        op, req_id = obj["op"], obj["id"]
        if op == "ping":
            self._send(
                writer,
                {
                    "id": req_id,
                    "event": "pong",
                    "draining": self._draining,
                    "protocol": protocol.PROTOCOL_VERSION,
                },
            )
        elif op == "stats":
            self._send(
                writer,
                {
                    "id": req_id,
                    "event": "stats",
                    "serve": serve_mod.snapshot(),
                    "scheduler": self.sched.state_snapshot(),
                    # The admission ledger's live view — backlog tokens,
                    # brownout, capacity — previously in-process-only
                    # (the autoscaler's feed); exposed here so external
                    # scrapers and tools/load_replay.py see the same
                    # pressure the scheduler sheds on.
                    "pressure": self.sched.pressure_snapshot(),
                    "uptime_s": round(time.monotonic() - self._t_start, 3),
                },
            )
        elif op == "check":
            self._send(writer, self._check_event(req_id))
        elif op == "refill":
            remaining = self.sched.refill_quota(
                obj["tenant"], int(obj["tokens"])
            )
            self._send(
                writer,
                {
                    "id": req_id,
                    "event": "ok",
                    "tenant": obj["tenant"],
                    "quota_remaining": remaining,
                },
            )
        elif op == "drain":
            self.begin_drain("drain_op")
            self._send(writer, {"id": req_id, "event": "ok"})
        elif op == "debate":
            self._handle_debate(obj, writer)

    def _check_event(self, req_id: str) -> dict:
        """Allocator/tier invariants across every live inner engine —
        the chaos drill's clean-survivor probe. ONE implementation of
        the walk, shared with the fleet worker's ``check`` op
        (fleet/replica.py check_engine_invariants) so the two probes
        can never drift."""
        from adversarial_spec_tpu.engine import dispatch
        from adversarial_spec_tpu.fleet.replica import check_engine_invariants

        problems: list[str] = []
        checked = 0
        for eng in dispatch.cached_engines():
            checked += 1
            try:
                check_engine_invariants(eng)
            except Exception as e:
                problems.append(f"{type(eng).__name__}: {e}")
        return {
            "id": req_id,
            "event": "check",
            "checked": checked,
            "ok": not problems,
            "problems": problems,
        }

    def _handle_debate(self, obj: dict, writer: asyncio.StreamWriter) -> None:
        req_id = obj["id"]
        self._debate_seq += 1
        debate_id = f"d{self._debate_seq:05d}"
        accept_t = time.monotonic()
        est = driver.estimate_debate_tokens(obj)
        shed = self.sched.try_admit(
            obj["tenant"],
            obj.get("tier", "interactive"),
            debate_id,
            est,
            models=obj.get("models") or (),
            prefill_tokens=driver.estimate_debate_prefill_tokens(obj),
            arrival_s=obs_mod.arrival_now(),
        )
        if shed is not None:
            self._send(
                writer,
                protocol.shed_event(
                    req_id, shed.reason, shed.retry_after_s, shed.message
                ),
            )
            return
        self._send(
            writer,
            {
                "id": req_id,
                "event": "accepted",
                "debate": debate_id,
                "est_tokens": est,
            },
        )
        on_stream = None
        if obj.get("stream"):
            def on_stream(index: int, text: str, _w=writer, _id=req_id):
                self._send_threadsafe(
                    _w,
                    {
                        "id": _id,
                        "event": "stream",
                        "index": index,
                        "text": text,
                    },
                )
        assert self._loop is not None
        task = self._loop.create_task(
            self._await_debate(
                req_id, debate_id, obj, writer, on_stream, accept_t
            )
        )
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _await_debate(
        self, req_id, debate_id, obj, writer, on_stream, accept_t
    ) -> None:
        assert self._loop is not None
        try:
            payload = await self._loop.run_in_executor(
                self.executor,
                lambda: driver.run_debate(
                    obj,
                    self.sched,
                    debate_id=debate_id,
                    journal_dir=self.sessions_dir,
                    on_stream=on_stream,
                    accept_t=accept_t,
                ),
            )
            payload = {"id": req_id, "event": "result", **payload}
        except Exception as e:  # a broken debate must not kill the daemon
            self.sched.finish_debate(debate_id)  # release the reservation
            payload = {
                "id": req_id,
                "event": "result",
                "error": f"{type(e).__name__}: {e}",
                "results": [],
            }
        self._send(writer, payload)
        try:
            await writer.drain()
        except (ConnectionError, RuntimeError):
            pass


def run_daemon(
    socket_path: str,
    *,
    sessions_dir: str | None = None,
    drain_report_path: str | None = None,
) -> int:
    """Blocking entry: serve on ``socket_path`` until drained."""
    daemon = ServeDaemon(
        socket_path,
        sessions_dir=sessions_dir,
        drain_report_path=drain_report_path,
        report_stdout=True,
    )
    return asyncio.run(daemon.run())
