"""One daemon debate, end to end: the reentrant round-driver wrapper.

Each accepted ``debate`` request runs on its own worker thread through
the SAME ``run_round`` the CLI uses — breakers, retries, hedging,
journal replay, trace propagation all included — scoped by:

- a :class:`~adversarial_spec_tpu.serve.gate.Submission` context, so
  every ``chat`` the round issues is scheduled fair-share under the
  request's (tenant, tier) identity;
- a per-debate trace scope (``RoundConfig.trace_scope``), so
  concurrent rounds mint collision-free ids from their own counters;
- an optional per-session round journal: a ``session``-carrying
  request is crash/drain-durable — completed opponents fsync the
  moment they resolve, and resubmitting the same session+spec+round
  replays them with zero engine work (the drain contract's
  "journal-commits in-flight debates").

Breaker authority in the daemon (ISSUE 14 satellite): the PROCESS
registry stays authoritative across every debate — an opponent model
that opened its circuit in one tenant's round is skipped in every
round of every tenant until its cooldown probe, and the registry's
one-probe-at-a-time rule means concurrent tenants cannot each burn a
probe on the same dead model. The per-debate view is SNAPSHOTTED at
round commit into the result payload (``breakers``), which is what a
client persists alongside its session — exactly the role
``SessionState.breakers`` plays for the CLI.
"""

from __future__ import annotations

import time

from adversarial_spec_tpu import serve as serve_mod
from adversarial_spec_tpu.debate import journal as journal_mod
from adversarial_spec_tpu.debate.core import RoundConfig, run_round
from adversarial_spec_tpu.engine.types import SamplingParams
from adversarial_spec_tpu.resilience import breaker as breaker_mod
from adversarial_spec_tpu.serve import gate
from adversarial_spec_tpu.serve.sched import ServeScheduler


def estimate_debate_tokens(payload: dict) -> int:
    """Admission-time cost estimate for a whole debate request: per-
    opponent prompt estimate (spec + template overhead, the 4-chars-
    per-token rule) plus the decode budget, times the pool size."""
    spec = payload.get("spec", "")
    models = payload.get("models", [])
    max_new = int(payload.get("max_new_tokens") or 1024)
    per_opp = max(1, len(spec) // 4) + 256 + max_new
    return per_opp * max(1, len(models))


def estimate_debate_prefill_tokens(payload: dict) -> int:
    """The PREFILL share of the debate estimate (prompt tokens only,
    no decode budget) — the scheduler's per-role backlog split and the
    disaggregated router's handoff threshold both read this scale."""
    spec = payload.get("spec", "")
    models = payload.get("models", [])
    per_opp = max(1, len(spec) // 4) + 256
    return per_opp * max(1, len(models))


def _params_from_payload(payload: dict) -> SamplingParams:
    return SamplingParams(
        max_new_tokens=int(payload.get("max_new_tokens") or 1024),
        greedy=bool(payload.get("greedy", False)),
    )


def run_debate(
    payload: dict,
    sched: ServeScheduler,
    *,
    debate_id: str,
    journal_dir=None,
    on_stream=None,
    accept_t: float | None = None,
) -> dict:
    """Execute one validated ``debate`` request (serve/protocol.py
    schema) and return the result-event payload. Runs on a daemon
    worker thread; MUST release the debate's admission reservation on
    every path (the ``finally`` below) — a leaked reservation is
    permanent phantom backlog."""
    tenant = payload["tenant"]
    tier = payload.get("tier", "interactive")
    spec = payload["spec"]
    models = list(payload["models"])
    round_num = int(payload.get("round") or 1)
    session = payload.get("session") or ""

    journal = None
    if session and journal_mod.env_enabled():
        journal = journal_mod.RoundJournal(session, journal_dir=journal_dir)

    cfg = RoundConfig(
        sampling=_params_from_payload(payload),
        journal=journal,
        # Fleet placement + trace scope both key on the most stable
        # identity available: the client's session when given (resume
        # must land on the same replica AND replay the same journal),
        # else the daemon-assigned debate id.
        debate_id=session or debate_id,
        trace_scope=session or debate_id,
    )

    # TTFT is measured from ADMISSION (``accept_t``, stamped by the
    # daemon the moment the debate was accepted), not from when a
    # worker thread got free: the executor queue wait is latency the
    # client pays and the SLO gate must see.
    sub = gate.Submission(
        tenant=tenant,
        tier=tier,
        debate=debate_id,
        on_stream=on_stream,
        t0=accept_t,
    )
    t0 = accept_t if accept_t is not None else time.monotonic()
    try:
        with gate.submission(sub):
            result = run_round(spec, models, round_num=round_num, cfg=cfg)
        wall_s = time.monotonic() - t0
        if journal is not None and all(r.ok for r in result.responses):
            # Round-commit only a FULLY-resolved round: a round that
            # lost opponents to quota sheds or a drain stays
            # uncommitted, so a resubmit replays the durable
            # completions and re-issues only the gap.
            try:
                journal.log_round_commit(round_num, result.all_agreed)
            except Exception:
                pass  # durability is best-effort by contract
        breakers = breaker_mod.default_registry()
        return {
            "all_agreed": result.all_agreed,
            "round": round_num,
            "trace_id": result.trace_id,
            "tenant": tenant,
            "tier": tier,
            "wall_s": round(wall_s, 6),
            "ttft_s": round(
                sub.ttft_s if sub.ttft_s is not None else wall_s, 6
            ),
            "journal_served": int(
                result.tracer.counters.get("journal.served", 0)
            ),
            "results": [
                {
                    "model": r.model,
                    "agreed": r.agreed,
                    "response": r.critique,
                    "spec": r.revised_spec,
                    "error": r.error,
                    "span_id": r.span_id,
                    "input_tokens": r.usage.input_tokens,
                    "output_tokens": r.usage.output_tokens,
                    "cached_tokens": r.usage.cached_tokens,
                }
                for r in result.responses
            ],
            # The per-debate breaker snapshot at round commit: the
            # client's durable view of which opponents are tripped
            # (process breakers stay authoritative daemon-side).
            "breakers": breakers.snapshot_for_resume(),
            "serve": serve_mod.snapshot(),
        }
    finally:
        sched.finish_debate(debate_id)
