"""The scheduler-gated engine seam + the single engine pump.

The round driver (``debate.core.run_round``) stays completely unaware
of the daemon: it calls ``get_engine(model).chat(...)`` exactly as the
CLI does. When the daemon is serving, ``dispatch.get_engine`` routes
through :func:`wrap`, which hands back a :class:`GatedEngine` — same
``Engine`` protocol, but ``chat`` splits the batch into per-opponent
:class:`~adversarial_spec_tpu.serve.sched.Unit`\\ s, submits them to
the fair-share scheduler, and blocks until each resolves. Concurrent
debates therefore interleave at OPPONENT-REQUEST granularity into the
one shared engine, in stride-fair order — the scheduler's contract,
not the accident of thread timing.

The :class:`EnginePump` is the only thread that touches the inner
engine (the batcher is not thread-safe by design — concurrency lives
in the batch dimension, not in Python threads): it pulls fair-order
batches from the scheduler, composes the delivery consumer below, runs
the ONE engine dispatch, and reports completions back.

The composed stream consumer is where three concerns meet on the PR 9
streaming seam, in precedence order:

1. the client's per-opponent stream events (``on_stream``, best
   effort — a broken client callback disables itself, never the
   decode);
2. the round driver's own consumer (early-convergence cancel: its
   ``False`` is a CLEAN cancel, so it is checked FIRST and recorded as
   ``cancelled_by_caller`` — a cancel and a preemption must never be
   confused);
3. the preemption policy (``ServeScheduler.should_preempt``): a batch
   unit holding the engine while interactive work waits returns False,
   the batcher releases the slot through the shared ``_release_slot``
   surgery (partial KV salvaged), and the scheduler re-queues the
   unit.

Outside a submission context (``validate`` preflights, plain library
calls in the daemon process) the gate is a transparent passthrough.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from adversarial_spec_tpu.engine import streaming as stream_mod
from adversarial_spec_tpu.engine.types import Completion
from adversarial_spec_tpu.resilience import faults as faults_mod
from adversarial_spec_tpu.serve.sched import ServeScheduler, Unit


class Submission:
    """Everything the gate needs to know about the debate whose round
    driver is currently calling ``chat`` on this thread: identity for
    the scheduler (tenant/tier/debate), the client stream callback,
    and the TTFT probe (first delivery or first completion, whichever
    lands first — the drill's interactive-SLO measurement)."""

    __slots__ = ("tenant", "tier", "debate", "on_stream", "t0", "ttft_s")

    def __init__(
        self,
        tenant: str,
        tier: str = "interactive",
        debate: str = "",
        on_stream=None,
        t0: float | None = None,
    ) -> None:
        self.tenant = tenant
        self.tier = tier
        self.debate = debate
        self.on_stream = on_stream
        self.t0 = time.monotonic() if t0 is None else t0
        self.ttft_s: float | None = None

    def note_first_token(self) -> None:
        if self.ttft_s is None:
            self.ttft_s = max(0.0, time.monotonic() - self.t0)


_local = threading.local()
_sched: ServeScheduler | None = None
_gates: dict[int, "GatedEngine"] = {}


def install(sched: ServeScheduler) -> None:
    """Arm the gate: from now on ``dispatch.get_engine`` wraps every
    engine it returns (one gate per inner engine, cached so
    ``run_round``'s group-by-engine-identity still batches)."""
    global _sched
    _sched = sched
    _gates.clear()


def uninstall() -> None:
    global _sched
    _sched = None
    _gates.clear()


def armed() -> bool:
    return _sched is not None


def wrap(inner):
    """The dispatch seam: the gated view of ``inner`` while serving,
    ``inner`` itself otherwise."""
    if _sched is None or isinstance(inner, GatedEngine):
        return inner
    gate = _gates.get(id(inner))
    if gate is None:
        gate = _gates[id(inner)] = GatedEngine(inner, _sched)
    return gate


@contextmanager
def submission(sub: Submission):
    """Scope a debate thread's ``chat`` calls to its submission
    identity (thread-local, like the trace ambient — each daemon
    debate thread carries its own)."""
    prev = getattr(_local, "sub", None)
    _local.sub = sub
    try:
        yield sub
    finally:
        _local.sub = prev


def current_submission() -> Submission | None:
    return getattr(_local, "sub", None)


class GatedEngine:
    """Engine-protocol adapter: ``chat`` becomes submit-and-wait on
    the fair-share scheduler; everything else passes through."""

    def __init__(self, inner, sched: ServeScheduler) -> None:
        self._inner = inner
        self._sched = sched

    def validate(self, model: str) -> str | None:
        return self._inner.validate(model)

    def chat(self, requests, params, consumer=None):
        sub = current_submission()
        if sub is None:
            # Transparent outside a submission scope (preflights,
            # library callers in the daemon process).
            if consumer is not None and stream_mod.consumer_supported(
                self._inner
            ):
                return self._inner.chat(requests, params, consumer=consumer)
            return self._inner.chat(requests, params)
        units = [
            Unit(
                debate=sub.debate,
                tenant=sub.tenant,
                tier=sub.tier,
                index=i,
                request=req,
                params=params,
                engine=self._inner,
                consumer=consumer,
                on_stream=sub.on_stream,
                submission=sub,
            )
            for i, req in enumerate(requests)
        ]
        self._sched.submit_units(units)
        for u in units:
            u.done.wait()
            if u.submission is not None:
                # No streaming armed: TTFT falls back to the first
                # resolved opponent.
                u.submission.note_first_token()
        return [u.completion for u in units]


def _composed_consumer(batch: list[Unit]):
    """One consumer for one engine dispatch, multiplexing the batch's
    units by row index. See the module docstring for the precedence
    contract."""
    def consume(row: int, text: str) -> bool:
        u = batch[row]
        if u.submission is not None:
            u.submission.note_first_token()
        if u.on_stream is not None:
            try:
                u.on_stream(u.index, text)
            except Exception:
                # A broken client callback disables itself; the decode
                # and the round are unharmed (the batcher's own
                # containment rule, applied one layer up).
                u.on_stream = None
        if u.consumer is not None:
            try:
                keep = bool(u.consumer(u.index, text))
            except Exception:
                keep = True
                u.consumer = None
            if not keep:
                u.cancelled_by_caller = True
                return False
        if u.preempt_requested or (
            _sched is not None and _sched.should_preempt(u)
        ):
            u.preempt_requested = True
            return False
        return True

    return consume


class EnginePump(threading.Thread):
    """The one thread that runs the inner engine: pull a fair-order
    batch, dispatch it, report completions. Exits when the scheduler
    stops (post-drain)."""

    def __init__(self, sched: ServeScheduler) -> None:
        super().__init__(name="advspec-serve-pump", daemon=True)
        self._sched = sched

    def run(self) -> None:
        while True:
            batch = self._sched.next_batch(timeout=0.1)
            if batch is None:
                return
            if not batch:
                continue
            self._execute(batch)

    def _execute(self, batch: list[Unit]) -> None:
        engine = batch[0].engine
        requests = [u.request for u in batch]
        params = batch[0].params
        try:
            if stream_mod.config().enabled and stream_mod.consumer_supported(
                engine
            ):
                comps = engine.chat(
                    requests, params, consumer=_composed_consumer(batch)
                )
            else:
                comps = engine.chat(requests, params)
        except Exception as e:  # the engine seam's containment rule
            kind = faults_mod.classify(e)
            faults_mod.record(kind, "serve_dispatch")
            comps = [
                Completion(error=str(e), transient=kind.transient)
                for _ in batch
            ]
        if len(comps) != len(batch):
            comps = list(comps) + [
                Completion(error="engine returned short batch")
                for _ in range(len(batch) - len(comps))
            ]
        # Drain-cancelled units resolve as drained (no re-queue); the
        # rest route through the normal completion path.
        if self._sched.draining and any(
            u.preempt_requested and c.cancelled and not u.cancelled_by_caller
            for u, c in zip(batch, comps)
        ):
            normal: list[tuple[Unit, Completion]] = []
            for u, c in zip(batch, comps):
                if (
                    u.preempt_requested
                    and c.cancelled
                    and not u.cancelled_by_caller
                ):
                    self._sched.drain_cancelled(u, c)
                else:
                    normal.append((u, c))
            if normal:
                self._sched.on_dispatch_complete(
                    [u for u, _ in normal], [c for _, c in normal]
                )
            return
        self._sched.on_dispatch_complete(batch, comps)
