"""Serve-daemon wire protocol: line-delimited JSON over a local socket.

Every message is ONE JSON object on ONE line (the journal's and fleet
worker's framing — a torn line is confined to itself). Clients write
request lines; the daemon answers each with one or more event lines
tagged with the request's client-assigned ``id``, terminating in
exactly one TERMINAL event. Requests may pipeline freely on one
connection (the overload drill's open-loop storm writes its whole
burst before reading a byte).

Request ops (``REQUEST_FIELDS`` is the schema contract, validated by
``validate_request`` before anything touches the scheduler):

- ``debate`` — run one critique round: tenant, tier, spec, models,
  round, optional session (arms the PR 10 crash-safe journal: a
  drain-interrupted debate is resumable by resubmitting the same
  session+spec+round), optional per-request stream flag and sampling
  overrides.
- ``ping`` / ``stats`` / ``check`` — liveness, the ``perf.serve``-
  shaped counters + scheduler state, and engine allocator/tier
  invariants (the chaos drill's clean-survivor probe).
- ``refill`` — add tokens to a tenant's quota (the admission ledger).
- ``drain`` — begin the graceful drain (the SIGTERM path, reachable
  over the wire for harnesses that cannot signal).

Response events (``RESPONSE_EVENTS``): ``accepted`` (admission took
the debate; carries the daemon-assigned debate id), ``shed`` (typed
refusal: a ``SHED_REASONS`` member + ``retry_after_s`` — the
load-shed contract: a storm is answered, never absorbed), ``stream``
(one opponent's text-so-far, when streaming was requested),
``result`` (terminal: the round payload), ``error`` (terminal:
malformed request), ``pong`` / ``stats`` / ``check`` / ``ok``
(terminal acks), ``draining`` (broadcast when drain begins).
"""

from __future__ import annotations

import json

PROTOCOL_VERSION = 1

REQUEST_OPS = ("debate", "ping", "stats", "check", "refill", "drain")

# Typed load-shed reasons (the admission contract docs/serving.md
# documents; every refusal names exactly one):
#
# - queue_full — the tenant's outstanding-debate queue is at cap;
# - backlog   — the estimated token backlog is at cap (global);
# - quota     — the tenant's token quota is exhausted;
# - brownout  — batch-tier admissions are paused during brownout;
# - draining  — the daemon is draining; no new admissions.
SHED_REASONS = ("queue_full", "backlog", "quota", "brownout", "draining")

TIERS = ("interactive", "batch")

RESPONSE_EVENTS = (
    "accepted",
    "shed",
    "stream",
    "result",
    "error",
    "pong",
    "stats",
    "check",
    "ok",
    "draining",
)

# Events that END a request's response stream: after one of these, no
# further event carries that request id.
TERMINAL_EVENTS = ("result", "shed", "error", "pong", "stats", "check", "ok")

# op -> {field: (types..., required?)}. ``op``/``id`` are common.
REQUEST_FIELDS: dict[str, dict[str, tuple]] = {
    "debate": {
        "tenant": (str, True),
        "tier": (str, False),  # default "interactive"
        "spec": (str, True),
        "models": (list, True),
        "round": (int, False),  # default 1
        "session": (str, False),  # arms the round journal
        "stream": (bool, False),  # per-opponent text-so-far events
        "max_new_tokens": (int, False),
        "greedy": (bool, False),
    },
    "ping": {},
    "stats": {},
    "check": {},
    "refill": {
        "tenant": (str, True),
        "tokens": (int, True),
    },
    "drain": {},
}


def encode(obj: dict) -> bytes:
    """One message, one line (compact separators — the framing)."""
    return (json.dumps(obj, separators=(",", ":")) + "\n").encode("utf-8")


def decode(line: bytes | str) -> dict | None:
    """Parse one line; None when undecodable (the caller answers with
    a typed ``error`` event, never a crash)."""
    if isinstance(line, bytes):
        line = line.decode("utf-8", errors="replace")
    line = line.strip()
    if not line:
        return None
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        return None
    return obj if isinstance(obj, dict) else None


def validate_request(obj: dict) -> list[str]:
    """Schema-check one decoded request line; returns human-readable
    problems (empty = valid). Malformed requests are answered with an
    ``error`` event carrying these — a bad client must never take the
    daemon down or wedge the scheduler."""
    if not isinstance(obj, dict):
        return [f"not an object: {obj!r}"]
    errors: list[str] = []
    op = obj.get("op")
    if op not in REQUEST_FIELDS:
        return [f"unknown op {op!r} (known: {', '.join(REQUEST_OPS)})"]
    if not isinstance(obj.get("id"), str) or not obj.get("id"):
        errors.append("missing/empty request 'id'")
    fields = REQUEST_FIELDS[op]
    for name, (py, required) in fields.items():
        if name not in obj:
            if required:
                errors.append(f"{op}: missing field {name!r}")
            continue
        v = obj[name]
        ok = isinstance(v, py) and not (
            py is int and isinstance(v, bool)
        )
        if not ok:
            errors.append(
                f"{op}: field {name!r} expected {py.__name__}, "
                f"got {type(v).__name__}"
            )
    for name in obj:
        if name not in fields and name not in ("op", "id"):
            errors.append(f"{op}: unknown field {name!r}")
    if op == "debate":
        tier = obj.get("tier", "interactive")
        if tier not in TIERS:
            errors.append(
                f"debate: unknown tier {tier!r} (known: {', '.join(TIERS)})"
            )
        models = obj.get("models")
        if isinstance(models, list) and (
            not models or not all(isinstance(m, str) and m for m in models)
        ):
            errors.append("debate: 'models' must be a non-empty str list")
    return errors


def shed_event(req_id: str, reason: str, retry_after_s: float, msg: str) -> dict:
    """The typed load-shed refusal — always carries WHEN to come back,
    so a well-behaved client backs off instead of hammering."""
    assert reason in SHED_REASONS, reason
    return {
        "id": req_id,
        "event": "shed",
        "reason": reason,
        "retry_after_s": round(max(0.0, retry_after_s), 3),
        "message": msg,
    }


def error_event(req_id: str, problems: list[str]) -> dict:
    return {
        "id": req_id or "",
        "event": "error",
        "message": "; ".join(problems) or "malformed request",
    }


def self_check() -> list[str]:
    """Protocol schema self-check (a tools/lint_all.py concern via the
    serve tests): every op has a schema, the validator fires on the
    canonical breakages, and the shed vocabulary matches the obs event
    vocabulary (one source of drift less)."""
    problems: list[str] = []
    if set(REQUEST_FIELDS) != set(REQUEST_OPS):
        problems.append("REQUEST_FIELDS keys != REQUEST_OPS")
    good = {
        "op": "debate",
        "id": "c1",
        "tenant": "t0",
        "spec": "## spec",
        "models": ["mock://agree"],
    }
    if validate_request(good):
        problems.append("canonical debate request failed validation")
    for bad, why in (
        ({**good, "op": "nope"}, "unknown op"),
        ({k: v for k, v in good.items() if k != "id"}, "missing id"),
        ({**good, "models": []}, "empty models"),
        ({**good, "tier": "bulk"}, "unknown tier"),
        ({**good, "extra": 1}, "unknown field"),
        ({**good, "round": "one"}, "wrong field type"),
    ):
        if not validate_request(bad):
            problems.append(f"validator failed to fire on {why}")
    try:
        from adversarial_spec_tpu.obs.events import SERVE_TIERS

        if tuple(TIERS) != tuple(SERVE_TIERS):
            problems.append("protocol TIERS != obs SERVE_TIERS")
    except ImportError:
        pass
    return problems
