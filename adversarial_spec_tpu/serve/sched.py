"""Admission control + fair-share scheduling + the request lifecycle.

One class owns the daemon's whole control plane so one lock serializes
it (``ServeScheduler``); the engine itself never blocks on this lock —
the pump (serve/gate.py) holds it only to PICK work, not to run it.

**Admission** (``try_admit``): a new debate is refused with a typed,
retry-after-carrying shed (serve/protocol.py ``SHED_REASONS``) when
its tenant's outstanding-debate queue is at ``max_queue_depth``, when
the estimated token backlog would cross ``max_backlog_tokens``, when
the tenant's token quota is exhausted, when the batch tier is paused
by brownout, or when the daemon is draining. Accepted debates RESERVE
their token estimate in the backlog ledger; completions release it —
so the ledger is the daemon's pressure signal, not a guess.

**Fair share** (``next_batch``): stride scheduling per (tier, tenant).
Each tenant carries a ``pass`` value; the runnable tenant with the
minimum pass is served next, and its pass advances by the ACTUAL
tokens its completion paid (``Usage`` — prefill actually computed plus
decode produced), so a tenant burning long decodes falls behind a
tenant of short ones at exactly the token exchange rate. Tiers are
strict priority: interactive always dispatches before batch — "batch
starves first" is the contract, not an accident. Same-model units at
the head of the fair order coalesce into one dispatch batch (N rows
of one batched decode on the real engine), and when the fair head
would force a WEIGHT SWAP (a different model than the one dispatching
— engine/weightres.py), same-model units deeper in the dispatching
tenant's own queue are pulled forward first: a swap is allowed only
after the resident model's queued work is exhausted. The pull is
bounded to the tenant's own queue, so inter-tenant stride fairness is
untouched (passes advance by tokens paid regardless of intra-tenant
order, and a tenant's opponent units are independent requests).

**Brownout**: when the backlog ledger crosses
``brownout_enter_fraction x max_backlog_tokens`` the daemon DECLARES
degradation before shedding interactive traffic: speculation γ drops
to ``brownout_gamma`` (cheaper steps, lower tail latency) and batch
ADMISSIONS pause (typed ``brownout`` sheds). Batch dispatch is NOT
paused outright — strict tier priority already starves it while
interactive work exists, and batch completions are what drain the
backlog that exits the brownout (pausing them would deadlock the
state machine below its own exit threshold). Hysteresis:
exit below ``brownout_exit_fraction``.

**Preemption**: the policy side of PR 9's ``_release_slot`` surgery.
A batch unit holding the engine while an interactive unit has waited
past its grace is cancelled THROUGH ITS STREAM CONSUMER (the composed
consumer in serve/gate.py consults ``should_preempt`` at every
delivery): the batcher salvages the partial prefix KV into the prefix
cache exactly as an early-cancel does, and the unit re-queues at the
head of its tenant's queue for re-admission. The preempted partial is
recorded; on the deterministic mock the re-run's transcript must carry
it as a byte prefix (pinned).

**Lifecycle** (graftlint's third GL-LIFECYCLE machine): every unit
exits through ONE release surgery — ``_release_unit`` — reached from
``_finish_unit`` / ``_shed_unit`` / ``_preempt_unit`` /
``_drain_unit``; the running-set ledger ``_running`` is written only
by the surgery and the ``_start_unit`` acquisition. The daemon request
lifecycle (accepted → queued → running → finished | shed | preempted |
drained) is emitted as ``ServeEvent``s so ``tools/obs_dump.py`` can
render who was served and who was shed, when.

Deliberately imports no jax — the mock-engine daemon drives this
entire state machine deterministically on CPU.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu import serve as serve_mod
from adversarial_spec_tpu.engine import weightres as weightres_mod
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams
from adversarial_spec_tpu.resilience import lockdep as lockdep_mod
from adversarial_spec_tpu.serve.protocol import SHED_REASONS, TIERS

# Floor for the retry-after estimate's drain rate (tokens/s): before
# the first completion lands there is no measured rate, and a zero
# rate would tell clients to retry never.
_MIN_DRAIN_RATE = 1024.0


def estimate_tokens(request: ChatRequest, params: SamplingParams) -> int:
    """Admission-time cost estimate for one opponent unit: prompt
    tokens (the 4-chars-per-token rule every engine's accounting uses)
    plus the full decode budget — an upper bound on purpose; the
    ledger releases the estimate and charges the actual on
    completion."""
    prompt = (len(request.system) + len(request.user)) // 4
    return max(1, prompt) + max(1, int(params.max_new_tokens))


def estimate_prefill_tokens(request: ChatRequest) -> int:
    """The PREFILL share of the admission estimate (prompt tokens
    only, same 4-chars-per-token rule) — the disaggregated fleet's
    routing threshold input and the prefill-pool backlog signal."""
    return max(1, (len(request.system) + len(request.user)) // 4)


@dataclass(frozen=True)
class ShedDecision:
    """A typed admission refusal: the reason names WHY (a
    ``SHED_REASONS`` member), ``retry_after_s`` names WHEN the backlog
    is expected to have drained enough to try again."""

    reason: str
    retry_after_s: float
    message: str


class Unit:
    """One opponent request from one debate, as the scheduler sees it:
    the unit of fair-share interleave, preemption, and quota
    enforcement. Resolution is a (completion, done-event) pair the
    gate's ``chat`` blocks on."""

    __slots__ = (
        "debate",
        "tenant",
        "tier",
        "index",
        "request",
        "params",
        "engine",
        "consumer",
        "on_stream",
        "submission",
        "est_tokens",
        "enqueued_t",
        "attempts",
        "preempt_requested",
        "cancelled_by_caller",
        "preempt_partials",
        "state",
        "completion",
        "done",
    )

    def __init__(
        self,
        *,
        debate: str,
        tenant: str,
        tier: str,
        index: int,
        request: ChatRequest,
        params: SamplingParams,
        engine,
        consumer=None,
        on_stream=None,
        submission=None,
    ) -> None:
        assert tier in TIERS, tier
        self.debate = debate
        self.tenant = tenant
        self.tier = tier
        self.index = index
        self.request = request
        self.params = params
        self.engine = engine
        self.consumer = consumer
        self.on_stream = on_stream
        self.submission = submission
        self.est_tokens = estimate_tokens(request, params)
        self.enqueued_t = 0.0
        self.attempts = 0
        self.preempt_requested = False
        self.cancelled_by_caller = False
        self.preempt_partials: list[str] = []
        self.state = "created"
        self.completion: Completion | None = None
        self.done = threading.Event()


class ServeScheduler:
    """The daemon's control plane: admission ledger, per-tenant stride
    queues, brownout state machine, and the unit lifecycle. One lock;
    engine execution happens outside it (serve/gate.py)."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._lock = lockdep_mod.make_lock("ServeScheduler._lock")
        self._cond = threading.Condition(self._lock)
        # tier -> tenant -> FIFO of queued units.
        self._queues: dict[str, dict[str, deque[Unit]]] = {
            t: {} for t in TIERS
        }
        # Stride passes per (tier, tenant); a new tenant joins at the
        # tier's current minimum so it cannot claim ancient credit.
        self._passes: dict[tuple[str, str], float] = {}
        # Units currently dispatched to the engine, keyed by id(unit).
        # LIFECYCLE-OWNED: written only by _start_unit (acquisition)
        # and _release_unit (the one release surgery).
        self._running: dict[int, Unit] = {}
        # Admission ledger: per-debate reserved token estimates (the
        # backlog), per-tenant outstanding debate counts, per-tenant
        # quota remainders (armed when config.tenant_quota_tokens > 0).
        self._reserved: dict[str, int] = {}
        # The PREFILL share of each reservation (role-aware elasticity:
        # the autoscaler scales the prefill pool on this sub-ledger,
        # the decode pool on the remainder). Kept beside _reserved,
        # released with it.
        self._reserved_prefill: dict[str, int] = {}
        self._debate_tenant: dict[str, str] = {}
        # Per-active-debate opponent pools (admission metadata): the
        # autoscaler's model-mix observer — a warming replica preloads
        # the hottest models counted here.
        self._debate_models: dict[str, list[str]] = {}
        self._outstanding: dict[str, int] = {}
        self._quota: dict[str, int] = {}
        # Capacity provider (fleet/autoscale.py): a callable returning
        # the routable replica count. The admission backlog cap and the
        # brownout thresholds scale by it — an elastic fleet that just
        # grew ADMITS more instead of browning out; None (the default,
        # and every pre-elastic deployment) keeps the static cap.
        self._capacity_fn = None
        self.brownout = False
        self._prev_gamma: int | None = None
        self.draining = False
        # Past the drain deadline: every unit submitted from now on
        # resolves IMMEDIATELY as drained (a late-starting debate
        # thread must never block on a queue nobody will serve).
        self._drain_forced = False
        self._stopped = False
        # Measured drain rate for retry-after estimates.
        self._charged_tokens = 0
        self._started_t = clock()

    # -- small helpers (callers hold the lock unless noted) ----------------

    def _backlog(self) -> int:
        return sum(self._reserved.values())

    def set_capacity_provider(self, fn) -> None:
        """Install (or clear, ``None``) the fleet-capacity observer:
        ``fn()`` returns the routable replica count; the effective
        backlog cap is ``max_backlog_tokens × max(1, fn())``."""
        with self._cond:
            self._capacity_fn = fn
            self._cond.notify_all()

    def _capacity_tokens(self, cfg) -> int:
        """The EFFECTIVE backlog cap: per-replica cap × routable
        replicas. Defensive on the provider — a capacity read must
        never take the admission path down."""
        base = cfg.max_backlog_tokens
        fn = self._capacity_fn
        if fn is None:
            return base
        try:
            factor = max(1, int(fn()))
        except Exception:
            factor = 1
        return base * factor

    def _drain_rate(self) -> float:
        elapsed = max(self._clock() - self._started_t, 1e-3)
        return max(self._charged_tokens / elapsed, _MIN_DRAIN_RATE)

    def _emit(self, op: str, *, tenant: str = "", tier: str = "interactive",
              debate: str = "", index: int = -1, reason: str = "",
              tokens: int = 0, trace_id: str = "", span_id: str = "",
              arrival_s: float = 0.0) -> None:
        if obs_mod.config().enabled:
            obs_mod.hot.serve_op(op).inc()
            obs_mod.hot.serve_backlog.set(float(self._backlog()))
            obs_mod.emit(
                obs_mod.ServeEvent(
                    op=op,
                    tenant=tenant,
                    tier=tier,
                    debate=debate,
                    index=index,
                    reason=reason,
                    tokens=tokens,
                    backlog_tokens=self._backlog(),
                    arrival_s=arrival_s,
                    trace_id=trace_id,
                    span_id=span_id,
                )
            )

    def _quota_remaining(self, tenant: str) -> int | None:
        """None = quotas unarmed (config 0)."""
        base = serve_mod.config().tenant_quota_tokens
        if base <= 0:
            return None
        if tenant not in self._quota:
            self._quota[tenant] = base
        return self._quota[tenant]

    def refill_quota(self, tenant: str, tokens: int) -> int:
        """Add tokens to a tenant's quota; returns the new remainder.
        Wakes the pump: a queued unit whose dispatch was about to shed
        on quota dispatches instead — the refill-race contract."""
        with self._cond:
            remaining = self._quota_remaining(tenant)
            if remaining is None:
                return -1
            self._quota[tenant] = remaining + max(0, int(tokens))
            self._cond.notify_all()
            return self._quota[tenant]

    # -- admission ---------------------------------------------------------

    def try_admit(
        self, tenant: str, tier: str, debate: str, est_tokens: int,
        models: list[str] | tuple[str, ...] = (),
        prefill_tokens: int = 0,
        arrival_s: float = 0.0,
    ) -> ShedDecision | None:
        """Admit one debate (reserving its estimate in the backlog
        ledger) or refuse it with a typed shed. Shed order under
        pressure is the contract docs/serving.md documents: drain >
        brownout (batch only) > queue depth > backlog > quota —
        brownout pauses batch ADMISSIONS one step before the hard caps
        start refusing interactive traffic. The backlog cap scales
        with fleet capacity (``set_capacity_provider``): with an
        elastic fleet, scale-out RAISES it before brownout would
        engage. ``models`` is admission metadata — the debate's
        opponent pool, feeding the autoscaler's model-mix observer."""
        cfg = serve_mod.config()
        with self._cond:
            cap_tokens = self._capacity_tokens(cfg)
            retry = est_tokens / self._drain_rate()
            shed: ShedDecision | None = None
            if self.draining:
                shed = ShedDecision(
                    "draining", retry, "daemon is draining; resubmit to "
                    "the replacement instance"
                )
            elif self.brownout and tier == "batch":
                shed = ShedDecision(
                    "brownout",
                    self._backlog() / self._drain_rate(),
                    "batch tier paused during brownout",
                )
            elif (
                self._outstanding.get(tenant, 0) >= cfg.max_queue_depth
            ):
                shed = ShedDecision(
                    "queue_full",
                    self._backlog() / self._drain_rate()
                    / max(len(self._outstanding), 1),
                    f"tenant {tenant!r} has "
                    f"{self._outstanding.get(tenant, 0)} debates "
                    f"outstanding (cap {cfg.max_queue_depth})",
                )
            elif self._backlog() + est_tokens > cap_tokens:
                shed = ShedDecision(
                    "backlog",
                    (self._backlog() + est_tokens - cap_tokens)
                    / self._drain_rate(),
                    f"estimated backlog {self._backlog()} + {est_tokens} "
                    f"tokens exceeds cap {cap_tokens}",
                )
            else:
                remaining = self._quota_remaining(tenant)
                if remaining is not None and remaining <= 0:
                    shed = ShedDecision(
                        "quota",
                        retry,
                        f"tenant {tenant!r} token quota exhausted "
                        "(refill to resume)",
                    )
            if shed is not None:
                serve_mod.stats.shed_debates += 1
                if obs_mod.config().enabled:
                    obs_mod.hot.serve_shed(shed.reason).inc()
                self._emit(
                    "shed", tenant=tenant, tier=tier, debate=debate,
                    reason=shed.reason, tokens=est_tokens,
                    arrival_s=arrival_s,
                )
                return shed
            self._outstanding[tenant] = self._outstanding.get(tenant, 0) + 1
            self._reserved[debate] = est_tokens
            if prefill_tokens > 0:
                self._reserved_prefill[debate] = min(
                    int(prefill_tokens), est_tokens
                )
            self._debate_tenant[debate] = tenant
            if models:
                self._debate_models[debate] = [str(m) for m in models]
            serve_mod.stats.accepted_debates += 1
            self._emit(
                "accepted", tenant=tenant, tier=tier, debate=debate,
                tokens=est_tokens, arrival_s=arrival_s,
            )
            self._update_brownout()
            return None

    def finish_debate(self, debate: str) -> None:
        """Debate-level bookkeeping at round end (the driver calls this
        after ``run_round`` returns, success or not): the residual
        reservation releases, the tenant's outstanding count drops, and
        freed capacity may exit brownout."""
        with self._cond:
            if debate not in self._debate_tenant:
                return  # idempotent: already finished (or never admitted)
            self._reserved.pop(debate, None)
            self._reserved_prefill.pop(debate, None)
            self._debate_models.pop(debate, None)
            tenant = self._debate_tenant.pop(debate, "")
            if tenant:
                self._outstanding[tenant] = max(
                    0, self._outstanding.get(tenant, 0) - 1
                )
            serve_mod.stats.completed_debates += 1
            self._emit("finished", tenant=tenant, debate=debate)
            self._update_brownout()
            self._cond.notify_all()

    # -- queueing + fair-share pick ----------------------------------------

    def submit_units(self, units: list[Unit]) -> None:
        """Queue opponent units for fair-share dispatch (the gate's
        ``chat`` calls this from the debate thread, then blocks on the
        units' done events)."""
        now = self._clock()
        with self._cond:
            if self._drain_forced or self._stopped:
                # The drain deadline passed (or the scheduler stopped):
                # resolve immediately — queueing would strand the
                # submitting debate thread on a queue nobody serves
                # (ungated raw-engine use after shutdown was the
                # alternative failure; neither is acceptable).
                for u in units:
                    self._drain_unit(u)
                self._cond.notify_all()
                return
            for u in units:
                u.enqueued_t = now
                u.state = "queued"
                q = self._queues[u.tier].setdefault(u.tenant, deque())
                q.append(u)
                key = (u.tier, u.tenant)
                if key not in self._passes:
                    tier_passes = [
                        v for (t, _), v in self._passes.items()
                        if t == u.tier
                    ]
                    self._passes[key] = min(tier_passes) if tier_passes else 0.0
                self._emit(
                    "queued", tenant=u.tenant, tier=u.tier,
                    debate=u.debate, index=u.index, tokens=u.est_tokens,
                    trace_id=u.request.trace_id, span_id=u.request.span_id,
                )
            self._cond.notify_all()

    def _pick_tenant(self, tier: str) -> str | None:
        """The runnable tenant with the minimum stride pass."""
        tenants = [
            t for t, q in self._queues[tier].items() if q
        ]
        if not tenants:
            return None
        return min(tenants, key=lambda t: (self._passes[(tier, t)], t))

    def _pop_runnable(self) -> Unit | None:
        """Pop the next unit in fair order: interactive strictly before
        batch, min-pass tenant within the tier. Quota-exhausted units
        shed HERE (dispatch-time enforcement: exhaustion mid-round
        sheds the remaining opponents; the round still commits)."""
        for tier in TIERS:  # ("interactive", "batch"): strict priority
            while True:
                tenant = self._pick_tenant(tier)
                if tenant is None:
                    break
                unit = self._queues[tier][tenant].popleft()
                remaining = self._quota_remaining(tenant)
                if remaining is not None and remaining <= 0:
                    self._shed_unit(
                        unit, "quota",
                        f"tenant {tenant!r} token quota exhausted "
                        "mid-round (refill to resume)",
                    )
                    continue
                return unit
        return None

    def next_batch(self, timeout: float = 0.1) -> list[Unit] | None:
        """The pump's pick: the fair-order head unit plus any same-
        model/same-params units that follow it in fair order, up to
        ``max_dispatch_batch`` (N rows of one batched decode on the
        real engine). Returns [] on timeout (pump re-polls), None once
        the scheduler is stopped (pump exits)."""
        cfg = serve_mod.config()
        with self._cond:
            first = self._pop_runnable()
            while first is None:
                if self._stopped:
                    return None
                if not self._cond.wait(timeout=timeout):
                    return []
                first = self._pop_runnable()
            batch = [first]
            while len(batch) < cfg.max_dispatch_batch:
                nxt = self._peek_matching(first)
                if nxt is None:
                    # The fair head would force a model swap: pull
                    # same-model work forward from the dispatching
                    # tenant's own queue before allowing it.
                    nxt = self._steal_same_model(first)
                if nxt is None:
                    break
                batch.append(nxt)
            for u in batch:
                self._start_unit(u)
            return batch

    def _peek_matching(self, first: Unit) -> Unit | None:
        """Pop the NEXT fair-order unit only when it can ride the same
        engine dispatch (same engine, model, params): fairness is never
        skipped around — a non-matching fair head ends the batch."""
        tenant = self._pick_tenant(first.tier)
        if tenant is None:
            return None
        q = self._queues[first.tier][tenant]
        head = q[0]
        if (
            head.engine is first.engine
            and head.request.model == first.request.model
            and head.params == first.params
        ):
            remaining = self._quota_remaining(tenant)
            if remaining is not None and remaining <= 0:
                return None  # quota shed happens on its own pick
            return q.popleft()
        return None

    def _steal_same_model(self, first: Unit) -> Unit | None:
        """Weight-swap-aware coalescing (engine/weightres.py): when the
        next fair-order unit runs a DIFFERENT model, scan the
        dispatching tenant's own queue for a same-(engine, model,
        params) unit and pull it into this dispatch — same-model
        opponent units coalesce before a swap is allowed. Scoped to
        ``first``'s own (tier, tenant) queue so stride fairness between
        tenants is untouched; counted into ``perf.weights``
        (``coalesced_units``) so the reorder is declared, not
        inferred."""
        if not weightres_mod.config().enabled:
            return None
        remaining = self._quota_remaining(first.tenant)
        if remaining is not None and remaining <= 0:
            return None
        q = self._queues[first.tier].get(first.tenant)
        if not q:
            return None
        for i, unit in enumerate(q):
            if (
                unit.engine is first.engine
                and unit.request.model == first.request.model
                and unit.params == first.params
            ):
                del q[i]
                weightres_mod.stats.coalesced_units += 1
                return unit
        return None

    def _start_unit(self, unit: Unit) -> None:
        """Acquisition: the only writer of ``_running`` besides the
        release surgery."""
        unit.state = "running"
        unit.attempts += 1
        self._running[id(unit)] = unit
        serve_mod.stats.units_dispatched += 1
        if obs_mod.config().enabled:
            obs_mod.hot.serve_queue_wait.observe(
                max(0.0, self._clock() - unit.enqueued_t)
            )
        self._emit(
            "running", tenant=unit.tenant, tier=unit.tier,
            debate=unit.debate, index=unit.index, tokens=unit.est_tokens,
            trace_id=unit.request.trace_id, span_id=unit.request.span_id,
        )

    # -- preemption policy -------------------------------------------------

    def should_preempt(self, unit: Unit) -> bool:
        """Policy: cancel this RUNNING batch unit when an interactive
        unit has out-waited its grace (the composed stream consumer
        consults this at every delivery — the engine's own delivery
        cadence is the polling clock, no timers). Interactive units are
        never preempted."""
        if unit.tier != "batch":
            return False
        cfg = serve_mod.config()
        grace = cfg.preempt_grace_s
        if cfg.interactive_ttft_slo_ms > 0.0 and grace <= 0.0:
            # Preempt BEFORE the breach: half the TTFT budget.
            grace = cfg.interactive_ttft_slo_ms / 1000.0 / 2.0
        now = self._clock()
        with self._lock:
            for q in self._queues["interactive"].values():
                if q and now - q[0].enqueued_t >= grace:
                    return True
        return False

    # -- completion + the lifecycle surgeries ------------------------------

    def on_dispatch_complete(
        self, batch: list[Unit], completions: list[Completion]
    ) -> None:
        """The pump reports one engine dispatch's outcome: charge the
        stride passes and quotas with the ACTUAL tokens paid, then
        route every unit through its lifecycle exit."""
        with self._cond:
            for unit, comp in zip(batch, completions):
                u = comp.usage
                paid = max(
                    0,
                    (u.input_tokens - u.cached_tokens) + u.output_tokens,
                )
                key = (unit.tier, unit.tenant)
                self._passes[key] = self._passes.get(key, 0.0) + paid
                remaining = self._quota_remaining(unit.tenant)
                if remaining is not None:
                    self._quota[unit.tenant] = remaining - paid
                self._charged_tokens += paid
                serve_mod.stats.tokens_charged += paid
                if (
                    comp.cancelled
                    and unit.preempt_requested
                    and not unit.cancelled_by_caller
                ):
                    self._preempt_unit(unit, comp)
                else:
                    self._finish_unit(unit, comp)
            self._update_brownout()
            self._cond.notify_all()

    def _finish_unit(self, unit: Unit, comp: Completion) -> None:
        """Exit: normal resolution (includes caller-cancelled units —
        an early-convergence cancel is a CLEAN result)."""
        serve_mod.stats.units_completed += 1
        self._release_unit(unit, "finished", comp)

    def _shed_unit(self, unit: Unit, reason: str, msg: str) -> None:
        """Exit: typed mid-round shed (quota exhaustion at dispatch).
        The unit resolves with a NON-transient error completion so the
        round driver records the failure and commits the round instead
        of burning its retry ladder on a policy decision."""
        assert reason in SHED_REASONS, reason
        serve_mod.stats.units_shed += 1
        if obs_mod.config().enabled:
            obs_mod.hot.serve_shed(reason).inc()
        self._release_unit(
            unit,
            "shed",
            Completion(error=f"shed ({reason}): {msg}", transient=False),
            reason=reason,
        )

    def _preempt_unit(self, unit: Unit, comp: Completion) -> None:
        """Exit + re-entry: a policy-cancelled batch unit releases
        through the surgery (its engine slot already released through
        the batcher's ``_release_slot`` with partial KV salvaged), then
        re-queues at the HEAD of its tenant's queue so it resumes as
        soon as interactive pressure clears. The partial transcript is
        kept — the mock re-run must reproduce it as a byte prefix."""
        serve_mod.stats.units_preempted += 1
        serve_mod.stats.preempted_partial_tokens += comp.usage.output_tokens
        unit.preempt_partials.append(comp.text)
        self._release_unit(unit, "preempted", None, reason="tier_pressure")
        unit.preempt_requested = False
        unit.state = "queued"
        unit.enqueued_t = self._clock()
        self._queues[unit.tier].setdefault(
            unit.tenant, deque()
        ).appendleft(unit)
        serve_mod.stats.units_readmitted += 1
        self._emit(
            "queued", tenant=unit.tenant, tier=unit.tier,
            debate=unit.debate, index=unit.index, reason="readmitted",
            trace_id=unit.request.trace_id, span_id=unit.request.span_id,
        )

    def _drain_unit(self, unit: Unit) -> None:
        """Exit: drain-deadline shed of a queued unit. The error is
        non-transient (no retry ladder) and the debate's journal keeps
        every ALREADY-completed opponent durable — resubmitting the
        same session+spec+round replays them with zero engine work."""
        serve_mod.stats.units_drained += 1
        self._release_unit(
            unit,
            "drained",
            Completion(
                error="drained: daemon shutting down (journal-committed "
                "opponents replay on resubmit)",
                transient=False,
            ),
            reason="draining",
        )

    def _release_unit(
        self,
        unit: Unit,
        outcome: str,
        comp: Completion | None,
        reason: str = "",
    ) -> None:
        """THE release surgery (GL-LIFECYCLE machine 3): every unit
        exit funnels through here — running-set removal, backlog
        release, lifecycle event, and resolution of the gate's wait.
        ``comp`` None (preemption) releases WITHOUT resolving: the
        unit re-queues and its reservation survives until it truly
        resolves. Caller holds the lock."""
        self._running.pop(id(unit), None)
        if comp is not None:
            if unit.debate in self._reserved:
                self._reserved[unit.debate] = max(
                    0, self._reserved[unit.debate] - unit.est_tokens
                )
            unit.state = outcome
            unit.completion = comp
        else:
            unit.state = outcome
        self._emit(
            outcome, tenant=unit.tenant, tier=unit.tier,
            debate=unit.debate, index=unit.index, reason=reason,
            tokens=(comp.usage.output_tokens if comp is not None else 0),
            trace_id=unit.request.trace_id, span_id=unit.request.span_id,
        )
        if comp is not None:
            unit.done.set()

    # -- brownout ----------------------------------------------------------

    def _update_brownout(self) -> None:
        """Hysteresis state machine over the backlog ledger. Entering
        lowers speculation γ (the declared degradation) and pauses
        batch admissions; exiting restores γ. Caller holds the lock.
        Thresholds are fractions of the EFFECTIVE capacity
        (``_capacity_tokens``): a scale-out that lands mid-brownout
        raises the exit threshold past the backlog and the next
        admission/finish exits brownout — capacity arriving IS the
        recovery path, one notch before shedding ever starts."""
        cfg = serve_mod.config()
        backlog = self._backlog()
        cap_tokens = self._capacity_tokens(cfg)
        if (
            not self.brownout
            and backlog >= cfg.brownout_enter_fraction * cap_tokens
        ):
            self.brownout = True
            serve_mod.stats.brownout_entries += 1
            self._prev_gamma = self._set_gamma(cfg.brownout_gamma)
            self._emit("brownout_enter", tokens=backlog)
        elif (
            self.brownout
            and backlog <= cfg.brownout_exit_fraction * cap_tokens
        ):
            self.brownout = False
            serve_mod.stats.brownout_exits += 1
            if self._prev_gamma is not None:
                self._set_gamma(self._prev_gamma)
                self._prev_gamma = None
            self._emit("brownout_exit", tokens=backlog)

    @staticmethod
    def _set_gamma(gamma: int) -> int | None:
        """Swap the process speculation γ; returns the previous value
        (None when the spec module is unavailable — brownout is then γ
        only in name, still a declared state)."""
        try:
            from adversarial_spec_tpu.engine import spec as spec_mod
        except ImportError:  # pragma: no cover - spec is stdlib-only
            return None
        prev = spec_mod.config().gamma
        spec_mod.configure(gamma=max(1, int(gamma)))
        return prev

    # -- drain + shutdown --------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admissions (typed ``draining`` sheds); dispatch
        CONTINUES so in-flight debates finish — the graceful half of
        the drain contract."""
        with self._cond:
            self.draining = True
            self._cond.notify_all()

    def force_drain(self) -> int:
        """The drain deadline passed: shed every queued unit (typed,
        journal-resumable) and flag every running unit for preemption-
        style cancellation so the pump's current dispatch returns
        promptly. Returns the number of units drained."""
        n = 0
        with self._cond:
            self.draining = True
            self._drain_forced = True
            for tier in TIERS:
                for q in self._queues[tier].values():
                    while q:
                        self._drain_unit(q.popleft())
                        n += 1
            for unit in list(self._running.values()):
                unit.preempt_requested = True
            self._cond.notify_all()
        return n

    def drain_cancelled(self, unit: Unit, comp: Completion) -> None:
        """A running unit cancelled BY force_drain resolves here (the
        pump routes it in): drained, not preempted — no re-queue."""
        with self._cond:
            serve_mod.stats.units_drained += 1
            self._release_unit(
                unit,
                "drained",
                Completion(
                    text=comp.text,
                    error="drained: daemon shutting down mid-decode "
                    "(partial kept; journal-committed opponents replay "
                    "on resubmit)",
                    transient=False,
                    usage=comp.usage,
                ),
                reason="draining",
            )
            self._cond.notify_all()

    def stop(self) -> None:
        """Final shutdown: force-drain whatever remains (queued units
        shed typed, running units flagged for cancel, future submits
        resolve drained on arrival), then stop the pump — no gate
        thread can be left blocked on a queue nobody serves."""
        self.force_drain()
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    # -- introspection -----------------------------------------------------

    def idle(self) -> bool:
        with self._lock:
            return not self._running and not any(
                q for qs in self._queues.values() for q in qs.values()
            )

    def pressure_snapshot(self) -> dict:
        """The autoscaler's observer (fleet/autoscale.py): the backlog
        ledger, the effective capacity it is measured against, the
        pressure flags, the ACTIVE affinity keys (admitted debate ids
        — the least-affine scale-in victim is picked by who primarily
        owns fewest of these), and the model mix (model → active-
        debate count, hottest first feeds the warm-replica residency
        preload). One lock acquire; safe from any thread."""
        with self._lock:
            mix: dict[str, int] = {}
            for models in self._debate_models.values():
                for m in models:
                    mix[m] = mix.get(m, 0) + 1
            prefill_backlog = sum(self._reserved_prefill.values())
            return {
                "backlog_tokens": self._backlog(),
                # The per-role split (fleet disaggregation): prefill is
                # the sub-ledger of prompt-token reservations, decode
                # the remainder — the autoscaler sizes each pool off
                # its own half.
                "prefill_backlog_tokens": prefill_backlog,
                "decode_backlog_tokens": max(
                    0, self._backlog() - prefill_backlog
                ),
                "capacity_tokens": self._capacity_tokens(
                    serve_mod.config()
                ),
                "brownout": self.brownout,
                "draining": self.draining,
                "active_keys": list(self._reserved),
                "model_mix": dict(
                    sorted(mix.items(), key=lambda kv: (-kv[1], kv[0]))
                ),
            }

    def state_snapshot(self) -> dict:
        """The ``stats`` protocol op's scheduler view."""
        with self._lock:
            return {
                "backlog_tokens": self._backlog(),
                "brownout": self.brownout,
                "draining": self.draining,
                "running_units": len(self._running),
                "queued_units": {
                    tier: {t: len(q) for t, q in qs.items() if q}
                    for tier, qs in self._queues.items()
                },
                "outstanding_debates": {
                    t: n for t, n in self._outstanding.items() if n
                },
                "quota_remaining": dict(self._quota),
                "drain_rate_tokens_per_s": round(self._drain_rate(), 1),
            }
