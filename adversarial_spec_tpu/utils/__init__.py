"""utils subpackage."""
