"""One-time jax process configuration (platform mirroring + compile cache).

Called lazily from the first jax-touching entry point (engine dispatch,
device introspection) so mock-only CLI flows never pay the jax import.

1. Mirror JAX_PLATFORMS into jax.config before first backend use: some
   environments bootstrap jax at interpreter start (sitecustomize PJRT
   plugins) in a way that snapshots their own platform choice; the user's
   env var is then silently ignored and a CPU-only run can block on an
   unreachable accelerator.
2. Enable the persistent compilation cache. The L5 debate protocol invokes
   the CLI once per round as a fresh process; without the cache every
   round re-pays the full XLA compile of prefill + decode (tens of
   seconds on TPU). The cache keys on program + topology, so round 2+ and
   every later debate reuse round 1's compiles.
"""

from __future__ import annotations

import os
from pathlib import Path

_configured = False


def configure_jax() -> None:
    global _configured
    if _configured:
        return
    _configured = True
    try:
        import jax
    except Exception:
        return  # jax missing/odd build: callers surface real errors

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or str(
        Path.home() / ".cache" / "adversarial-spec-tpu" / "xla-cache"
    )
    for option, value in (
        ("jax_compilation_cache_dir", cache_dir),
        ("jax_persistent_cache_min_compile_time_secs", 1.0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(option, value)
        except Exception:
            pass  # option renamed/absent in this jax version
