"""Tracing and profiling.

SURVEY §5: the reference has no tracing at all (its nearest analog is the
cost tracker), but per-round wall-clock and tokens/sec/chip are this
framework's north-star metric, so tracing is first-class here:

- ``Tracer`` — lightweight span timers building a per-round phase
  breakdown (validate / prefill / decode / parse ...), nestable, with a
  machine-readable report that the CLI attaches to ``--json`` output.
  Spans carry CALL COUNTS (a span entered twice reports both the
  accumulated seconds and how many entries produced them, so averages
  are computable) and a NESTED TREE mirroring the entry stack; tracers
  compose via ``merge()`` — the debate layer's per-opponent spans and
  the engine's per-request spans graft into one report.
- ``maybe_profile`` — wraps a block in a ``jax.profiler`` trace when a
  directory is given (view with TensorBoard / xprof), no-op otherwise.

Kept deliberately pure-Python and allocation-light: a span is two
``time.monotonic`` calls and a dict entry.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


def _tree_node(children: dict, name: str) -> dict:
    node = children.get(name)
    if node is None:
        node = children[name] = {"total_s": 0.0, "count": 0, "children": {}}
    return node


def _merge_tree(dst: dict, src: dict) -> None:
    for name, node in src.items():
        d = _tree_node(dst, name)
        d["total_s"] += node["total_s"]
        d["count"] += node["count"]
        _merge_tree(d["children"], node["children"])


def _round_tree(children: dict) -> dict:
    return {
        name: {
            "total_s": round(node["total_s"], 4),
            "count": node["count"],
            "children": _round_tree(node["children"]),
        }
        for name, node in children.items()
    }


@dataclass
class Tracer:
    """Named wall-clock spans with counters, for one logical operation."""

    spans: dict[str, float] = field(default_factory=dict)
    counters: dict[str, float] = field(default_factory=dict)
    # Entries per span name: spans[k] / span_counts[k] is the average.
    span_counts: dict[str, int] = field(default_factory=dict)
    # Nested span tree mirroring the entry stack ("round" > "chat" ...):
    # {name: {"total_s", "count", "children": {...}}}.
    tree: dict = field(default_factory=dict)
    _t0: float = field(default_factory=time.monotonic)
    _stack: list = field(default_factory=list)

    @contextlib.contextmanager
    def span(self, name: str):
        start = time.monotonic()
        self._stack.append(name)
        path = tuple(self._stack)
        try:
            yield
        finally:
            self._stack.pop()
            self._record_span(name, time.monotonic() - start, path)

    def _record_span(
        self, name: str, seconds: float, path: tuple | None = None
    ) -> None:
        self.spans[name] = self.spans.get(name, 0.0) + seconds
        self.span_counts[name] = self.span_counts.get(name, 0) + 1
        children = self.tree
        for part in path or (name,):
            node = _tree_node(children, part)
            children = node["children"]
        node["total_s"] += seconds
        node["count"] += 1

    def add_span(self, name: str, seconds: float) -> None:
        """Record an externally measured duration as one span entry
        (flat + root of the tree) — for durations produced by another
        layer (per-opponent chat latencies, per-request engine walls)
        that never ran under this tracer's context manager."""
        self._record_span(name, seconds)

    def count(self, name: str, value: float) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + value

    def count_many(self, values: dict[str, float]) -> None:
        """Merge a counter dict (e.g. the resilience subsystem's fault
        counts or breaker transition totals) into this tracer."""
        for name, value in values.items():
            self.count(name, value)

    def merge(self, other: "Tracer", prefix: str = "") -> None:
        """Fold another tracer's spans/counters/tree into this one.
        With ``prefix``, flat keys gain ``prefix/`` and the tree grafts
        under a ``prefix`` node — how the debate layer's per-opponent
        spans and the engine's per-request spans compose into the one
        report the CLI emits."""

        def key(k: str) -> str:
            return f"{prefix}/{k}" if prefix else k

        for k, v in other.spans.items():
            self.spans[key(k)] = self.spans.get(key(k), 0.0) + v
        for k, v in other.span_counts.items():
            self.span_counts[key(k)] = self.span_counts.get(key(k), 0) + v
        for k, v in other.counters.items():
            self.counters[key(k)] = self.counters.get(key(k), 0.0) + v
        if prefix:
            node = _tree_node(self.tree, prefix)
            _merge_tree(node["children"], other.tree)
            node["total_s"] += sum(
                n["total_s"] for n in other.tree.values()
            )
            node["count"] += sum(n["count"] for n in other.tree.values())
        else:
            _merge_tree(self.tree, other.tree)

    def rate(self, tokens_key: str, time_key: str) -> float:
        t = self.spans.get(time_key, 0.0)
        return self.counters.get(tokens_key, 0.0) / t if t > 0 else 0.0

    def report(self) -> dict:
        total = time.monotonic() - self._t0
        out: dict = {
            "total_s": round(total, 4),
            "spans": {k: round(v, 4) for k, v in self.spans.items()},
        }
        if self.span_counts:
            out["span_counts"] = dict(self.span_counts)
        if self.tree:
            out["span_tree"] = _round_tree(self.tree)
        if self.counters:
            out["counters"] = {
                k: round(v, 2) for k, v in self.counters.items()
            }
        return out


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """jax.profiler trace into ``trace_dir`` when given; no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
