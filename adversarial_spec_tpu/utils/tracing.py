"""Tracing and profiling.

SURVEY §5: the reference has no tracing at all (its nearest analog is the
cost tracker), but per-round wall-clock and tokens/sec/chip are this
framework's north-star metric, so tracing is first-class here:

- ``Tracer`` — lightweight span timers building a per-round phase
  breakdown (validate / prefill / decode / parse ...), nestable, with a
  machine-readable report that the CLI attaches to ``--json`` output.
- ``maybe_profile`` — wraps a block in a ``jax.profiler`` trace when a
  directory is given (view with TensorBoard / xprof), no-op otherwise.

Kept deliberately pure-Python and allocation-light: a span is two
``time.monotonic`` calls and a dict entry.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


@dataclass
class Tracer:
    """Named wall-clock spans with counters, for one logical operation."""

    spans: dict[str, float] = field(default_factory=dict)
    counters: dict[str, float] = field(default_factory=dict)
    _t0: float = field(default_factory=time.monotonic)

    @contextlib.contextmanager
    def span(self, name: str):
        start = time.monotonic()
        try:
            yield
        finally:
            self.spans[name] = self.spans.get(name, 0.0) + (
                time.monotonic() - start
            )

    def count(self, name: str, value: float) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + value

    def count_many(self, values: dict[str, float]) -> None:
        """Merge a counter dict (e.g. the resilience subsystem's fault
        counts or breaker transition totals) into this tracer."""
        for name, value in values.items():
            self.count(name, value)

    def rate(self, tokens_key: str, time_key: str) -> float:
        t = self.spans.get(time_key, 0.0)
        return self.counters.get(tokens_key, 0.0) / t if t > 0 else 0.0

    def report(self) -> dict:
        total = time.monotonic() - self._t0
        out: dict = {
            "total_s": round(total, 4),
            "spans": {k: round(v, 4) for k, v in self.spans.items()},
        }
        if self.counters:
            out["counters"] = {
                k: round(v, 2) for k, v in self.counters.items()
            }
        return out


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """jax.profiler trace into ``trace_dir`` when given; no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
