"""Benchmark: critique tokens/sec/chip for a batched multi-opponent decode.

Measures the north-star metric (BASELINE.json): decode throughput of one
debate round's opponent pool run as a single batched generate — 4 opponents
(batch rows) critiquing the SAME spec prompt on one model (shared-prefix
prefill fires), temperature-0.7 sampling with a fixed seed so rows diverge
the way a real round does, synthetic weights (zero egress). Baseline
target: 1500 critique tokens/sec/chip.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "tok/s/chip", "vs_baseline": N/1500}
On CPU fallback (and --long-context, which has no published baseline)
"vs_baseline" is null — a CPU ratio against the TPU north star is
machine noise, not signal.

Robustness: the TPU tunnel in this environment can wedge (backend init
blocks forever), so platform selection happens via a DETACHED subprocess
probe with a file handshake — the probe is never killed (SIGKILLing a
TPU-holding process is what wedges the tunnel for every later process;
NOTES.md round 1); if it doesn't report in time we simply stop waiting,
leave it to finish on its own, and run the bench on CPU with a smaller
config, saying so in the "platform" field rather than hanging the driver.

Modes:
  python bench.py                 # north-star decode bench (one JSON line)
  python bench.py --long-context  # 16k-token prefill bench (one JSON line)
  python bench.py --round-loop    # BASELINE config 4 shape: 5 rounds,
                                  # growing spec, 4 opponents (one line)
  python bench.py --mode prefix   # prefix-KV-cache micro-bench: 3 rounds
                                  # of a growing spec through the
                                  # continuous batcher, cache on vs off;
                                  # also writes BENCH_prefix.json
  python bench.py --mode interleave
                                  # fused+pipelined vs legacy scheduler
                                  # drive loop on a mixed admit-while-
                                  # decoding workload; also writes
                                  # BENCH_interleave.json
  python bench.py --mode obs-overhead
                                  # flight recorder + metrics registry
                                  # emit-path cost over the mock mixed
                                  # workload (CPU host-overhead pin,
                                  # budget < 3%); writes BENCH_obs.json
  python bench.py --mode spec     # per-slot speculation in the batcher:
                                  # growing-spec rounds under the mock
                                  # acceptance model (tokens/step,
                                  # acceptance) + real-batcher spec-on
                                  # vs spec-off walls with identical
                                  # greedy tokens; writes BENCH_spec.json
  python bench.py --mode tier     # tiered KV cache: restart-rehydration
                                  # (disk store) + pressure-thrash
                                  # (host tier) workloads on the CPU
                                  # mock, plus a real-batcher parity/
                                  # retrace phase; writes BENCH_tier.json
  python bench.py --mode cancel   # streaming early-convergence
                                  # cancellation: mock debate rounds
                                  # with verbose early-[AGREE]
                                  # opponents (tokens-saved fraction,
                                  # byte-identical prefixes) + real-
                                  # batcher freed-slot re-admission;
                                  # writes BENCH_cancel.json
  python bench.py --mode recover  # mid-round kill recovery: SIGKILL a
                                  # subprocess round after 2 of 4
                                  # opponents journal, resume, pin the
                                  # fraction of round tokens salvaged
                                  # (journal + KV disk store) vs a cold
                                  # re-run; writes BENCH_recover.json
  python bench.py --mode serve    # advspec serve daemon: capacity
                                  # point (debates/s), overload storm
                                  # (typed sheds, brownout, zero
                                  # accepted loss), SIGTERM drain
                                  # drill; writes BENCH_serve.json
  python bench.py --mode residency
                                  # opponent-pool weight residency: a
                                  # 4-model pool under a 2-model HBM
                                  # budget, host-paging (demote/
                                  # promote) vs naive evict-reload
                                  # weight-load seconds, swap-overlap
                                  # fraction, byte-identical
                                  # transcripts, zero re-promotion
                                  # recompiles (mock + tiny-real);
                                  # writes BENCH_residency.json
  python bench.py --mode fleet    # replicated engines: aggregate
                                  # mock tokens/s of 3 replicas with
                                  # prefix-affinity routing vs 1
                                  # replica, affinity vs random
                                  # cross-round cache hit-rate, plus
                                  # the replica-kill recovery drill;
                                  # writes BENCH_fleet.json
  python bench.py --mode kernels  # fused serving kernels: interpret-
                                  # mode parity pins (int8/int4 dequant-
                                  # matmul vs XLA, multi-position span
                                  # verify vs dense gather) + real-
                                  # batcher A/B on int4 weights with
                                  # byte-identical transcripts and zero
                                  # unexpected recompiles; writes
                                  # BENCH_kernels.json
  python bench.py --mode elastic  # elastic fleet: accepted-debate
                                  # throughput + p99 TTFT under a
                                  # paced load step, autoscaled
                                  # (floor 1, ceiling 3) vs fixed
                                  # 3-replica fleet at equal chip
                                  # ceiling, plus the lose-nothing
                                  # scale-in drill (byte-identical
                                  # transcripts, zero duplicated
                                  # completions); writes
                                  # BENCH_elastic.json
  python bench.py --mode disagg   # prefill/decode disaggregation:
                                  # decode-side p99 TTFT + accepted-
                                  # debate throughput, role-split fleet
                                  # (2 prefill + 2 decode) vs symmetric
                                  # 4-replica fleet at equal replica
                                  # count on a prefill-heavy workload,
                                  # plus the cross-replica KV handoff
                                  # hit fraction (byte-identical
                                  # transcripts, zero duplicated
                                  # completions); writes
                                  # BENCH_disagg.json
  python bench.py --mode capacity # capacity frontier: seeded open-loop
                                  # trace replay (tools/load_replay.py)
                                  # binary-searched to the SLO breach
                                  # per knob arm (replicas 1 vs 3);
                                  # writes BENCH_capacity.json
  --no-interleave                 # escape hatch for any batcher-driven
                                  # mode: run the legacy serialized loop
                                  # (equivalent to ADVSPEC_INTERLEAVE=0)
  --no-speculative                # escape hatch: plain token-at-a-time
                                  # decode (ADVSPEC_SPECULATIVE=0)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

BASELINE_TOK_S_CHIP = 1500.0
N_OPPONENTS = 4
PROMPT_TOKENS = 1024
DECODE_TOKENS = 256
LONG_CONTEXT_TOKENS = 16384


def _probe_tpu(timeout_s: float = 120.0) -> bool:
    """Can a fresh process initialize the accelerator backend in time?

    Wedge-safe: the probe runs detached and writes its verdict to a
    marker file. On timeout the probe is LEFT RUNNING — a timeout-killed
    TPU process wedges the axon tunnel for the whole session (learned in
    round 1) — and we just proceed on CPU.
    """
    marker_dir = tempfile.mkdtemp(prefix="tpu_probe_")
    marker = os.path.join(marker_dir, "verdict")
    # Atomic handshake: write to a temp name, then rename — the parent
    # can never observe a half-written verdict.
    code = (
        "import jax, os\n"
        "d = jax.devices()\n"
        f"tmp = {marker!r} + '.tmp'\n"
        "open(tmp, 'w').write(d[0].platform)\n"
        f"os.rename(tmp, {marker!r})\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,  # survives us; never signaled
    )
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(marker):
            platform = open(marker).read().strip().lower()
            if platform in ("", "cpu"):
                return False
            # The tunnel is single-client: wait for the probe to release
            # the TPU before the parent initializes its own client. If
            # teardown itself hangs, fall back to CPU (and leave the
            # probe alone — killing it is what wedges the tunnel).
            try:
                proc.wait(timeout=max(10.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                return False
            return True
        if proc.poll() is not None and not os.path.exists(marker):
            return False  # probe died without a verdict (backend error)
        time.sleep(1.0)
    return False  # timed out: leave the probe alone, fall back to CPU


def _bench_model(platform: str):
    """Shared model setup for the decode benches (_run_bench and
    _run_round_loop): size/dtype by platform, dp×tp mesh sharding on
    multi-chip hosts — ONE copy so a mode can't silently drop the mesh
    and misreport 'per chip'."""
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    size = "1b" if platform != "cpu" else "tiny"
    cfg = get_config("llama", size)
    params = T.init_params(
        jax.random.key(0),
        cfg,
        dtype=jnp.bfloat16 if platform != "cpu" else jnp.float32,
    )
    n_devices = len(jax.devices())
    mesh = None
    n_chips = 1
    if platform != "cpu" and n_devices > 1:
        import math as _math

        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        dp = _math.gcd(N_OPPONENTS, n_devices)
        mesh = make_mesh({"dp": dp, "tp": n_devices // dp})
        params = shard_params(mesh, params)
        n_chips = n_devices
    return cfg, params, mesh, n_chips, size


def _run_bench(platform: str) -> dict:
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()  # persistent compile cache: repeat runs skip XLA compiles
    import jax

    from adversarial_spec_tpu.engine.generate import generate

    # Real-accelerator bench uses the 1b llama shape (fits one v5e chip
    # in bf16 with cache headroom); CPU fallback uses the tiny config so
    # the driver always gets a number instead of a multi-hour crawl.
    # The real debate-round shape: every opponent critiques the SAME
    # spec prompt (shared-prefix prefill fires on one chip), and
    # temperature sampling diverges the rows.
    cfg, params, mesh, n_chips, size = _bench_model(platform)
    rng = __import__("random").Random(0)
    prompt = [rng.randrange(3, cfg.vocab_size) for _ in range(PROMPT_TOKENS)]
    prompts = [list(prompt) for _ in range(N_OPPONENTS)]

    kw = dict(
        max_new_tokens=DECODE_TOKENS,
        eos_ids=[],  # synthetic model: measure the full decode length
        temperature=0.7,
        seed=0,
        mesh=mesh,
    )
    # Warmup: compile prefill + decode chunk.
    generate(params, cfg, prompts, **kw)
    # Measured run.
    t0 = time.monotonic()
    result = generate(params, cfg, prompts, **kw)
    wall = time.monotonic() - t0

    tok_s_chip = result.decode_tokens / result.decode_time_s / n_chips
    return {
        "metric": "critique_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        # The 1500 north star is a TPU-chip number; a CPU-fallback ratio
        # against it is machine noise (VERDICT r3), so report null there.
        "vs_baseline": (
            round(tok_s_chip / BASELINE_TOK_S_CHIP, 3)
            if platform != "cpu"
            else None
        ),
        "platform": platform,
        "model": f"llama-{size}",
        "opponents": N_OPPONENTS,
        "prompt_tokens": PROMPT_TOKENS,
        "decode_tokens_per_opponent": DECODE_TOKENS,
        "decode_time_s": round(result.decode_time_s, 3),
        "prefill_time_s": round(result.prefill_time_s, 3),
        "round_wall_s": round(wall, 3),
    }


def _run_long_context(platform: str) -> dict:
    """16k-token prefill (BASELINE config 5's context scale).

    Multi-device meshes prefill sequence-parallel (ring attention over
    sp — parallel/sp.py); single device uses chunked prefill. CPU runs a
    thin model so the 16k×16k attention is tractable; the measurement
    structure is identical either way.
    """
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.engine.generate import generate
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    if platform != "cpu":
        cfg = get_config("llama", "1b", max_seq_len=LONG_CONTEXT_TOKENS + 64)
        dtype = jnp.bfloat16
    else:
        from dataclasses import replace

        cfg = replace(
            get_config("llama", "tiny"),
            n_layers=2,
            max_seq_len=LONG_CONTEXT_TOKENS + 64,
        )
        dtype = jnp.float32
    params = T.init_params(jax.random.key(0), cfg, dtype=dtype)

    rng = __import__("random").Random(1)
    prompt = [
        rng.randrange(3, cfg.vocab_size) for _ in range(LONG_CONTEXT_TOKENS)
    ]

    n_devices = len(jax.devices())
    mesh = None
    mode = "chunked"
    if n_devices > 1:
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        sp = max(d for d in (4, 2, 1) if n_devices % d == 0)
        mesh = make_mesh({"sp": sp, "dp": n_devices // sp})
        params = shard_params(mesh, params)
        mode = f"sp{sp}"

    kw = dict(
        max_new_tokens=8,  # prefill is the measurement; decode is a tail
        eos_ids=[],
        greedy=True,
        mesh=mesh,
        speculative=False,
    )
    generate(params, cfg, [prompt], **kw)  # warmup/compile
    t0 = time.monotonic()
    result = generate(params, cfg, [prompt], **kw)
    wall = time.monotonic() - t0

    prefill_tok_s = LONG_CONTEXT_TOKENS / result.prefill_time_s
    return {
        "metric": "prefill_16k_tokens_per_sec",
        "value": round(prefill_tok_s, 1),
        "unit": "tok/s",
        "vs_baseline": None,  # BASELINE publishes no prefill number
        "platform": platform,
        "mode": mode,
        "model": "llama-1b" if platform != "cpu" else "llama-tiny-2L",
        "context_tokens": LONG_CONTEXT_TOKENS,
        "prefill_time_s": round(result.prefill_time_s, 3),
        "wall_s": round(wall, 3),
    }


def _run_round_loop(platform: str) -> dict:
    """BASELINE config 4's loop shape: 5 critique rounds over one spec,
    4 opponents per round, the spec GROWING by one revision per round
    (each round re-prefills the larger context — the part the one-round
    bench cannot see). Decode throughput is the north-star metric; the
    whole-loop wall time additionally covers the prefill regrowth."""
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()

    from adversarial_spec_tpu.engine.generate import generate

    n_rounds = 5
    revision_tokens = 256  # per round: the synthesized revision delta

    cfg, params, mesh, n_chips, size = _bench_model(platform)
    rng = __import__("random").Random(0)
    spec = [rng.randrange(3, cfg.vocab_size) for _ in range(PROMPT_TOKENS)]

    kw = dict(
        max_new_tokens=DECODE_TOKENS,
        eos_ids=[],
        temperature=0.7,
        seed=0,
        mesh=mesh,
    )
    # Warm up EVERY bucket the loop will hit (prompts pad to power-of-two
    # buckets; round 1's 1024 bucket and rounds 2-5's 2048 bucket are
    # different compiled programs) so the timed loop measures steady
    # state, never an XLA compile.
    largest = spec + [5] * (revision_tokens * (n_rounds - 1))
    generate(params, cfg, [list(largest)] * N_OPPONENTS, **kw)
    generate(params, cfg, [list(spec)] * N_OPPONENTS, **kw)

    decode_tokens = 0
    decode_time = prefill_time = 0.0
    t0 = time.monotonic()
    for _ in range(n_rounds):
        r = generate(
            params, cfg, [list(spec)] * N_OPPONENTS, **kw
        )
        decode_tokens += r.decode_tokens
        decode_time += r.decode_time_s
        prefill_time += r.prefill_time_s
        # Synthesize: the spec grows by one revision's worth of tokens.
        spec = spec + [
            rng.randrange(3, cfg.vocab_size) for _ in range(revision_tokens)
        ]
    wall = time.monotonic() - t0

    tok_s = decode_tokens / decode_time / n_chips
    return {
        "metric": "round_loop_critique_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tok/s/chip",
        "vs_baseline": (
            round(tok_s / BASELINE_TOK_S_CHIP, 3)
            if platform != "cpu"
            else None
        ),
        "platform": platform,
        "model": f"llama-{size}",
        "rounds": n_rounds,
        "opponents": N_OPPONENTS,
        "spec_tokens_start": PROMPT_TOKENS,
        "spec_tokens_end": PROMPT_TOKENS + revision_tokens * n_rounds,
        "decode_tokens_total": decode_tokens,
        "decode_time_s": round(decode_time, 3),
        "prefill_time_s": round(prefill_time, 3),
        "loop_wall_s": round(wall, 3),
    }


def _run_prefix(platform: str) -> dict:
    """Prefix-KV-cache micro-bench: 3 debate-shaped rounds (2 opponents
    sharing one growing spec) through the ContinuousBatcher, greedy, with
    the prefix cache ON vs OFF. Reports per-round prefill tokens, the
    hit rate, tokens saved, decode tok/s both ways, and whether the two
    configurations produced identical tokens (they must)."""
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import random

    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    size = "1b" if platform != "cpu" else "tiny"
    cfg = get_config("llama", size)
    params = T.init_params(
        jax.random.key(0),
        cfg,
        dtype=jnp.bfloat16 if platform != "cpu" else jnp.float32,
    )
    n_rounds, n_opp = 3, 2
    base_len, delta_len, max_new = (
        (1024, 256, 64) if platform != "cpu" else (512, 64, 16)
    )

    def run(enabled):
        prefix_mod.configure(enabled=enabled)
        prefix_mod.reset_stats()
        rng = random.Random(1)
        spec = [rng.randrange(3, cfg.vocab_size) for _ in range(base_len)]
        b = ContinuousBatcher(
            params,
            cfg,
            max_batch=n_opp,
            max_new_cap=max_new,
            page_size=64,
            capacity_tokens=1 << 15,
            greedy=True,
            prefix_cache=enabled,
        )
        per_round, toks = [], []
        decode_tokens = 0
        t0 = time.monotonic()
        for _ in range(n_rounds):
            before = prefix_mod.stats.prefilled_tokens
            for i in range(n_opp):
                b.submit(
                    SchedRequest(
                        req_id=i,
                        prompt_ids=list(spec),
                        max_new_tokens=max_new,
                    )
                )
            results = b.run_all()
            toks.append([r.tokens.tolist() for r in results])
            decode_tokens += sum(r.n_generated for r in results)
            per_round.append(prefix_mod.stats.prefilled_tokens - before)
            spec = spec + [
                rng.randrange(3, cfg.vocab_size) for _ in range(delta_len)
            ]
        wall = time.monotonic() - t0
        return per_round, toks, wall, decode_tokens, prefix_mod.snapshot()

    off_rounds, off_toks, off_wall, off_dec, _ = run(False)
    on_rounds, on_toks, on_wall, on_dec, on_snap = run(True)
    tail_saving = 1.0 - (sum(on_rounds[1:]) / max(sum(off_rounds[1:]), 1))
    payload = {
        "metric": "prefix_cache_tail_prefill_saving",
        "value": round(tail_saving, 4),
        "unit": "fraction of rounds-2+ prefill tokens avoided",
        "vs_baseline": None,  # no published prefix-cache baseline yet
        "platform": platform,
        "model": f"llama-{size}",
        "rounds": n_rounds,
        "opponents": n_opp,
        "spec_tokens_start": base_len,
        "spec_tokens_delta_per_round": delta_len,
        "prefill_tokens_per_round_cache_on": on_rounds,
        "prefill_tokens_per_round_cache_off": off_rounds,
        "hit_rate": on_snap["hit_rate"],
        "cached_tokens": on_snap["cached_tokens"],
        "saved_tokens": on_snap["saved_tokens"],
        "tokens_identical": on_toks == off_toks,
        "wall_s_cache_on": round(on_wall, 3),
        "wall_s_cache_off": round(off_wall, 3),
        "decode_tokens": on_dec,
    }
    return payload


def _run_interleave(platform: str) -> dict:
    """Fused-step + pipelined drive loop vs the legacy serialized loop,
    on a mixed admit-while-decoding workload: more requests than slots,
    alternating multi-chunk and short prompts, so newcomers' prompt
    chunks must either ride residents' decode programs (fused) or stall
    them (legacy). Greedy, prefix cache off (isolates the loop itself),
    pool sized to the workload (the paged gather reads the WHOLE pool
    every step on the CPU reference path, so an oversized pool would
    drown the loop overhead this bench isolates). Each mode warms every
    compiled program (the fused program is distinct) with one untimed
    drain, then runs several timed drains; the reported wall is the MIN
    across repeats — the workload is deterministic, so min is the
    noise-robust statistic on a shared machine. Greedy tokens must be
    identical across modes."""
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import random

    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.engine import interleave as interleave_mod
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    size = "1b" if platform != "cpu" else "tiny"
    cfg = get_config("llama", size)
    params = T.init_params(
        jax.random.key(0),
        cfg,
        dtype=jnp.bfloat16 if platform != "cpu" else jnp.float32,
    )
    n_req, n_slots = 6, 2
    # Long prompts get SHORT budgets and short prompts LONG ones, so a
    # long newcomer's multi-chunk prefill always has a long-running
    # resident to ride (equal budgets would let co-residents finish in
    # lockstep and admissions land in an idle batch — no overlap to
    # measure).
    # Long prompts span several admission chunks (the leading ones ride
    # fused steps; the final chunk admits standalone by design); small
    # decode chunks keep per-program compute low enough that the loop
    # overhead this bench isolates is visible on CPU at all.
    long_len, short_len, long_new, short_new, chunk = (
        (2900, 96, 16, 96, 16)
        if platform != "cpu"
        else (1400, 40, 8, 72, 4)
    )
    rng = random.Random(7)
    prompts = [
        [
            rng.randrange(3, cfg.vocab_size)
            for _ in range(long_len if i % 2 == 0 else short_len)
        ]
        for i in range(n_req)
    ]
    budgets = [long_new if i % 2 == 0 else short_new for i in range(n_req)]
    max_new = max(long_new, short_new)

    n_repeats = int(os.environ.get("BENCH_INTERLEAVE_REPEATS", "5"))

    def mk(enabled: bool) -> ContinuousBatcher:
        return ContinuousBatcher(
            params,
            cfg,
            max_batch=n_slots,
            max_new_cap=max_new,
            page_size=64,
            capacity_tokens=4096,
            greedy=True,
            chunk=chunk,
            prefix_cache=False,
            interleave=enabled,
        )

    def drain(b):
        for i, p in enumerate(prompts):
            b.submit(
                SchedRequest(
                    req_id=i,
                    prompt_ids=list(p),
                    max_new_tokens=budgets[i],
                )
            )
        t0 = time.monotonic()
        results = b.run_all()
        return time.monotonic() - t0, results

    # Warm BOTH modes' compiled programs, capturing tokens for the
    # parity check, then alternate timed drains (mode A, mode B, A, B,
    # …) so machine drift hits both modes equally.
    batchers = {False: mk(False), True: mk(True)}
    toks = {}
    for enabled, b in batchers.items():
        _, results = drain(b)
        toks[enabled] = [r.tokens.tolist() for r in results]
        # Telemetry counters are lifetime sums and the warmup pass is
        # compile-dominated; reset so the report accounts timed passes.
        b.stalled_prefill_s = b.overlapped_prefill_s = 0.0
        b.decode_time_s = 0.0
    # Process-wide interleave stats are accumulated PER MODE (reset
    # around every drain): a single aggregate would blend the legacy
    # drains' all-stalled accounting into the fused mode's split and
    # misrepresent the loop being measured.
    mode_stats: dict[bool, dict] = {
        False: {}, True: {},
    }

    def _accumulate(into: dict) -> None:
        for k, v in interleave_mod.stats.snapshot().items():
            into[k] = round(into.get(k, 0) + v, 6)

    walls: dict[bool, list] = {False: [], True: []}
    for rep in range(n_repeats):
        # Alternate which mode goes first: under monotonically drifting
        # machine load, a fixed order would systematically penalize the
        # second mode of every pair.
        order = (False, True) if rep % 2 == 0 else (True, False)
        for enabled in order:
            interleave_mod.reset_stats()
            w, _ = drain(batchers[enabled])
            walls[enabled].append(round(w, 3))
            _accumulate(mode_stats[enabled])

    def split(b):
        return {
            "stalled_prefill_s": round(b.stalled_prefill_s, 4),
            "overlapped_prefill_s": round(b.overlapped_prefill_s, 4),
            "decode_time_s": round(b.decode_time_s, 4),
        }

    legacy_wall, fused_wall = min(walls[False]), min(walls[True])
    return {
        "metric": "interleave_wall_speedup",
        "value": round(legacy_wall / fused_wall, 4) if fused_wall else None,
        "unit": "legacy wall / fused+pipelined wall (>1 = faster)",
        "vs_baseline": None,  # no published interleave baseline
        "platform": platform,
        "model": f"llama-{size}",
        "requests": n_req,
        "slots": n_slots,
        "prompt_tokens_long": long_len,
        "prompt_tokens_short": short_len,
        "decode_tokens_long_prompt": long_new,
        "decode_tokens_short_prompt": short_new,
        "chunk": chunk,
        "repeats": n_repeats,
        "wall_s_fused": fused_wall,
        "wall_s_legacy": legacy_wall,
        "walls_fused": walls[True],
        "walls_legacy": walls[False],
        "tokens_identical": toks[True] == toks[False],
        "fused": split(batchers[True]),
        "legacy": split(batchers[False]),
        "interleave_fused": mode_stats[True],
        "interleave_legacy": mode_stats[False],
        "escape_hatch": "--no-interleave / ADVSPEC_INTERLEAVE=0",
    }


def _run_spec(platform: str) -> dict:
    """Per-slot speculation in the ContinuousBatcher, measured twice:

    1. MOCK ACCEPTANCE MODEL (engine/mock.py): a growing-spec
       multi-round debate workload — each round's ``[SPEC]`` revision is
       a near-copy of the document in the prompt, exactly the output
       shape prompt-lookup thrives on. Deterministic on CPU, so the
       headline mean tokens/step and acceptance rate are byte-stable
       run to run. Plain decode emits 1 token/step by definition, so
       tokens/step IS the speedup bound speculation buys at equal
       quality (transcripts must be byte-identical spec-on vs off).
    2. REAL BATCHER (llama tiny on CPU / 1b on TPU): the same growing
       workload through the paged serving path, spec-on vs spec-off —
       walls both ways, byte-identical greedy tokens, the measured
       acceptance on a real (random-weight) model, and the retrace
       watch's verdict that the verify program compiled once per
       distinct draft width (``unexpected_recompiles`` must be 0).
    """
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import random
    import re

    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu import obs
    from adversarial_spec_tpu.engine import spec as spec_mod
    from adversarial_spec_tpu.engine.mock import MockEngine
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    gamma = spec_mod.env_gamma()
    n_rounds, n_opp = 4, 2

    # --- 1. Mock acceptance model: growing-spec debate rounds. -------
    def mock_rounds(enabled: bool):
        spec_mod.configure(enabled=enabled, gamma=gamma)
        spec_mod.reset_stats()
        eng = MockEngine()
        doc = (
            "The allocator SHALL bound page reuse by refcount. "
            "Verification MUST cover every accepted draft position. "
        ) * 24
        texts = []
        t0 = time.monotonic()
        for rnd in range(1, n_rounds + 1):
            reqs = [
                ChatRequest(
                    model="mock://critic",
                    system="You are an adversarial spec critic.",
                    user=(
                        f"Debate round {rnd}\n--- DOCUMENT ---\n{doc}"
                        "\n--- END DOCUMENT ---"
                    ),
                )
                for _ in range(n_opp)
            ]
            outs = eng.chat(reqs, SamplingParams())
            texts.append([c.text for c in outs])
            m = re.search(r"\[SPEC\]\n(.*)\n\[/SPEC\]", outs[0].text, re.S)
            doc = m.group(1) if m else doc
        return texts, time.monotonic() - t0, spec_mod.stats.snapshot()

    mock_on_texts, mock_on_wall, mock_snap = mock_rounds(True)
    mock_off_texts, mock_off_wall, _ = mock_rounds(False)

    # --- 2. Real batcher: growing-spec rounds, spec on vs off. -------
    size = "1b" if platform != "cpu" else "tiny"
    cfg = get_config("llama", size)
    params = T.init_params(
        jax.random.key(0),
        cfg,
        dtype=jnp.bfloat16 if platform != "cpu" else jnp.float32,
    )
    base_len, delta_len, max_new = (
        (1024, 256, 64) if platform != "cpu" else (384, 64, 24)
    )

    def batcher_rounds(enabled: bool):
        spec_mod.configure(enabled=enabled, gamma=gamma)
        spec_mod.reset_stats()
        obs.configure(enabled=True)
        obs.reset_stats()
        rng = random.Random(1)
        # Tiled segments, not i.i.d. tokens: prompt-lookup drafts from
        # recurring n-grams, and a spec document genuinely repeats its
        # phrasing (section headers, SHALL/MUST boilerplate) — an
        # i.i.d.-random prompt has no bigram structure to draft from
        # and would measure the overhead half of the trade only.
        seg = [rng.randrange(3, cfg.vocab_size) for _ in range(16)]
        spec = (seg * (base_len // len(seg) + 1))[:base_len]
        b = ContinuousBatcher(
            params,
            cfg,
            max_batch=n_opp,
            max_new_cap=max_new,
            page_size=64,
            capacity_tokens=1 << 15,
            greedy=True,
            prefix_cache=False,
        )
        toks = []
        t0 = time.monotonic()
        for _ in range(n_rounds):
            for i in range(n_opp):
                b.submit(
                    SchedRequest(
                        req_id=i,
                        prompt_ids=list(spec),
                        max_new_tokens=max_new,
                    )
                )
            results = b.run_all()
            toks.append([r.tokens.tolist() for r in results])
            # The spec grows by round R's first revision — the debate
            # loop's shape (critique tokens re-enter the next prompt).
            spec = spec + toks[-1][0] + [
                rng.randrange(3, cfg.vocab_size) for _ in range(delta_len)
            ]
        wall = time.monotonic() - t0
        return toks, wall, spec_mod.stats.snapshot(), obs.snapshot()

    on_toks, on_wall, on_snap, on_obs = batcher_rounds(True)
    off_toks, off_wall, _, _ = batcher_rounds(False)
    retrace = on_obs["retrace"]
    verify = retrace["programs"].get("scheduler_spec_chunk", {})

    return {
        "metric": "spec_mock_tokens_per_step",
        # Plain decode = 1 token/step, so this IS the ≥2× criterion.
        "value": mock_snap["tokens_per_step"],
        "unit": "mean tokens emitted per verify step (mock model)",
        "vs_baseline": None,  # no published speculation baseline
        "platform": platform,
        "model": f"llama-{size}",
        "gamma": gamma,
        "rounds": n_rounds,
        "opponents": n_opp,
        "mock": {
            "tokens_per_step": mock_snap["tokens_per_step"],
            "acceptance_rate": mock_snap["acceptance_rate"],
            "spec_steps": mock_snap["spec_steps"],
            "transcripts_identical": mock_on_texts == mock_off_texts,
            "wall_s_spec_on": round(mock_on_wall, 3),
            "wall_s_spec_off": round(mock_off_wall, 3),
        },
        "batcher": {
            "tokens_per_step": on_snap["tokens_per_step"],
            "acceptance_rate": on_snap["acceptance_rate"],
            "spec_steps": on_snap["spec_steps"],
            "rolled_back_pages": on_snap["rolled_back_pages"],
            "tokens_identical": on_toks == off_toks,
            "wall_s_spec_on": round(on_wall, 3),
            "wall_s_spec_off": round(off_wall, 3),
            "unexpected_recompiles": retrace["unexpected_recompiles"],
            "verify_program": verify,
        },
        "escape_hatch": "--no-speculative / ADVSPEC_SPECULATIVE=0",
    }


def _run_tier(platform: str) -> dict:
    """Tiered-KV bench (engine/kvtier.py), three phases:

    1. RESTART REHYDRATION (mock, deterministic): a 5-round growing-spec
       session with the disk store armed, "restarted" after round 2 (a
       FRESH engine — new allocator, radix index, host tier — sharing
       only the store directory). The restarted process's rounds are
       the session's rounds 2+; the headline is the fraction of their
       prefill tokens the store rehydrates vs a tier-off restart, with
       byte-identical transcripts both ways.
    2. PRESSURE THRASH (mock, deterministic): the radix index capped
       far below the document's block count, so every insert LRU-evicts
       the tail. Tier-off re-prefills the evicted tail every round;
       tier-on promotes it back from host RAM. Reported as the fraction
       of tier-off's rounds-2+ re-prefill the host tier avoids.
    3. REAL BATCHER (llama tiny on CPU / 1b on TPU): the same two
       stories through the paged serving path — demote/promote under a
       page cap and restart-rehydration through a store dir — with
       byte-identical greedy tokens tier-on vs tier-off, allocator +
       tier invariants checked after every drain, and the retrace
       watch's verdict that tiering added zero unexpected recompiles.
    """
    import re
    import shutil

    from adversarial_spec_tpu import obs
    from adversarial_spec_tpu.engine import kvtier as kvtier_mod
    from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
    from adversarial_spec_tpu.engine.mock import MockEngine
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

    n_opp = 2
    base_doc = (
        "The allocator SHALL bound page reuse by refcount. "
        "Demoted blocks MUST reach exactly one terminal state. "
        "Rehydrated prefixes MUST be byte-identical to recomputation. "
    ) * 64  # ~10.6 KB -> ~2600 mock tokens, ~165 blocks

    def mock_session(
        tier_on: bool,
        store_dir: str,
        restart_after: int,
        n_rounds: int,
        cap_pages: int = 0,
    ):
        """Drive a growing-spec session; returns (texts, per-round
        prefilled tokens, tier snapshot). ``restart_after=k`` swaps in a
        FRESH MockEngine after round k (the restart); per-round prefill
        is measured as deltas on the process-wide prefix stats."""
        kvtier_mod.configure(
            enabled=tier_on, host_mb=64, store_dir=store_dir
        )
        prefix_mod.configure(enabled=True, max_pages=cap_pages)
        prefix_mod.reset_stats()
        kvtier_mod.reset_stats()
        eng = MockEngine()
        doc = base_doc
        texts, per_round = [], []
        for rnd in range(1, n_rounds + 1):
            if restart_after and rnd == restart_after + 1:
                eng = MockEngine()  # the restart: only the store survives
            before = prefix_mod.stats.prefilled_tokens
            reqs = [
                ChatRequest(
                    model="mock://critic",
                    system="You are an adversarial spec critic.",
                    # PREFIX-STABLE ordering (the PR 2 template rule):
                    # document first, round header trailing — required
                    # for cross-round (and cross-restart) chain hits.
                    user=(
                        f"--- DOCUMENT ---\n{doc}\n--- END DOCUMENT ---\n"
                        f"Debate round {rnd}"
                    ),
                )
                for _ in range(n_opp)
            ]
            outs = eng.chat(reqs, SamplingParams())
            texts.append([c.text for c in outs])
            per_round.append(
                prefix_mod.stats.prefilled_tokens - before
            )
            m = re.search(r"\[SPEC\]\n(.*)\n\[/SPEC\]", outs[0].text, re.S)
            doc = m.group(1) if m else doc
        return texts, per_round, kvtier_mod.stats.snapshot()

    # --- 1. restart rehydration (disk store). ------------------------
    store = tempfile.mkdtemp(prefix="bench_tier_store_")
    restart_after, n_rounds = 2, 5
    on_texts, on_rounds, on_snap = mock_session(
        True, store, restart_after, n_rounds
    )
    off_texts, off_rounds, _ = mock_session(
        False, "", restart_after, n_rounds
    )
    tail_on = sum(on_rounds[restart_after:])
    tail_off = sum(off_rounds[restart_after:])
    rehydrated_fraction = 1.0 - tail_on / max(tail_off, 1)
    shutil.rmtree(store, ignore_errors=True)

    # --- 2. pressure thrash (host tier). -----------------------------
    cap = 64  # far under the document's block count: every insert evicts
    p_on_texts, p_on_rounds, p_snap = mock_session(True, "", 0, 4, cap)
    p_off_texts, p_off_rounds, _ = mock_session(False, "", 0, 4, cap)
    thrash_on = sum(p_on_rounds[1:])
    thrash_off = sum(p_off_rounds[1:])
    pressure_saving = 1.0 - thrash_on / max(thrash_off, 1)

    # --- 3. real batcher: parity + invariants + retrace. --------------
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import random

    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.engine import spec as spec_mod
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    size = "1b" if platform != "cpu" else "tiny"
    cfg = get_config("llama", size)
    params = T.init_params(
        jax.random.key(0),
        cfg,
        dtype=jnp.bfloat16 if platform != "cpu" else jnp.float32,
    )
    base_len, delta_len, max_new, b_rounds = (
        (1024, 128, 48, 2) if platform != "cpu" else (512, 64, 16, 2)
    )
    spec_mod.configure(enabled=False)  # isolate the tier effect

    def batcher_rounds(tier_on: bool, cap_pages: int, store_dir: str):
        kvtier_mod.configure(
            enabled=tier_on, host_mb=64, store_dir=store_dir
        )
        prefix_mod.configure(enabled=True, max_pages=cap_pages)
        prefix_mod.reset_stats()
        kvtier_mod.reset_stats()
        obs.configure(enabled=True)
        obs.reset_stats()
        rng = random.Random(1)
        seg = [rng.randrange(3, cfg.vocab_size) for _ in range(16)]
        doc = (seg * (base_len // len(seg) + 1))[:base_len]
        b = ContinuousBatcher(
            params,
            cfg,
            max_batch=n_opp,
            max_new_cap=max_new,
            page_size=64,
            capacity_tokens=1 << 15,
            greedy=True,
        )
        toks, per_round = [], []
        t0 = time.monotonic()
        for _ in range(b_rounds):
            before = prefix_mod.stats.prefilled_tokens
            for i in range(n_opp):
                b.submit(
                    SchedRequest(
                        req_id=i,
                        prompt_ids=list(doc),
                        max_new_tokens=max_new,
                    )
                )
            results = b.run_all()
            toks.append([r.tokens.tolist() for r in results])
            per_round.append(prefix_mod.stats.prefilled_tokens - before)
            doc = doc + [
                rng.randrange(3, cfg.vocab_size) for _ in range(delta_len)
            ]
            b.allocator.check_invariants()
            if b.tiers is not None:
                b.tiers.check_invariants()
        wall = time.monotonic() - t0
        return (
            toks,
            per_round,
            wall,
            kvtier_mod.stats.snapshot(),
            obs.snapshot(),
        )

    # Pressure story (page cap forces demote/promote mid-session).
    bt_on, bp_on, bw_on, bsnap_on, bobs_on = batcher_rounds(True, 4, "")
    bt_off, bp_off, bw_off, _, _ = batcher_rounds(False, 4, "")
    # Restart story: batcher A populates the store; a FRESH batcher B
    # (same store) rehydrates; the tier-off fresh batcher is cold.
    bstore = tempfile.mkdtemp(prefix="bench_tier_bstore_")
    batcher_rounds(True, 0, bstore)
    rt_warm, rp_warm, _, rsnap, robs = batcher_rounds(True, 0, bstore)
    rt_cold, rp_cold, _, _, _ = batcher_rounds(False, 0, "")
    shutil.rmtree(bstore, ignore_errors=True)

    return {
        "metric": "tier_restart_rehydrated_fraction",
        # Fraction of the restarted process's rounds-2+ prefill tokens
        # served from the disk store (vs a tier-off restart).
        "value": round(rehydrated_fraction, 4),
        "unit": "fraction of rounds-2+ prefill tokens rehydrated after "
        "restart (mock)",
        "vs_baseline": None,  # no published tiering baseline
        "platform": platform,
        "model": f"llama-{size}",
        "opponents": n_opp,
        "restart": {
            "rounds": n_rounds,
            "restart_after_round": restart_after,
            "rehydrated_fraction": round(rehydrated_fraction, 4),
            "prefill_per_round_tier_on": on_rounds,
            "prefill_per_round_tier_off": off_rounds,
            "rehydrated_tokens": on_snap["rehydrated_tokens"],
            "disk_hit_rate": on_snap["disk_hit_rate"],
            "store_writes": on_snap["store_writes"],
            "transcripts_identical": on_texts == off_texts,
        },
        "pressure": {
            "rounds": 4,
            "prefix_cache_page_cap": cap,
            "reprefill_avoided_fraction": round(pressure_saving, 4),
            "prefill_per_round_tier_on": p_on_rounds,
            "prefill_per_round_tier_off": p_off_rounds,
            "promoted_tokens": p_snap["promoted_tokens"],
            "demoted_tokens": p_snap["demoted_tokens"],
            "host_hit_rate": p_snap["host_hit_rate"],
            "transcripts_identical": p_on_texts == p_off_texts,
        },
        "batcher": {
            "rounds": b_rounds,
            "pressure_tokens_identical": bt_on == bt_off,
            "pressure_prefill_tier_on": bp_on,
            "pressure_prefill_tier_off": bp_off,
            "pressure_promoted_tokens": bsnap_on["promoted_tokens"],
            "wall_s_tier_on": round(bw_on, 3),
            "wall_s_tier_off": round(bw_off, 3),
            "restart_tokens_identical": rt_warm == rt_cold,
            "restart_prefill_warm": rp_warm,
            "restart_prefill_cold": rp_cold,
            "restart_rehydrated_tokens": rsnap["rehydrated_tokens"],
            "unexpected_recompiles": (
                bobs_on["retrace"]["unexpected_recompiles"]
                + robs["retrace"]["unexpected_recompiles"]
            ),
        },
        "escape_hatch": "--no-kv-tier / ADVSPEC_KV_TIER=0",
    }


def _run_residency(platform: str) -> dict:
    """Weight-residency bench (engine/weightres.py), two phases:

    1. MOCK (deterministic): a 4-model opponent pool under an HBM
       budget that fits 2, six rounds. Host paging on (demote/promote)
       vs off (naive evict-reload) compared on total weight-load
       seconds — synthetic walls on exact binary fractions, so the
       ratio is a pinned number, not a measurement. Transcripts must be
       byte-identical across paging-on / paging-off / unconstrained
       (residency is pure accounting on the mock).
    2. TINY-REAL: four tiny families through the real TpuEngine with
       ``ADVSPEC_HBM_BUDGET_BYTES`` sized to the two largest models.
       Same three arms, measured walls; the resident arm additionally
       pins zero unexpected recompiles on re-promotion (promoted params
       restore their original committed shardings) and reports the
       swap-overlap fraction (promotions the prefetch thread ran under
       the current group's decode — the _stage_next path).
    """
    from adversarial_spec_tpu.engine import mock as mock_mod
    from adversarial_spec_tpu.engine import weightres
    from adversarial_spec_tpu.engine.mock import MockEngine
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

    n_models = 4
    mock_rounds = 6

    def _set_budget(nbytes: int | None) -> None:
        if nbytes is None:
            os.environ.pop("ADVSPEC_HBM_BUDGET_BYTES", None)
        else:
            os.environ["ADVSPEC_HBM_BUDGET_BYTES"] = str(nbytes)

    def mock_arm(budget_models: int | None, paging: bool):
        _set_budget(
            budget_models * mock_mod._MODEL_BYTES
            if budget_models is not None
            else None
        )
        weightres.configure(enabled=paging, host_mb=1024)
        weightres.reset_stats()
        eng = MockEngine()
        texts = []
        for rnd in range(1, mock_rounds + 1):
            reqs = [
                ChatRequest(
                    model=f"mock://critic?pool={m}",
                    system="You are an adversarial spec critic.",
                    user=f"Critique the document.\nDebate round {rnd}",
                )
                for m in range(n_models)
            ]
            outs = eng.chat(reqs, SamplingParams())
            texts.append([c.text for c in outs])
        if eng.ledger is not None:
            eng.ledger.check_invariants()
        return texts, weightres.snapshot()

    try:
        m_res_texts, m_res = mock_arm(2, True)
        m_thrash_texts, m_thrash = mock_arm(2, False)
        m_free_texts, _ = mock_arm(None, True)
    finally:
        _set_budget(None)
    mock_identical = (
        m_res_texts == m_thrash_texts == m_free_texts
    )
    mock_ratio = m_thrash["weight_load_wall_s"] / max(
        m_res["weight_load_wall_s"], 1e-9
    )

    # --- 2. tiny-real: the same pool through the real engine. ---------
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    from adversarial_spec_tpu import obs
    from adversarial_spec_tpu.engine import spec as spec_mod
    from adversarial_spec_tpu.engine.tpu import TpuEngine

    aliases = [
        "random-tiny",
        "random-gemma-tiny",
        "random-mistral-tiny",
        "random-qwen-tiny",
    ]
    # Enough rounds that the steady-state swap cost dominates the
    # shared 4-load warm-up: the ratio's asymptote is load/promote
    # (~6x on CPU tiny models), and 6 rounds clears the 2.0 acceptance
    # floor with margin on a noisy host.
    real_rounds = 6
    sampling = SamplingParams(max_new_tokens=16, greedy=True, seed=0)
    spec_mod.configure(enabled=False)  # isolate the residency effect

    def real_arm(budget: int | None, paging: bool):
        _set_budget(budget)
        weightres.configure(enabled=paging, host_mb=4096)
        weightres.reset_stats()
        obs.configure(enabled=True)
        obs.reset_stats()
        obs.retrace.clear()
        eng = TpuEngine()
        texts = []
        for rnd in range(1, real_rounds + 1):
            reqs = [
                ChatRequest(
                    model=f"tpu://{a}",
                    system="You are an adversarial spec critic.",
                    user=f"Critique the document.\nDebate round {rnd}",
                )
                for a in aliases
            ]
            outs = eng.chat(reqs, sampling)
            errs = [c.error for c in outs if not c.ok]
            if errs:
                raise RuntimeError(f"residency bench arm failed: {errs}")
            texts.append([c.text for c in outs])
            eng.check_residency_invariants()
        snap = weightres.snapshot()
        retrace = obs.snapshot()["retrace"]
        bytes_by_alias = {
            a: eng.ledger._entries[a].bytes_device
            or eng.ledger._entries[a].bytes_host
            for a in eng.ledger._entries
        }
        return texts, snap, retrace, bytes_by_alias

    try:
        # Unconstrained arm first: baseline transcripts + model bytes
        # (everything fits, so the reported bytes are device bytes).
        base_texts, _, _, sizes = real_arm(None, True)
        two_largest = sum(sorted(sizes.values(), reverse=True)[:2])
        budget = int(two_largest * 1.05)  # fits 2, never 3
        res_texts, r_res, r_retrace, _ = real_arm(budget, True)
        thrash_texts, r_thrash, _, _ = real_arm(budget, False)
    finally:
        _set_budget(None)
    real_identical = base_texts == res_texts == thrash_texts
    real_ratio = r_thrash["weight_load_wall_s"] / max(
        r_res["weight_load_wall_s"], 1e-9
    )

    return {
        "metric": "residency_load_wall_ratio",
        # Naive evict-reload weight-load seconds over host-paging
        # weight-load seconds, 4-model pool / 2-model budget (real
        # engine; >= 2.0 is the acceptance floor, mock_ratio is the
        # deterministic pin of the same arithmetic).
        "value": round(real_ratio, 3),
        "unit": "x fewer weight-load seconds than evict-reload "
        "(4-model pool, 2-model HBM budget)",
        "vs_baseline": None,  # no published residency baseline
        "platform": platform,
        "within_budget": bool(real_ratio >= 2.0 and mock_ratio >= 2.0),
        "pool_models": n_models,
        "budget_models": 2,
        "load_wall_resident_s": round(r_res["weight_load_wall_s"], 4),
        "load_wall_thrash_s": round(r_thrash["weight_load_wall_s"], 4),
        "swap_overlap_fraction": r_res["swap_overlap_fraction"],
        "transcripts_byte_identical": {
            "mock": mock_identical,
            "real": real_identical,
        },
        "unexpected_recompiles": r_retrace["unexpected_recompiles"],
        "mock": {
            "rounds": mock_rounds,
            "load_wall_ratio": round(mock_ratio, 3),
            "resident": {
                k: m_res[k]
                for k in (
                    "loads",
                    "demotions",
                    "promotions",
                    "weight_load_wall_s",
                    "swap_overlap_fraction",
                    "coalesced_groups",
                )
            },
            "thrash": {
                k: m_thrash[k]
                for k in ("loads", "freed_models", "weight_load_wall_s")
            },
        },
        "real": {
            "rounds": real_rounds,
            "models": aliases,
            "budget_bytes": budget,
            "load_wall_ratio": round(real_ratio, 3),
            "resident": {
                k: r_res[k]
                for k in (
                    "loads",
                    "demotions",
                    "promotions",
                    "promotions_overlapped",
                    "weight_load_wall_s",
                    "coalesced_groups",
                )
            },
            "thrash": {
                k: r_thrash[k]
                for k in ("loads", "freed_models", "weight_load_wall_s")
            },
        },
        "escape_hatch": "--no-weight-res / ADVSPEC_WEIGHT_RES=0",
    }


def _run_kernels(platform: str) -> dict:
    """Fused serving-kernel bench (ops/pallas_quant.py dequant-matmuls +
    the multi-position verify kernel in ops/pallas_paged.py), two phases:

    1. PARITY (interpret mode): each fused kernel against its XLA
       reference — int8 dequant-matmul, int4 dequant-matmul (even and
       odd contraction width: the packed zero-row pad), and the
       multi-position paged-attention span verify against a dense
       gather/softmax reference with an unmapped trailing page.
    2. REAL BATCHER A/B (int4-quantized llama, spec on): one growing-
       spec workload three ways — XLA verify + XLA matmul, Pallas span
       verify, Pallas span verify + fused matmul — byte-identical
       greedy transcripts across arms, per-arm decode tokens/s, and the
       retrace watch pinning zero unexpected recompiles with both
       kernels live.
    """
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import random

    import jax
    import jax.numpy as jnp
    import numpy as np

    from adversarial_spec_tpu import obs
    from adversarial_spec_tpu.engine import spec as spec_mod
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config
    from adversarial_spec_tpu.ops import pallas_paged, pallas_quant, quant

    interpret = platform == "cpu"
    rng = np.random.default_rng(0)
    parity: dict[str, bool] = {}
    max_abs_diff: dict[str, float] = {}

    def _pin(name: str, got, ref, tol: float) -> None:
        d = float(jnp.max(jnp.abs(got.astype(jnp.float32) - ref)))
        max_abs_diff[name] = d
        parity[name] = bool(d <= tol)

    # --- 1a. Fused dequant-matmuls vs the XLA dequant-fusion path. ---
    x = jnp.asarray(rng.standard_normal((24, 256)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((256, 128)), jnp.float32)
    w8 = quant.quantize_int8(w)
    _pin(
        "matmul_int8",
        pallas_quant.matmul_int8(x, w8["q"], w8["scale"], interpret=True),
        quant.matmul(x, w8),
        0.0,  # whole-K accumulation order matches XLA's: bit-exact
    )
    w4 = quant.quantize_int4(w)
    _pin(
        "matmul_int4",
        pallas_quant.matmul_int4(x, w4["q4"], w4["scale"], interpret=True),
        quant.matmul(x, w4),
        2e-4,  # even/odd K-split reassociates the contraction sum
    )
    xo = jnp.asarray(rng.standard_normal((8, 255)), jnp.float32)
    wo = quant.quantize_int4(
        jnp.asarray(rng.standard_normal((255, 128)), jnp.float32)
    )
    _pin(
        "matmul_int4_odd_k",
        pallas_quant.matmul_int4(xo, wo["q4"], wo["scale"], interpret=True),
        quant.matmul(xo, wo),
        2e-4,
    )

    # --- 1b. Multi-position span verify vs a dense gather reference. --
    B, S, Hq, Hkv, D, page, P = 2, 3, 4, 2, 64, 16, 4
    g, T_slots = Hq // Hkv, P * page
    q = jnp.asarray(rng.standard_normal((B, S, Hq, D)), jnp.float32)
    k_pages = jnp.asarray(
        rng.standard_normal((B * P + 1, Hkv, page, D)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.standard_normal((B * P + 1, Hkv, page, D)), jnp.float32
    )
    # Three mapped pages per row, trailing page unmapped (sentinel 0).
    table = np.zeros((B, P), np.int32)
    for b in range(B):
        table[b, :3] = 1 + b * P + np.arange(3)
    base = 2 * page + 5  # the span starts mid-page-3
    starts = np.zeros((B, S), np.int32)
    ends = np.asarray(
        base + 1 + np.arange(S)[None, :] + np.zeros((B, 1), np.int32),
        np.int32,
    )
    scale = float(D) ** -0.5
    got_mq = pallas_paged.paged_decode_attention_mq(
        q, k_pages, v_pages, jnp.asarray(table),
        jnp.asarray(starts), jnp.asarray(ends), interpret=True,
    )
    qn, kn, vn = (np.asarray(a, np.float64) for a in (q, k_pages, v_pages))
    ref_mq = np.zeros((B, S, Hq, D))
    for b in range(B):
        ids = np.maximum(table[b], 0)
        kd = kn[ids].transpose(1, 0, 2, 3).reshape(Hkv, T_slots, D)
        vd = vn[ids].transpose(1, 0, 2, 3).reshape(Hkv, T_slots, D)
        mapped = np.repeat(table[b] > 0, page)
        slot = np.arange(T_slots)
        for s in range(S):
            valid = mapped & (slot >= starts[b, s]) & (slot < ends[b, s])
            for h in range(Hq):
                logits = kd[h // g] @ qn[b, s, h] * scale
                logits[~valid] = -np.inf
                wts = np.exp(logits - logits.max())
                wts[~valid] = 0.0
                ref_mq[b, s, h] = (wts @ vd[h // g]) / max(wts.sum(), 1e-30)
    _pin("paged_mq_verify", got_mq, jnp.asarray(ref_mq, jnp.float32), 1e-4)

    # --- 2. Real batcher: three arms over one growing-spec workload. --
    size = "1b" if platform != "cpu" else "tiny"
    cfg = get_config("llama", size)
    params = quant.quantize_params(
        T.init_params(
            jax.random.key(0),
            cfg,
            dtype=jnp.bfloat16 if platform != "cpu" else jnp.float32,
        ),
        fmt="int4",
    )
    gamma = 4
    n_rounds, n_opp = 2, 2
    base_len, delta_len, max_new = (
        (1024, 256, 64) if platform != "cpu" else (192, 32, 16)
    )

    def arm(use_pallas_verify: bool, use_pallas_matmul: bool):
        spec_mod.configure(enabled=True, gamma=gamma)
        spec_mod.reset_stats()
        obs.configure(enabled=True)
        obs.reset_stats()
        obs.retrace.clear()
        prng = random.Random(1)
        seg = [prng.randrange(3, cfg.vocab_size) for _ in range(16)]
        spec = (seg * (base_len // len(seg) + 1))[:base_len]
        b = ContinuousBatcher(
            params,
            cfg,
            max_batch=n_opp,
            max_new_cap=max_new,
            page_size=64,
            capacity_tokens=1 << 15,
            greedy=True,
            prefix_cache=False,
            use_pallas_matmul=use_pallas_matmul,
        )
        b._use_pallas = use_pallas_verify
        b._pallas_interpret = interpret
        toks, n_toks = [], 0
        t0 = time.monotonic()
        for _ in range(n_rounds):
            for i in range(n_opp):
                b.submit(
                    SchedRequest(
                        req_id=i,
                        prompt_ids=list(spec),
                        max_new_tokens=max_new,
                    )
                )
            results = b.run_all()
            toks.append([r.tokens.tolist() for r in results])
            n_toks += sum(len(t) for t in toks[-1])
            spec = spec + toks[-1][0] + [
                prng.randrange(3, cfg.vocab_size) for _ in range(delta_len)
            ]
        wall = time.monotonic() - t0
        return toks, n_toks / max(wall, 1e-9), obs.snapshot()["retrace"]

    xla_toks, xla_tps, _ = arm(False, False)
    pv_toks, pv_tps, _ = arm(True, False)
    pf_toks, pf_tps, pf_retrace = arm(True, True)

    tokens_per_s = {
        "xla": round(xla_tps, 2),
        "pallas_verify": round(pv_tps, 2),
        "pallas_verify_fused_matmul": round(pf_tps, 2),
    }
    transcripts = {
        "pallas_verify": xla_toks == pv_toks,
        "pallas_verify_fused_matmul": xla_toks == pf_toks,
    }
    recompiles = pf_retrace["unexpected_recompiles"]
    gates_ok = bool(
        all(parity.values()) and all(transcripts.values()) and not recompiles
    )

    return {
        "metric": "kernels_fused_decode_tok_s",
        # Decode throughput with BOTH fused kernels live (span verify +
        # int4 dequant-matmul). On CPU the kernels run in interpret mode
        # so the number is a functional pin, not a speed claim — the
        # speedup story is the TPU ladder's phase E sweep; the contract
        # here is parity + byte-identical transcripts + zero retraces.
        "value": tokens_per_s["pallas_verify_fused_matmul"],
        "unit": "decode tok/s, Pallas span verify + fused int4 matmul",
        "vs_baseline": None,  # no published fused-kernel baseline
        "platform": platform,
        "within_budget": gates_ok,
        "model": f"llama-{size}",
        "gamma": gamma,
        "rounds": n_rounds,
        "opponents": n_opp,
        "interpret": interpret,
        "parity": parity,
        "max_abs_diff": {k: float(v) for k, v in max_abs_diff.items()},
        "tokens_per_s": tokens_per_s,
        "transcripts_byte_identical": transcripts,
        "unexpected_recompiles": recompiles,
        "escape_hatch": "ContinuousBatcher(use_pallas_matmul=False) / "
        "generate(use_pallas_matmul=False)",
    }


def _run_cancel(platform: str) -> dict:
    """Streaming early-convergence cancellation bench, two phases:

    1. MOCK DEBATE ROUNDS (deterministic): a 4-opponent pool where two
       opponents agree IMMEDIATELY but keep talking (``agree_tail`` —
       the verbose-agreement failure mode the matched-ceiling debate
       study makes pure waste) and two critique normally. Early cancel
       stops each agreeing opponent the moment ``[AGREE]`` completes;
       the headline is the fraction of the round's decode tokens that
       never had to be produced, pinned ≥ 30%, with every streamed
       transcript the blocking reply's byte-identical prefix.
    2. REAL BATCHER (tiny CPU model / 1b TPU): one slot, two queued
       requests — the first cancels after a few tokens, so the second
       admits into the freed slot and the whole drain finishes in far
       fewer decode dispatches than the first request's budget alone
       would have taken (freed-slot re-admission, pinned), with
       ``check_invariants`` clean after the cancel and
       ``unexpected_recompiles`` 0 with streaming on.
    """
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu import obs
    from adversarial_spec_tpu.debate.core import run_round
    from adversarial_spec_tpu.engine import streaming as stream_mod
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    spec_doc = (
        "## Goals\nServe heavy traffic fast.\n## Constraints\n"
        "The allocator SHALL bound page reuse by refcount.\n" * 8
    )
    models = [
        "mock://critic?agree_after=1&agree_tail=160",
        "mock://critic?agree_after=1&agree_tail=160",
        "mock://critic",
        "mock://critic",
    ]

    def mock_round(early_cancel: bool):
        stream_mod.configure(enabled=True, early_cancel=early_cancel)
        stream_mod.reset_stats()
        t0 = time.monotonic()
        result = run_round(spec_doc, list(models), round_num=1)
        wall = time.monotonic() - t0
        texts = [r.critique for r in result.responses]
        return texts, wall, stream_mod.snapshot()

    on_texts, on_wall, on_snap = mock_round(True)
    off_texts, off_wall, _ = mock_round(False)
    # Byte-identical transcripts up to each cancellation point: every
    # streamed reply is a prefix of the blocking reply.
    prefix_ok = all(
        full.startswith(part) for part, full in zip(on_texts, off_texts)
    )
    saved_fraction = on_snap["saved_fraction"]

    # --- 2. Real batcher: freed-slot re-admission. -------------------
    size = "1b" if platform != "cpu" else "tiny"
    cfg = get_config("llama", size)
    params = T.init_params(
        jax.random.key(0),
        cfg,
        dtype=jnp.bfloat16 if platform != "cpu" else jnp.float32,
    )
    budget = 256 if platform == "cpu" else 512
    prompts = [[5, 6, 7, 8] * 24, [9, 10, 11, 12] * 24]

    def batcher_drain(cancel: bool, only_req0: bool = False):
        stream_mod.configure(enabled=True, early_cancel=True)
        stream_mod.reset_stats()
        obs.configure(enabled=True)
        obs.reset_stats()
        obs.retrace.clear()
        b = ContinuousBatcher(
            params,
            cfg,
            max_batch=1,
            max_new_cap=budget,
            page_size=64,
            capacity_tokens=1 << 13,
            greedy=True,
        )
        cb = (lambda toks: len(toks) < 8) if cancel else None
        b.submit(
            SchedRequest(
                req_id=0,
                prompt_ids=list(prompts[0]),
                max_new_tokens=budget,
                on_tokens=cb,
            )
        )
        if not only_req0:
            b.submit(
                SchedRequest(
                    req_id=1,
                    prompt_ids=list(prompts[1]),
                    max_new_tokens=16,
                )
            )
        t0 = time.monotonic()
        results = b.run_all()
        wall = time.monotonic() - t0
        b.allocator.check_invariants()
        steps = sum(
            1
            for e in obs.recorder.events()
            if e["type"] == "step" and e["kind"] != "prefill"
        )
        return results, wall, steps, obs.snapshot()

    c_res, c_wall, c_steps, c_obs = batcher_drain(True)
    f_res, f_wall, f_steps, _ = batcher_drain(False)
    _, _, alone_steps, _ = batcher_drain(False, only_req0=True)
    r0 = next(r for r in c_res if r.req_id == 0)
    r1 = next(r for r in c_res if r.req_id == 1)
    # Re-admission pin: with the cancel, the whole 2-request drain (the
    # queued request included, START to FINISH) takes fewer decode
    # dispatches than request 0's budget ALONE takes uncancelled — the
    # queued request was admitted into the freed slot and completed
    # before the cancelled request's old budget would have elapsed.
    readmit_ok = bool(
        r0.cancelled
        and r1.n_generated == 16
        and r1.error is None
        and c_steps < alone_steps
    )
    within = saved_fraction >= 0.30 and prefix_ok and readmit_ok

    return {
        "metric": "cancel_tokens_saved_fraction",
        "value": round(saved_fraction, 4),
        "unit": "fraction of round decode tokens saved by early cancel",
        "vs_baseline": None,  # no published cancellation baseline
        "platform": platform,
        "within_budget": within,
        "budget": 0.30,
        "model": f"llama-{size}",
        "mock": {
            "opponents": len(models),
            "cancels": on_snap["cancels"],
            "tokens_saved": on_snap["tokens_saved"],
            "streamed_tokens": on_snap["streamed_tokens"],
            "saved_fraction": saved_fraction,
            "transcripts_prefix_identical": prefix_ok,
            "wall_s_cancel_on": round(on_wall, 3),
            "wall_s_cancel_off": round(off_wall, 3),
        },
        "batcher": {
            "budget": budget,
            "cancelled_at": int(r0.n_generated),
            "tokens_saved": int(r0.tokens_saved),
            "decode_steps_with_cancel": c_steps,
            "decode_steps_without": f_steps,
            "decode_steps_req0_alone_uncancelled": alone_steps,
            "readmission_before_old_budget": readmit_ok,
            "wall_s_with_cancel": round(c_wall, 3),
            "wall_s_without": round(f_wall, 3),
            "unexpected_recompiles": c_obs["retrace"][
                "unexpected_recompiles"
            ],
        },
        "escape_hatch": "--no-stream / --no-early-cancel "
        "(ADVSPEC_STREAM=0 / ADVSPEC_EARLY_CANCEL=0)",
    }


def _run_recover(platform: str) -> dict:
    """Mid-round kill recovery bench (deterministic CPU mock,
    subprocess-driven — writes BENCH_recover.json):

    A 4-opponent round is SIGKILLed the instant the 2nd opponent's
    journal record becomes durable (``ADVSPEC_JOURNAL_KILL_AFTER``),
    then resumed with ``--resume``; a cold re-run of the same round
    with fresh state is the baseline. The headline is the fraction of
    the round's ENGINE tokens (prefill actually computed + decode
    actually produced) that recovery salvaged vs that cold re-run —
    journal-served opponents pay zero engine work, and the
    content-addressed KV disk store (PR 7) rehydrates the re-issued
    opponents' shared prefix, so the budget is >= 50% salvaged
    (``within_budget``). Transcripts must be byte-identical to the
    cold run throughout. Escape hatch: ``--no-journal``
    (``ADVSPEC_JOURNAL=0``).
    """
    import signal
    import tempfile

    # ONE subprocess-CLI driver for the whole kill-recovery tooling:
    # the drill (tools/chaos_run.py --crash) and this bench must test
    # the same recovery contract, so they share the helper instead of
    # drifting apart.
    from tools.chaos_run import _cli

    repo = os.path.dirname(os.path.abspath(__file__))
    spec_doc = (
        "## Goals\nServe heavy traffic from millions of users, fast.\n"
        "## Constraints\n"
        "The allocator SHALL bound page reuse by refcount.\n" * 6
    )
    models = [f"mock://critic?v={k}" for k in range(1, 5)]
    kill_after = 2

    def _failed(stage: str, proc) -> dict:
        # A failed child is a bench VERDICT, not a crash: surface the
        # child's stderr in the payload instead of dying on its empty
        # stdout (the bench_trend lesson from PR 8).
        return {
            "metric": "recover_tokens_salvaged_fraction",
            "value": 0.0,
            "unit": "fraction of round prefill+decode tokens salvaged "
            "across a mid-round SIGKILL (journal + tier store) vs cold",
            "vs_baseline": None,
            "platform": platform,
            "within_budget": False,
            "budget": 0.5,
            "error": (
                f"{stage} subprocess failed rc={proc.returncode}: "
                f"{proc.stderr[-400:]}"
            ),
            "escape_hatch": "--no-journal (ADVSPEC_JOURNAL=0)",
        }

    with tempfile.TemporaryDirectory(prefix="advspec-recover-") as td:

        def run_cli(args, env, stdin=None):
            return _cli(args, env, td, stdin=stdin)

        base = {
            **os.environ,
            "PYTHONPATH": repo,
            "JAX_PLATFORMS": "cpu",
            # The tiered-KV disk store persists the crashed process's
            # prefix blocks; the resumed process rehydrates from it.
            "ADVSPEC_KV_TIER": "1",
        }
        critique = [
            "critique",
            "--models",
            ",".join(models),
            "--json",
        ]
        env_kill = {
            **base,
            "ADVSPEC_SESSIONS_DIR": os.path.join(td, "sessions"),
            "ADVSPEC_KV_STORE_DIR": os.path.join(td, "store"),
            "ADVSPEC_JOURNAL_KILL_AFTER": str(kill_after),
        }
        p_kill = run_cli(
            [*critique, "--session", "recover"], env_kill, stdin=spec_doc
        )
        killed_ok = p_kill.returncode == -signal.SIGKILL
        env_resume = dict(env_kill)
        env_resume.pop("ADVSPEC_JOURNAL_KILL_AFTER")
        p_resume = run_cli(["critique", "--resume", "recover", "--json"],
                           env_resume)
        if p_resume.returncode != 0:
            return _failed("resume", p_resume)
        resumed = json.loads(p_resume.stdout)
        env_cold = {
            **base,
            "ADVSPEC_SESSIONS_DIR": os.path.join(td, "sessions-cold"),
            "ADVSPEC_KV_STORE_DIR": os.path.join(td, "store-cold"),
        }
        p_cold = run_cli(
            [*critique, "--session", "recover"], env_cold, stdin=spec_doc
        )
        if p_cold.returncode != 0:
            return _failed("cold reference", p_cold)
        cold = json.loads(p_cold.stdout)

    def engine_tokens(payload: dict, salvaged_decode: float = 0.0) -> dict:
        # Prefill the engine actually computed this round (journal-
        # served opponents never reach the engine; tier-rehydrated
        # prefix tokens are already netted out by the cache stats) +
        # decode it actually produced (total output minus the decode
        # that came back off the journal).
        prefill = payload["perf"]["prefix_cache"]["prefilled_tokens"]
        out_total = sum(
            r["output_tokens"] for r in payload["results"]
        )
        return {
            "prefill_tokens": int(prefill),
            "decode_tokens": int(out_total - salvaged_decode),
            "total": int(prefill + out_total - salvaged_decode),
        }

    salvaged_decode = resumed["perf"]["counters"].get(
        "debate/journal.salvaged_decode_tokens", 0.0
    )
    served = int(
        resumed["perf"]["counters"].get("debate/journal.served", 0)
    )
    paid_cold = engine_tokens(cold)
    paid_resumed = engine_tokens(resumed, salvaged_decode)
    salvaged_fraction = (
        1.0 - paid_resumed["total"] / paid_cold["total"]
        if paid_cold["total"]
        else 0.0
    )
    transcripts_ok = all(
        a["response"] == b["response"]
        for a, b in zip(resumed["results"], cold["results"])
    )
    within = (
        killed_ok
        and served == kill_after
        and transcripts_ok
        and salvaged_fraction >= 0.5
    )
    return {
        "metric": "recover_tokens_salvaged_fraction",
        "value": round(salvaged_fraction, 4),
        "unit": "fraction of round prefill+decode tokens salvaged "
        "across a mid-round SIGKILL (journal + tier store) vs cold",
        "vs_baseline": None,  # no published recovery baseline
        "platform": platform,
        "within_budget": within,
        "budget": 0.5,
        "opponents": len(models),
        "kill_after_completions": kill_after,
        "victim_sigkilled": killed_ok,
        "journal_served": served,
        "salvaged_decode_tokens": int(salvaged_decode),
        "paid_cold": paid_cold,
        "paid_recovered": paid_resumed,
        "transcripts_byte_identical": transcripts_ok,
        "escape_hatch": "--no-journal (ADVSPEC_JOURNAL=0)",
    }


def _run_serve(platform: str) -> dict:
    """Serve-daemon bench (deterministic CPU mock — writes
    BENCH_serve.json):

    - **capacity point**: an in-process ``advspec serve`` daemon with
      wide-open caps takes a closed burst of debates; the measured
      completion rate (debates/s and charged tokens/s on the mock) is
      the capacity the admission caps should be sized against — the
      number "millions of users" divides by.
    - **overload storm** (shared with ``tools/chaos_run.py
      --overload`` so the bench and the drill can never test different
      contracts): an open-loop burst at several times the backlog cap
      must shed typed with zero accepted-request loss, brownout
      entered, interactive p99 TTFT within the drill SLO.
    - **SIGTERM drain drill** (shared with ``--drain``): a subprocess
      daemon SIGTERMed mid-burst exits 0 with a clean drain report and
      journal-resumable drained sessions.

    Headline: capacity (debates/s). ``shed_fraction``,
    ``brownout_transitions``, and ``capacity`` are the schema fields
    tools/bench_trend.py validates for this mode. Escape hatch: none
    needed — the daemon only runs when asked to (``debate serve``).
    """
    import asyncio
    import threading

    from adversarial_spec_tpu import serve as serve_mod
    from adversarial_spec_tpu.serve.client import ServeClient
    from adversarial_spec_tpu.serve.daemon import ServeDaemon

    n_debates, n_opp = 32, 2
    spec = (
        "## Goals\nServe heavy traffic from millions of users, fast.\n"
        "## Constraints\n" + "The daemon SHALL shed, not collapse. " * 24
    )
    models = [f"mock://critic?v={k}" for k in range(n_opp)]

    # Phase 1 — capacity point: wide-open caps, closed burst, measure
    # the drain rate the admission controller should be sized against.
    serve_mod.reset_stats()
    serve_mod.configure(
        max_queue_depth=n_debates + 1,
        max_backlog_tokens=10_000_000,
        tenant_quota_tokens=0,
        drain_deadline_s=5.0,
    )
    with tempfile.TemporaryDirectory(prefix="advspec-bench-serve-") as td:
        sock = os.path.join(td, "serve.sock")
        ready = threading.Event()
        daemon = ServeDaemon(sock, sessions_dir=os.path.join(td, "s"))
        th = threading.Thread(
            target=lambda: asyncio.run(daemon.run(ready=ready)),
            daemon=True,
        )
        th.start()
        if not ready.wait(10):
            raise RuntimeError("bench serve daemon did not come up")
        client = ServeClient(sock, timeout_s=120)
        try:
            t0 = time.monotonic()
            ids = [
                client.submit_debate(
                    spec,
                    models,
                    tenant=f"t{k % 4}",
                    stream=False,
                    max_new_tokens=512,
                )
                for k in range(n_debates)
            ]
            lost = 0
            for rid in ids:
                last = client.collect(rid, timeout_s=120)[-1]
                if last["event"] != "result" or last.get("error") or any(
                    r["error"] for r in last["results"]
                ):
                    lost += 1
            capacity_wall = time.monotonic() - t0
            cap_snap = serve_mod.snapshot()
            client.drain()
        finally:
            client.close()
            th.join(timeout=15)
    debates_per_s = round(n_debates / capacity_wall, 2)
    tokens_per_s = round(cap_snap["tokens_charged"] / capacity_wall, 1)

    # Phases 2+3 — the chaos drills, verbatim (one contract).
    from tools.chaos_run import run_drain_drill, run_overload

    overload_failures, overload = run_overload(verbose=False)
    drain_failures, drain = run_drain_drill(verbose=False)

    within = (
        lost == 0
        and debates_per_s > 0
        and not overload_failures
        and not drain_failures
    )
    return {
        "metric": "serve_capacity_debates_per_s",
        "value": debates_per_s,
        "unit": "mock debates/s through the serve daemon at the "
        "capacity point (closed burst, wide-open admission caps)",
        "vs_baseline": None,  # no published serving baseline
        "platform": platform,
        "within_budget": within,
        "capacity": {
            "debates": n_debates,
            "opponents": n_opp,
            "wall_s": round(capacity_wall, 3),
            "debates_per_s": debates_per_s,
            "tokens_per_s": tokens_per_s,
            "lost": lost,
        },
        "shed_fraction": overload.get("shed_fraction", 0.0),
        "brownout_transitions": int(
            overload.get("brownout_entries", 0)
            + overload.get("brownout_exits", 0)
        ),
        "overload": {**overload, "failures": overload_failures,
                     "ok": not overload_failures},
        "drain": {**drain, "failures": drain_failures,
                  "ok": not drain_failures},
        "escape_hatch": "the daemon only runs when asked to "
        "(debate serve); one-shot CLI rounds are unchanged",
    }


def _run_capacity(platform: str) -> dict:
    """Capacity-frontier bench (deterministic seeded replay on the CPU
    mock — writes BENCH_capacity.json): delegates to
    ``tools/load_replay.py`` — a seeded heavy-tailed synthetic trace is
    replayed open-loop against an in-process serve daemon, binary-
    searching the rate multiplier until the SLO breaches, per knob arm
    (replica count 1 vs 3 through the scheduler's capacity provider).

    Headline: accepted debates/s at the SLO frontier on the baseline
    arm. ``vs_baseline`` compares against the committed
    BENCH_capacity.json, and tools/bench_trend.py fails the gate when
    the frontier drops >10% — capacity regressions, not just single-
    stream wall, now fail loudly. Escape hatch: the harness only runs
    when asked to; deleting BENCH_capacity.json drops the gate."""
    import tools.load_replay as load_replay

    slo = load_replay.SLOSpec()
    reqs = load_replay.synthesize(load_replay.SynthSpec(seed=0, requests=64))
    frontier = load_replay.frontier_sweep(
        reqs,
        [
            load_replay.ServeKnobs(replicas=1),
            load_replay.ServeKnobs(replicas=3),
        ],
        slo,
        max_doublings=4,
        bisect_iters=2,
    )
    baseline = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_capacity.json"
    )
    from pathlib import Path

    payload = load_replay.bench_payload(
        frontier,
        slo,
        "synthetic seed=0 requests=64",
        platform=platform,
        baseline_path=Path(baseline),
    )
    return payload


def _run_fleet(platform: str) -> dict:
    """Fleet bench (deterministic CPU mock — writes BENCH_fleet.json):

    A multi-debate workload (6 debates x 3 rounds x 3 opponents, each
    debate its own document) runs through the fleet router three ways:

    - **single** — 1 in-process replica (the pre-fleet topology's
      capacity: every debate serializes onto one engine's busy clock);
    - **multi/affinity** — 3 replicas, prefix-affinity routing (each
      debate consistent-hashes onto one replica, so rounds 2+ re-hit
      the prefix KV that replica already holds);
    - **multi/random** — 3 replicas, round-robin routing (the control
      arm: a debate's rounds scatter, so cross-round prefix reuse
      mostly misses).

    Busy seconds are the mock's synthetic tokens/1024 clock summed per
    replica (prefill actually computed + decode produced), so the
    aggregate-throughput model is deterministic: single-replica
    tokens/s divides by the ONE replica's busy clock, fleet tokens/s
    by the SLOWEST replica's (replicas serve debates concurrently).
    Headline: the >= 2-replica aggregate speedup (budget > 1x), with
    affinity's cross-round cache saved-fraction required to beat
    random routing, transcripts byte-identical across all three arms,
    and the replica-kill recovery drill (tools/chaos_run.py
    --replica-kill: SIGKILL one of 2 worker replicas mid-round) green.
    Escape hatch: --no-fleet (ADVSPEC_FLEET=0) keeps the single-engine
    topology.
    """
    from adversarial_spec_tpu import fleet as fleet_mod
    from adversarial_spec_tpu.engine import kvtier
    from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams
    from adversarial_spec_tpu.fleet.router import FleetEngine

    n_debates, n_rounds, n_opp = 6, 3, 3
    docs = [
        f"## Spec {d}\n"
        + "The allocator SHALL bound page reuse by refcount. " * 40
        + f"\nDebate {d}'s own constraint body, revision zero.\n"
        for d in range(n_debates)
    ]
    params = SamplingParams()

    # The affinity phase measures DEVICE-cache reuse: tiering off so a
    # random-routed miss is a genuine re-prefill, not a disk save.
    kvtier.configure(enabled=False)

    def run_arm(replicas: int, affinity: bool) -> dict:
        prefix_mod.configure(enabled=True, max_pages=0)
        prefix_mod.reset_stats()
        fleet_mod.reset_stats()
        engine = FleetEngine(
            replicas=replicas, transport="inproc", affinity=affinity
        )
        transcripts = []
        for r in range(1, n_rounds + 1):
            for d in range(n_debates):
                reqs = [
                    ChatRequest(
                        model=f"mock://critic?v={k}",
                        system="You are an adversarial spec reviewer.",
                        user=(
                            f"Debate round {r}\n--- DOCUMENT ---\n"
                            f"{docs[d]}\n--- END DOCUMENT ---"
                        ),
                        affinity_key=f"debate-{d}",
                    )
                    for k in range(n_opp)
                ]
                comps = engine.chat(reqs, params)
                if not all(c.ok for c in comps):
                    raise RuntimeError("mock fleet round failed")
                transcripts.extend(c.text for c in comps)
        busys = sorted(
            (s["busy_s"] for s in engine.router.replica_stats()),
            reverse=True,
        )
        snap = prefix_mod.snapshot()
        fleet_snap = fleet_mod.snapshot()
        engine.shutdown()
        total = snap["prefilled_tokens"] + snap["saved_tokens"]
        decode = sum(_estimate(t) for t in transcripts)
        saved_fraction = snap["saved_tokens"] / total if total else 0.0
        return {
            "replicas": replicas,
            "affinity": affinity,
            "transcripts": transcripts,
            "busy_s": [round(b, 6) for b in busys],
            "tokens": int(snap["prefilled_tokens"] + decode),
            "tokens_per_s": round(
                (snap["prefilled_tokens"] + decode) / busys[0], 1
            ),
            "cache_saved_fraction": round(saved_fraction, 4),
            "affinity_hit_rate": fleet_snap["affinity_hit_rate"],
        }

    def _estimate(text: str) -> int:
        return max(1, len(text) // 4)

    single = run_arm(1, affinity=True)
    multi = run_arm(3, affinity=True)
    random_arm = run_arm(3, affinity=False)

    transcripts_ok = (
        single["transcripts"] == multi["transcripts"]
        and single["transcripts"] == random_arm["transcripts"]
    )
    speedup = (
        multi["tokens_per_s"] / single["tokens_per_s"]
        if single["tokens_per_s"]
        else 0.0
    )

    # Phase 2: the replica-loss recovery drill (worker subprocesses,
    # SIGKILL mid-round) — shared with tools/chaos_run.py so the bench
    # and the drill can never test different contracts.
    from tools.chaos_run import run_replica_kill

    kill_failures, kill_payload = run_replica_kill(verbose=False)

    for arm in (single, multi, random_arm):
        arm.pop("transcripts")
    within = (
        speedup > 1.0
        and multi["cache_saved_fraction"] > random_arm["cache_saved_fraction"]
        and transcripts_ok
        and not kill_failures
    )
    return {
        "metric": "fleet_aggregate_speedup",
        "value": round(speedup, 3),
        "unit": "aggregate mock tokens/s, 3 replicas w/ prefix-affinity "
        "routing vs 1 replica, equal workload",
        "vs_baseline": None,  # no published fleet baseline
        "platform": platform,
        "within_budget": within,
        "budget": 1.0,
        "workload": {
            "debates": n_debates,
            "rounds": n_rounds,
            "opponents": n_opp,
        },
        "single": single,
        "multi_affinity": multi,
        "multi_random": random_arm,
        "affinity_vs_random_saved_fraction": [
            multi["cache_saved_fraction"],
            random_arm["cache_saved_fraction"],
        ],
        "transcripts_byte_identical": transcripts_ok,
        "replica_kill": {
            **kill_payload,
            "failures": kill_failures,
            "ok": not kill_failures,
        },
        "escape_hatch": "--no-fleet (ADVSPEC_FLEET=0)",
    }


def _run_elastic(platform: str) -> dict:
    """Elastic-fleet bench (mock serve daemon — writes
    BENCH_elastic.json), two drills:

    **Load step** — the same wave-burst open-loop demand step runs
    against two fleets at the SAME chip ceiling (3 replicas):

    - **fixed** — 3 replicas from the start, no autoscaler: the serve
      scheduler's admission cap and brownout thresholds are sized for
      ONE engine (the pre-elastic coupling), so the step sheds at 1x
      the per-replica backlog cap no matter how many chips idle behind
      the router;
    - **elastic** — floor 1, ceiling 3, the autoscaler's capacity
      provider stretches the admission cap and brownout thresholds
      with LIVE membership: the fleet grows under the step and admits
      what the fixed arm refuses.

    Headline: accepted-debate throughput (completed debates per storm
    second), elastic vs fixed, with interactive p99 TTFT reported for
    both arms (growing must not trade admission for latency collapse).

    **Scale-in** — the fleet-bench debate workload runs once on a
    static 2-replica fleet and once with a PLANNED scale-in (drain ->
    retire through the autoscaler's lifecycle) between rounds:
    transcripts must be byte-identical and duplicated completions
    zero — membership change loses nothing.

    Escape hatch: --no-fleet / ADVSPEC_FLEET_AUTOSCALE=0 keeps the
    static topology.
    """
    import asyncio
    import threading

    from adversarial_spec_tpu import fleet as fleet_mod
    from adversarial_spec_tpu import serve as serve_mod
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams
    from adversarial_spec_tpu.fleet.autoscale import Autoscaler
    from adversarial_spec_tpu.fleet.router import FleetEngine
    from adversarial_spec_tpu.serve.client import ServeClient
    from adversarial_spec_tpu.serve.daemon import ServeDaemon

    n_waves, wave_size = 8, 6
    spec_doc = (
        "## Goals\nAbsorb a demand step without shedding accepted work.\n"
        "## Constraints\n" + "The fleet SHALL grow before it sheds. " * 10
    )
    models = ["mock://critic?v=1", "mock://critic?v=2"]
    old_serve = serve_mod.snapshot()
    old_fleet = fleet_mod.config()

    def run_step(elastic: bool) -> dict:
        serve_mod.reset_stats()
        serve_mod.configure(
            max_queue_depth=64,
            max_backlog_tokens=4000,  # per-replica; elastic stretches
            tenant_quota_tokens=0,
            drain_deadline_s=3.0,
        )
        fleet_mod.shutdown_fleet()
        fleet_mod.configure(
            enabled=True,
            replicas=1 if elastic else 3,  # equal CEILING, not floor
            transport="inproc",
            autoscale=elastic,
            min_replicas=1,
            max_replicas=3,
            scale_out_fraction=0.6,
            scale_in_fraction=0.15,
            scale_out_ticks=1,
            # Scale-in hysteresis must exceed the inter-wave gap or the
            # controller flaps the fleet down between bursts and pays a
            # re-warm on the next one — the drill pins the knob doing
            # its job, not a lucky cadence.
            scale_in_ticks=20,
            scale_cooldown_s=0.05,
            scale_interval_s=0.01,
        )
        fleet_mod.reset_stats()
        with tempfile.TemporaryDirectory(prefix="advspec-elastic-") as td:
            sock = os.path.join(td, "serve.sock")
            ready = threading.Event()
            daemon = ServeDaemon(
                sock, sessions_dir=os.path.join(td, "sessions")
            )
            th = threading.Thread(
                target=lambda: asyncio.run(daemon.run(ready=ready)),
                daemon=True,
            )
            th.start()
            if not ready.wait(10):
                raise RuntimeError("bench daemon did not come up")
            client = ServeClient(sock, timeout_s=60)
            try:
                # Warmup: one debate end-to-end so neither arm pays
                # first-request construction costs inside the
                # measured window (arm order must not decide the
                # headline).
                client.collect(
                    client.submit_debate(
                        spec_doc, models, tenant="warm", max_new_tokens=32
                    ),
                    timeout_s=60,
                )
                # The load step: waves of an UNPACED burst (each wave
                # alone overruns one replica's admission cap several
                # times) separated by a gap longer than the control
                # loop's tick — a demand step the fixed arm must shed
                # into and the elastic arm gets to grow into.
                t0 = time.monotonic()
                submitted = []
                for wave in range(n_waves):
                    for k in range(wave_size):
                        tier = "interactive" if k % 2 else "batch"
                        submitted.append(
                            (
                                client.submit_debate(
                                    spec_doc,
                                    models,
                                    tenant=f"t{k % 2}",
                                    tier=tier,
                                    max_new_tokens=1280,
                                ),
                                tier,
                            )
                        )
                    time.sleep(0.03)
                accepted = completed = shed = 0
                ttfts: list[float] = []
                for rid, tier in submitted:
                    evs = client.collect(rid, timeout_s=120)
                    last = evs[-1]
                    if evs[0]["event"] == "accepted":
                        accepted += 1
                        if last["event"] == "result" and not last.get(
                            "error"
                        ):
                            completed += 1
                            if tier == "interactive":
                                ttfts.append(float(last["ttft_s"]))
                    elif last["event"] == "shed":
                        shed += 1
                wall = time.monotonic() - t0
                client.drain()
            finally:
                client.close()
                th.join(timeout=15)
        from adversarial_spec_tpu.obs.metrics import percentile

        p99 = percentile(ttfts, 0.99)
        return {
            "elastic": {"yes": elastic},
            "accepted": accepted,
            "completed": completed,
            "shed": shed,
            "storm_wall_s": round(wall, 3),
            "accepted_debates_per_s": round(completed / wall, 3)
            if wall
            else 0.0,
            "ttft_p99_s": round(p99, 4),
            "scale_outs": fleet_mod.stats.scale_outs,
            "scale_ins": fleet_mod.stats.scale_ins,
            "flaps_suppressed": fleet_mod.stats.flaps_suppressed,
        }

    def run_scale_in(planned: bool) -> tuple[list[str], int]:
        """The fleet-bench workload with (optionally) a planned
        scale-in between rounds; returns (transcripts, dup count)."""
        fleet_mod.reset_stats()
        n_deb, n_rounds, n_opp = 4, 2, 3
        params = SamplingParams()
        engine = FleetEngine(replicas=2, transport="inproc")
        scaler = Autoscaler(
            engine,
            pressure=lambda: {"backlog_tokens": 0, "active_keys": []},
        )
        transcripts: list[str] = []
        try:
            for r in range(1, n_rounds + 1):
                for d in range(n_deb):
                    reqs = [
                        ChatRequest(
                            model=f"mock://critic?v={k}",
                            system="You are an adversarial spec reviewer.",
                            user=(
                                f"Debate round {r}\n--- DOCUMENT ---\n"
                                f"{spec_doc}\n--- END DOCUMENT ---"
                            ),
                            affinity_key=f"debate-{d}",
                        )
                        for k in range(n_opp)
                    ]
                    comps = engine.chat(reqs, params)
                    if not all(c.ok for c in comps):
                        raise RuntimeError("mock elastic round failed")
                    transcripts.extend(c.text for c in comps)
                if planned and r == 1:
                    # The planned handoff: drain the least-affine
                    # replica out of the ring, retire it through the
                    # lifecycle surgery, keep serving on the survivor.
                    fleet_mod.configure(min_replicas=1, scale_cooldown_s=0.0)
                    scaler._scale_in({}, 2, cfg=fleet_mod.config())
                    if len(engine.router.alive_ids()) != 1:
                        raise RuntimeError("planned scale-in did not land")
        finally:
            scaler.shutdown()
            dup = fleet_mod.stats.duplicated_completions
            engine.shutdown()
        return transcripts, dup

    try:
        fixed = run_step(elastic=False)
        elastic = run_step(elastic=True)
        base_transcripts, base_dup = run_scale_in(planned=False)
        scaled_transcripts, scaled_dup = run_scale_in(planned=True)
    finally:
        fleet_mod.shutdown_fleet()
        fleet_mod.configure(
            enabled=old_fleet.enabled,
            replicas=old_fleet.replicas,
            transport=old_fleet.transport,
            autoscale=old_fleet.autoscale,
            min_replicas=old_fleet.min_replicas,
            max_replicas=old_fleet.max_replicas,
            scale_out_fraction=old_fleet.scale_out_fraction,
            scale_in_fraction=old_fleet.scale_in_fraction,
            scale_out_ticks=old_fleet.scale_out_ticks,
            scale_in_ticks=old_fleet.scale_in_ticks,
            scale_cooldown_s=old_fleet.scale_cooldown_s,
            scale_interval_s=old_fleet.scale_interval_s,
        )
        fleet_mod.reset_stats()
        serve_mod.configure(
            max_queue_depth=old_serve["max_queue_depth"],
            max_backlog_tokens=old_serve["max_backlog_tokens"],
            tenant_quota_tokens=old_serve["tenant_quota_tokens"],
            drain_deadline_s=old_serve["drain_deadline_s"],
        )
        serve_mod.reset_stats()

    ratio = (
        elastic["accepted_debates_per_s"] / fixed["accepted_debates_per_s"]
        if fixed["accepted_debates_per_s"]
        else 0.0
    )
    transcripts_ok = base_transcripts == scaled_transcripts
    dup_total = base_dup + scaled_dup
    within = (
        ratio > 1.0
        and elastic["scale_outs"] >= 1
        and transcripts_ok
        and dup_total == 0
    )
    return {
        "metric": "elastic_accepted_throughput_ratio",
        "value": round(ratio, 3),
        "unit": "completed accepted debates/s under a wave-burst load "
        "step, "
        "elastic fleet (floor 1, ceiling 3) vs fixed 3-replica fleet "
        "with single-engine admission caps (equal chip ceiling)",
        "vs_baseline": None,  # no published elasticity baseline
        "platform": platform,
        "within_budget": within,
        "budget": 1.0,
        "workload": {
            "waves": n_waves,
            "wave_size": wave_size,
            "wave_gap_ms": 30,
        },
        "accepted_throughput_elastic": elastic["accepted_debates_per_s"],
        "accepted_throughput_fixed": fixed["accepted_debates_per_s"],
        "ttft_p99_s": {
            "elastic": elastic["ttft_p99_s"],
            "fixed": fixed["ttft_p99_s"],
        },
        "load_step": {"elastic": elastic, "fixed": fixed},
        "transcripts_byte_identical": {"scale_in": transcripts_ok},
        "duplicated_completions": dup_total,
        "escape_hatch": "--no-fleet (ADVSPEC_FLEET_AUTOSCALE=0)",
    }


def _run_disagg(platform: str) -> dict:
    """Prefill/decode disaggregation bench (deterministic CPU mock —
    writes BENCH_disagg.json).

    A prefill-heavy debate workload (8 debates sharing one large
    document, 2 rounds, 2 opponents, short decode budgets) runs
    through two fleets at EQUAL replica count (4):

    - **symmetric** — 4 undifferentiated replicas, prefix-affinity
      routing: every replica pays the shared document's full prefill
      the first time a debate lands on it, stalling that debate's
      first decode step behind ~P tokens of prefill;
    - **disagg** — 2 prefill + 2 decode replicas: round-1 admissions
      over the handoff threshold prefill on the prefill pool, publish
      their paged-KV blocks to the shared content-addressed store, and
      the decode replica promotes the shipped chains before its first
      step — decode-side prefill shrinks to the residual (unpaged
      tail) tokens.

    Both clocks are the mock's deterministic tokens/1024 busy model
    (prefill actually computed + decode produced), so the bench is
    exact on CPU: **decode-side TTFT** per request is (input -
    cached)/1024 synthetic seconds — the prefill stall the serving
    replica pays before its first decode step — and accepted-debate
    throughput divides completed debates by the BUSIEST replica's
    clock (replicas serve concurrently; the slowest pool gates).
    Headline: round-1 decode-side p99 TTFT, disagg vs symmetric, with
    the handoff hit fraction (adopted/attempts), byte-identical
    transcripts across arms, zero duplicated completions, and zero
    decode-side unexpected recompiles required. Escape hatch:
    ADVSPEC_FLEET_PREFILL_REPLICAS=0 keeps the symmetric topology.
    """
    from adversarial_spec_tpu import fleet as fleet_mod
    from adversarial_spec_tpu import obs as obs_mod
    from adversarial_spec_tpu.engine import kvtier
    from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams
    from adversarial_spec_tpu.fleet.router import FleetEngine

    n_debates, n_rounds, n_opp, n_replicas = 8, 2, 2, 4
    # One large shared document (the prefill-heavy part), with every
    # per-debate / per-round variation APPENDED so the shared prefix
    # stays block-aligned across debates, rounds, and opponents.
    shared_doc = (
        "## Goals\nServe first tokens before the prefill pool pays "
        "for them twice.\n## Constraints\n"
        + "The decode replica SHALL NOT re-prefill shipped blocks. " * 120
    )
    params = SamplingParams(max_new_tokens=64, greedy=True)

    def make_reqs(d: int, r: int) -> list:
        return [
            ChatRequest(
                model=f"mock://critic?v={k}",
                system="You are an adversarial spec reviewer.",
                user=(
                    f"--- DOCUMENT ---\n{shared_doc}\n--- END DOCUMENT "
                    f"---\nDebate {d} round {r}: focus on section {d}."
                ),
                affinity_key=f"debate-{d}",
            )
            for k in range(n_opp)
        ]

    def run_arm(prefill_replicas: int) -> dict:
        prefix_mod.configure(enabled=True, max_pages=0)
        prefix_mod.reset_stats()
        fleet_mod.reset_stats()
        obs_mod.reset_stats()
        obs_mod.retrace.clear()
        with tempfile.TemporaryDirectory(prefix="advspec-disagg-") as td:
            # The shared content-addressed store: the handoff's wire.
            # Both arms run the same tier config (only the topology
            # differs); write-through flush keeps the publish window
            # tight so a handoff's blocks are durable at publish time.
            kvtier.configure(
                enabled=True,
                host_mb=64,
                store_dir=os.path.join(td, "kvstore"),
                flush_blocks=8,
            )
            kvtier.reset_stats()
            engine = FleetEngine(
                replicas=n_replicas,
                transport="inproc",
                affinity=True,
                prefill_replicas=prefill_replicas,
            )
            transcripts: list[str] = []
            ttfts_r1: list[float] = []
            completed = 0
            try:
                for r in range(1, n_rounds + 1):
                    for d in range(n_debates):
                        comps = engine.chat(make_reqs(d, r), params)
                        if not all(c.ok for c in comps):
                            raise RuntimeError("mock disagg round failed")
                        completed += 1
                        transcripts.extend(c.text for c in comps)
                        if r == 1:
                            ttfts_r1.extend(
                                max(
                                    c.usage.input_tokens
                                    - c.usage.cached_tokens,
                                    0,
                                )
                                / 1024.0
                                for c in comps
                            )
                busys = sorted(
                    (
                        (s.get("role", ""), s["busy_s"])
                        for s in engine.router.replica_stats()
                    ),
                    key=lambda t: t[1],
                    reverse=True,
                )
                fleet_snap = fleet_mod.snapshot()
            finally:
                dup = fleet_mod.stats.duplicated_completions
                engine.shutdown()
            kvtier.configure(enabled=False, store_dir="", flush_blocks=0)
        from adversarial_spec_tpu.obs.metrics import percentile

        p99 = percentile(ttfts_r1, 0.99)
        busiest = busys[0][1] if busys else 0.0
        return {
            "prefill_replicas": prefill_replicas,
            "decode_replicas": n_replicas - prefill_replicas,
            "transcripts": transcripts,
            "ttft_p99_s": round(p99, 6),
            "busy_s_by_replica": [
                {"role": role or "any", "busy_s": round(b, 6)}
                for role, b in busys
            ],
            "accepted_debates_per_s": round(completed / busiest, 3)
            if busiest
            else 0.0,
            "completed": completed,
            "handoff": {
                "attempts": fleet_snap["handoff_attempts"],
                "adopted": fleet_snap["handoff_adopted"],
                "degraded": fleet_snap["handoff_degraded"],
                "abandoned": fleet_snap["handoff_abandoned"],
                "shipped_blocks": fleet_snap["handoff_shipped_blocks"],
                "hit_fraction": fleet_snap["handoff_hit_rate"],
            },
            "duplicated_completions": dup,
            "unexpected_recompiles": obs_mod.snapshot()["retrace"][
                "unexpected_recompiles"
            ],
        }

    symmetric = run_arm(prefill_replicas=0)
    disagg = run_arm(prefill_replicas=2)

    transcripts_ok = symmetric["transcripts"] == disagg["transcripts"]
    for arm in (symmetric, disagg):
        arm.pop("transcripts")
    dup_total = (
        symmetric["duplicated_completions"] + disagg["duplicated_completions"]
    )
    recompiles = disagg["unexpected_recompiles"]
    hit_fraction = disagg["handoff"]["hit_fraction"]
    # Guard the ratio: a fully-adopted handoff can drive the disagg
    # residual prefill to zero tokens.
    ratio = symmetric["ttft_p99_s"] / max(disagg["ttft_p99_s"], 1 / 1024.0)
    within = (
        disagg["ttft_p99_s"] < symmetric["ttft_p99_s"]
        and disagg["handoff"]["attempts"] >= n_debates
        and hit_fraction > 0.0
        and transcripts_ok
        and dup_total == 0
        and recompiles == 0
    )
    return {
        "metric": "disagg_decode_ttft_p99_speedup",
        "value": round(ratio, 3),
        "unit": "round-1 decode-side p99 TTFT (synthetic tokens/1024 "
        "prefill stall before the first decode step), symmetric "
        "4-replica fleet vs 2 prefill + 2 decode at equal replica "
        "count, prefill-heavy shared-document workload",
        "vs_baseline": None,  # no published disaggregation baseline
        "platform": platform,
        "within_budget": within,
        "budget": 1.0,
        "workload": {
            "debates": n_debates,
            "rounds": n_rounds,
            "opponents": n_opp,
            "replicas": n_replicas,
            "shared_doc_chars": len(shared_doc),
            "max_new_tokens": params.max_new_tokens,
        },
        "ttft_p99_s": {
            "disagg": disagg["ttft_p99_s"],
            "symmetric": symmetric["ttft_p99_s"],
        },
        "accepted_debates_per_s": {
            "disagg": disagg["accepted_debates_per_s"],
            "symmetric": symmetric["accepted_debates_per_s"],
        },
        "handoff": disagg["handoff"],
        "handoff_hit_fraction": hit_fraction,
        "transcripts_byte_identical": {"disagg": transcripts_ok},
        "duplicated_completions": dup_total,
        "unexpected_recompiles": recompiles,
        "arms": {"disagg": disagg, "symmetric": symmetric},
        "escape_hatch": "ADVSPEC_FLEET_PREFILL_REPLICAS=0 "
        "(symmetric topology)",
    }


def _run_obs_overhead(platform: str) -> dict:
    """Observability overhead bench: what fraction of the mock mixed
    workload's wall the recorder+metrics emit path costs. Budget < 3%
    (``within_budget`` in BENCH_obs.json); escape hatch ``--no-obs``.

    The pin is COMPOSITIONAL, not an on/off wall difference: shared-CPU
    noise on the bench host swings a ~30 ms drain by 3x at timescales
    longer than any affordable repeat budget, so differencing two noisy
    walls cannot resolve a ~1-2% effect (the A/B walls are still
    recorded, as ``ab_*``, for the honest record). Instead:

    - ``per_request_emit_s``: the wall floor (min over K tight-loop
      blocks, each long enough to average intra-block noise) of ONE
      request's worth of emits through the REAL entry points — the
      exact event mix + hot-handle metric ops the mock's per-request
      accounting performs (which is the schema/metric parity of the
      TPU scheduler's per-step sites).
    - ``wall_s_obs_off``: the drain's wall floor (min-of-N) with obs
      off — the fastest the workload demonstrably runs.
    - ``value`` = per_request_emit_s * requests_per_run / off-floor:
      the emit path's share of the best-case wall. Ratio of two floor
      measurements, stable where the A/B difference is not.
    """
    from adversarial_spec_tpu import obs
    from adversarial_spec_tpu.engine import interleave as interleave_mod
    from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
    from adversarial_spec_tpu.engine.mock import MockEngine
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

    n_rounds, n_opp = 8, 4
    base = "# Spec\n" + ("lorem ipsum dolor sit amet " * 400)  # ~10.8 KB
    params = SamplingParams(max_new_tokens=1024)
    n_repeats = int(os.environ.get("BENCH_OBS_REPEATS", "7"))

    def drain(enabled: bool) -> float:
        # Arrivals armed whenever obs is: the < 3% budget covers the
        # worst case (the per-queued-event monotonic arrival stamp
        # included), not just the byte-deterministic default.
        obs.configure(enabled=enabled, arrivals=enabled)
        obs.reset_stats()
        prefix_mod.reset_stats()
        interleave_mod.reset_stats()
        engine = MockEngine()
        spec = base
        t0 = time.monotonic()
        for rnd in range(1, n_rounds + 1):
            reqs = [
                ChatRequest(
                    model="mock://critic",
                    system="You are a critic.",
                    user=(
                        f"--- DOCUMENT ---\n{spec}\n--- END DOCUMENT ---\n"
                        f"Debate round {rnd}"
                    ),
                )
                for _ in range(n_opp)
            ]
            comps = engine.chat(reqs, params)
            spec = spec + f"\n## Revision note (round {rnd})\n" + comps[0].text[:256]
        return time.monotonic() - t0

    def emit_requests(n: int) -> None:
        """One mock request's emit workload, n times, through the real
        entry points (obs.emit + the cached obs.hot handles — the same
        calls engine/mock.py and the scheduler's hot sites make)."""
        emit = obs.emit
        hot = obs.hot
        for i in range(n):
            # prefix-cache lookup funnel (stats.record_lookup)
            emit(obs.CacheEvent(op="lookup", matched_tokens=288, hit=True))
            hot.hit_ratio.set(0.666667)
            # _account_interleave: step event + 2 histogram observes
            emit(
                obs.StepEvent(
                    kind="fused", n_live=2, admission_slot=1,
                    prefill_tokens=13,
                )
            )
            hot.prefill_chunk.observe(0.012695)
            hot.ttft.observe(0.012695)
            # _emit_lifecycle: 5 transitions + outcome counter + the
            # causal-trace span set (request envelope + stage walls)
            # + the two SLO gates, exactly the mock's per-request
            # accounting since the tracing PR.
            for st in ("queued", "admitted", "prefill", "decode", "finished"):
                emit(
                    obs.RequestEvent(
                        req_id=i, state=st, slot=1, tokens=99,
                        cached_tokens=288,
                        # queue-edge arrival stamp, as engine/mock.py
                        # pays it when ADVSPEC_OBS_ARRIVALS is armed
                        arrival_s=(
                            obs.arrival_now() if st == "queued" else 0.0
                        ),
                    )
                )
            for name, phase, wall in (
                ("request", "begin", 0.0),
                ("queued", "begin", 0.0),
                ("queued", "end", 0.0),
                ("prefill", "begin", 0.0),
                ("prefill", "end", 0.012695),
                ("decode", "begin", 0.0),
                ("decode", "end", 0.062695),
                ("request", "end", 0.07539),
            ):
                emit(
                    obs.SpanEvent(
                        name=name, phase=phase, req_id=i, slot=1,
                        wall_s=wall, span_id="tr-001-01/s01",
                    )
                )
            obs.slo_check("ttft", "tr-001-01/s01", 0.012695)
            obs.slo_check("round", "tr-001-01/s01", 0.07539)
            hot.req_finished.inc()
            # chat fan-in counter (1/len(batch) per request; count the
            # whole inc here — a deliberate overestimate)
            hot.mock_chat_requests.inc()

    # Warm both paths (allocator/caches/metric families), then measure.
    drain(False)
    drain(True)
    events_per_run = obs.recorder.seq
    requests_per_run = n_rounds * n_opp

    # Emit-cost floor: K blocks of N requests; each block is long
    # enough (tens of ms) that intra-block noise averages, and the min
    # across blocks floors inter-block noise.
    obs.configure(enabled=True, arrivals=True)
    n_block = int(os.environ.get("BENCH_OBS_EMIT_BLOCK", "50000"))
    per_request = []
    for _ in range(5):
        obs.reset_stats()
        t0 = time.monotonic()
        emit_requests(n_block)
        per_request.append((time.monotonic() - t0) / n_block)
    per_request_emit_s = min(per_request)
    obs.reset_stats()

    # A/B drain walls (auxiliary record) + the off-floor denominator.
    walls: dict[bool, list] = {False: [], True: []}
    for rep in range(n_repeats):
        order = (False, True) if rep % 2 == 0 else (True, False)
        for enabled in order:
            walls[enabled].append(round(drain(enabled), 4))
    # leave the process default armed, arrivals back to the env default
    obs.configure(enabled=True, arrivals=obs.env_arrivals())
    off_wall, on_wall = min(walls[False]), min(walls[True])
    overhead = (
        per_request_emit_s * requests_per_run / off_wall if off_wall else 0.0
    )
    return {
        "metric": "obs_overhead_fraction",
        "value": round(overhead, 4),
        "unit": "per-request emit-path wall x requests / obs-off floor "
        "wall (CPU, mock)",
        "vs_baseline": None,  # budget pin, not a throughput baseline
        "budget": 0.03,
        "within_budget": overhead < 0.03,
        "platform": "cpu",  # mock workload: device-independent
        "rounds": n_rounds,
        "opponents": n_opp,
        "repeats": n_repeats,
        "events_recorded_per_run": events_per_run,
        "requests_per_run": requests_per_run,
        "per_request_emit_us": round(per_request_emit_s * 1e6, 3),
        "wall_s_obs_off": off_wall,
        "ab_wall_s_obs_on": on_wall,
        "ab_value": round(on_wall / off_wall - 1.0, 4) if off_wall else 0.0,
        "ab_walls_on": walls[True],
        "ab_walls_off": walls[False],
        "escape_hatch": "--no-obs / ADVSPEC_OBS=0",
    }


def _run_cpu_fallback(runner, note: str | None = None) -> dict:
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    payload = runner("cpu")
    if note:
        payload["note"] = note
    return payload


def _harvested_tuning() -> dict:
    """Env overrides measured by the TPU ladder, if a harvest exists.

    The driver records BENCH_r* by running plain `python bench.py`; when
    tpu_session.sh has already harvested crossover/sweep data on this
    machine, the TPU child runs at the measured-best settings instead of
    the defaults — the recorded number is the tuned one, automatically.
    Returns {} when no harvest (or no tools/ checkout) is available.
    """
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    try:
        sys.path.insert(0, here)
        from tools.crossover_report import load, recommended_env

        paths = sorted(
            glob.glob(os.path.join(here, "tpu_results", "*.jsonl")),
            key=os.path.getmtime,
        )
        if not paths:
            return {}
        env = recommended_env(load(paths[-1]))
        if env:
            print(f"bench: applying harvested tuning {env}",
                  file=sys.stderr)
        return env
    except Exception:
        return {}  # tuning is an optimization; never block the bench


def _run_tpu_in_child(mode_flag: str, timeout_s: float) -> dict | None:
    """Run the TPU measurement in a DETACHED child with a deadline.

    A healthy probe does not guarantee a healthy tunnel: the relay can
    accept the client and then block forever on the first execute RPC
    (observed this round — bench hung >30 min after a 0.2 s probe). The
    child owns the tunnel and is never signaled; the parent polls for its
    JSON result and walks away on timeout so the driver is never hung.
    """
    out_dir = tempfile.mkdtemp(prefix="bench_tpu_")
    out_path = os.path.join(out_dir, "result.json")
    child_env = dict(os.environ)
    # Measured settings win over defaults, but an operator's explicit
    # env always wins over the harvest.
    tuned = {}
    for k, v in _harvested_tuning().items():
        if k not in child_env:
            child_env[k] = v
            tuned[k] = v
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--_tpu-child", out_path]
        + ([mode_flag] if mode_flag else []),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
        env=child_env,
    )
    def _result() -> dict:
        with open(out_path) as f:
            payload = json.load(f)
        if tuned:
            payload["tuned_env"] = tuned  # traceability of the harvest
        return payload

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(out_path):
            return _result()
        if child.poll() is not None:
            # Exited: re-check the result once — the child may have
            # renamed it into place between the exists() check and exit.
            if os.path.exists(out_path):
                return _result()
            return None  # died without a result (compile error etc.)
        time.sleep(2.0)
    return None  # timed out: leave the child to the tunnel, fall back


def main() -> int:
    args = sys.argv[1:]
    if "--no-interleave" in args:
        # Escape hatch: every batcher-driven mode (and any TPU child)
        # runs the legacy serialized loop. Env so the child inherits it.
        os.environ["ADVSPEC_INTERLEAVE"] = "0"
        from adversarial_spec_tpu.engine import interleave as _il

        _il.configure(enabled=False)

    def _mode(name: str) -> bool:
        return f"--{name}" in args or (
            "--mode" in args
            and args[args.index("--mode") + 1 :][:1] == [name]
        )

    prefix_mode = _mode("prefix")
    interleave_mode = _mode("interleave")
    obs_mode = _mode("obs-overhead")
    spec_mode = _mode("spec")
    tier_mode = _mode("tier")
    cancel_mode = _mode("cancel")
    recover_mode = _mode("recover")
    fleet_mode = _mode("fleet")
    serve_mode = _mode("serve")
    residency_mode = _mode("residency")
    elastic_mode = _mode("elastic")
    disagg_mode = _mode("disagg")
    kernels_mode = _mode("kernels")
    capacity_mode = _mode("capacity")
    if "--no-speculative" in args:
        # Escape hatch mirror of --no-interleave: batcher-driven modes
        # (and any TPU child) decode token-at-a-time.
        os.environ["ADVSPEC_SPECULATIVE"] = "0"
        from adversarial_spec_tpu.engine import spec as _sp

        _sp.configure(enabled=False)
    if "--long-context" in args:
        mode_flag, runner = "--long-context", _run_long_context
    elif "--round-loop" in args:
        mode_flag, runner = "--round-loop", _run_round_loop
    elif prefix_mode:
        mode_flag, runner = "--prefix", _run_prefix
    elif interleave_mode:
        mode_flag, runner = "--interleave", _run_interleave
    elif obs_mode:
        mode_flag, runner = "--obs-overhead", _run_obs_overhead
    elif spec_mode:
        mode_flag, runner = "--spec", _run_spec
    elif tier_mode:
        mode_flag, runner = "--tier", _run_tier
    elif cancel_mode:
        mode_flag, runner = "--cancel", _run_cancel
    elif recover_mode:
        mode_flag, runner = "--recover", _run_recover
    elif fleet_mode:
        mode_flag, runner = "--fleet", _run_fleet
    elif serve_mode:
        mode_flag, runner = "--serve", _run_serve
    elif residency_mode:
        mode_flag, runner = "--residency", _run_residency
    elif elastic_mode:
        mode_flag, runner = "--elastic", _run_elastic
    elif disagg_mode:
        mode_flag, runner = "--disagg", _run_disagg
    elif kernels_mode:
        mode_flag, runner = "--kernels", _run_kernels
    elif capacity_mode:
        mode_flag, runner = "--capacity", _run_capacity
    else:
        mode_flag, runner = "", _run_bench

    if "--_tpu-child" in args:
        # Child mode: we own the tunnel; run on whatever backend jax finds
        # and write the result atomically for the waiting parent.
        out_path = args[args.index("--_tpu-child") + 1]
        import jax

        payload = runner(jax.devices()[0].platform)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.rename(tmp, out_path)
        return 0

    if (
        obs_mode
        or recover_mode
        or fleet_mode
        or serve_mode
        or elastic_mode
        or disagg_mode
        or capacity_mode
    ):
        # Mock-only workloads — no jax, no device, no TPU probe: the
        # obs budget is a CPU host-overhead pin by definition, and the
        # recovery/fleet/serve drills are mock rounds (in-process
        # replicas, SIGKILL-able subprocess workers, and the serve
        # daemon's socket front).
        payload = runner("cpu")
    elif os.environ.get("BENCH_FORCE_CPU") == "1" or not _probe_tpu():
        payload = _run_cpu_fallback(runner)
    else:
        timeout_s = float(os.environ.get("BENCH_TPU_TIMEOUT_S", "1500"))
        payload = _run_tpu_in_child(mode_flag, timeout_s)
        if payload is None:
            payload = _run_cpu_fallback(
                runner,
                note=(
                    "tpu run launched but produced no result in time "
                    "(tunnel hang or compile error); CPU fallback"
                ),
            )
    if (
        prefix_mode
        or interleave_mode
        or obs_mode
        or spec_mode
        or tier_mode
        or cancel_mode
        or recover_mode
        or fleet_mode
        or serve_mode
        or residency_mode
        or elastic_mode
        or disagg_mode
        or kernels_mode
        or capacity_mode
    ):
        # Persist the perf trajectory point alongside the BENCH_r*
        # series the driver records.
        name = (
            "BENCH_prefix.json"
            if prefix_mode
            else "BENCH_interleave.json"
            if interleave_mode
            else "BENCH_obs.json"
            if obs_mode
            else "BENCH_spec.json"
            if spec_mode
            else "BENCH_tier.json"
            if tier_mode
            else "BENCH_cancel.json"
            if cancel_mode
            else "BENCH_recover.json"
            if recover_mode
            else "BENCH_fleet.json"
            if fleet_mode
            else "BENCH_residency.json"
            if residency_mode
            else "BENCH_elastic.json"
            if elastic_mode
            else "BENCH_disagg.json"
            if disagg_mode
            else "BENCH_kernels.json"
            if kernels_mode
            else "BENCH_capacity.json"
            if capacity_mode
            else "BENCH_serve.json"
        )
        out = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), name
        )
        with open(out, "w") as f:
            json.dump(payload, f, indent=2)
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
