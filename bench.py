"""Benchmark: critique tokens/sec/chip for a batched multi-opponent decode.

Measures the north-star metric (BASELINE.json): decode throughput of one
debate round's opponent pool run as a single batched generate — 4 opponents
(batch rows) critiquing the SAME spec prompt on one model (shared-prefix
prefill fires), temperature-0.7 sampling with a fixed seed so rows diverge
the way a real round does, synthetic weights (zero egress). Baseline
target: 1500 critique tokens/sec/chip.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "tok/s/chip", "vs_baseline": N/1500}

Robustness: the TPU tunnel in this environment can wedge (backend init
blocks forever), so platform selection happens via a short subprocess
probe; if the TPU doesn't come up, the bench runs on CPU with a smaller
config and says so in the "platform" field rather than hanging the driver.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_TOK_S_CHIP = 1500.0
N_OPPONENTS = 4
PROMPT_TOKENS = 1024
DECODE_TOKENS = 256


def _probe_tpu(timeout_s: float = 120.0) -> bool:
    """Can a fresh process initialize the accelerator backend in time?"""
    code = "import jax; d=jax.devices(); print(d[0].platform)"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=timeout_s,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and "cpu" not in out.stdout.strip().lower()


def _run_bench(platform: str) -> dict:
    import jax

    from adversarial_spec_tpu.engine.generate import generate
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    # Real-accelerator bench uses the 1b llama shape (fits one v5e chip in
    # bf16 with cache headroom); CPU fallback uses the tiny config so the
    # driver always gets a number instead of a multi-hour crawl.
    size = "1b" if platform != "cpu" else "tiny"
    import jax.numpy as jnp

    cfg = get_config("llama", size)
    params = T.init_params(
        jax.random.key(0),
        cfg,
        dtype=jnp.bfloat16 if platform != "cpu" else jnp.float32,
    )

    # The real debate-round shape: every opponent critiques the SAME spec
    # prompt (shared-prefix prefill fires on one chip), and temperature
    # sampling diverges the rows — exactly what a critique round does.
    rng = __import__("random").Random(0)
    prompt = [rng.randrange(3, cfg.vocab_size) for _ in range(PROMPT_TOKENS)]
    prompts = [list(prompt) for _ in range(N_OPPONENTS)]

    # Multi-chip: shard the round over a dp×tp mesh so every chip
    # participates before dividing by chip count; single chip (the usual
    # bench hardware) and CPU run unsharded and divide by 1.
    n_devices = len(jax.devices())
    mesh = None
    n_chips = 1
    if platform != "cpu" and n_devices > 1:
        import math as _math

        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        dp = _math.gcd(N_OPPONENTS, n_devices)
        mesh = make_mesh({"dp": dp, "tp": n_devices // dp})
        params = shard_params(mesh, params)
        n_chips = n_devices

    kw = dict(
        max_new_tokens=DECODE_TOKENS,
        eos_ids=[],  # synthetic model: measure the full decode length
        temperature=0.7,
        seed=0,
        mesh=mesh,
    )
    # Warmup: compile prefill + decode chunk.
    generate(params, cfg, prompts, **kw)
    # Measured run.
    t0 = time.monotonic()
    result = generate(params, cfg, prompts, **kw)
    wall = time.monotonic() - t0

    tok_s_chip = result.decode_tokens / result.decode_time_s / n_chips
    return {
        "metric": "critique_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s_chip / BASELINE_TOK_S_CHIP, 3),
        "platform": platform,
        "model": f"llama-{size}",
        "opponents": N_OPPONENTS,
        "prompt_tokens": PROMPT_TOKENS,
        "decode_tokens_per_opponent": DECODE_TOKENS,
        "decode_time_s": round(result.decode_time_s, 3),
        "prefill_time_s": round(result.prefill_time_s, 3),
        "round_wall_s": round(wall, 3),
    }


def main() -> int:
    if os.environ.get("BENCH_FORCE_CPU") == "1" or not _probe_tpu():
        # Backend unreachable (or forced): pin CPU before jax import.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        payload = _run_bench("cpu")
    else:
        import jax

        payload = _run_bench(jax.devices()[0].platform)
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
