#!/usr/bin/env bash
# Demo: a full adversarial spec debate on the mock engine (no TPU, no
# downloads), then the synthetic-TPU path. Run from the repo root.
#
#   examples/demo.sh                 # everything (tpu:// leg compiles XLA:
#                                    # ~1-3 min cold on a CPU box)
#   examples/demo.sh --skip-tpu-leg  # mock-only, finishes in seconds
set -euo pipefail
# Uses whatever accelerator jax finds; set JAX_PLATFORMS=cpu to force CPU
# (e.g. on a box whose TPU tunnel is unavailable).
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

RUN_TPU_LEG=1
if [[ "${1:-}" == "--skip-tpu-leg" ]]; then
  RUN_TPU_LEG=0
fi

SPEC='# Webhook Delivery Service

Delivers webhooks to customer endpoints with retries.

## Scope
v1 targets at-least-once delivery with exponential backoff.'

echo "=== Round 1: 3 opponents (one flaky), session tracked ==="
echo "$SPEC" | python3 -m adversarial_spec_tpu.cli critique \
  --models "mock://agree,mock://critic?agree_after=3,mock://flaky?fail=1&agree_after=2" \
  --doc-type tech --session demo --show-cost

for round in 2 3; do
  echo; echo "=== Round $round (resumed) ==="
  python3 -m adversarial_spec_tpu.cli critique --resume demo
done

echo; echo "=== Export the converged spec as tasks ==="
echo "$SPEC" | python3 -m adversarial_spec_tpu.cli export-tasks --models mock://tasks

if [[ "$RUN_TPU_LEG" == "1" ]]; then
  echo; echo "=== Synthetic tpu:// opponent (random weights, real engine) ==="
  echo "$SPEC" | python3 -m adversarial_spec_tpu.cli critique \
    --models tpu://random-tiny --greedy --max-new-tokens 32 2>/dev/null
else
  echo; echo "=== Synthetic tpu:// opponent: skipped (--skip-tpu-leg) ==="
fi

echo; echo "=== Cleanup ==="
rm -f .adversarial-spec-checkpoints/demo-round-*.md
python3 - <<'PY'
from adversarial_spec_tpu.debate.session import SESSIONS_DIR
p = SESSIONS_DIR / "demo.json"
p.unlink(missing_ok=True)
print("removed", p)
PY
