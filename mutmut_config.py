"""Mutation-testing configuration (mutmut).

Parity with the reference's mutmut_config.py (SURVEY component #8): skip
mutants in configuration data, prompt text, and logging so the mutation
score measures *logic*, not constants a human would never get wrong twice.

Run: ``mutmut run`` (dev-only; mutmut is not a runtime dependency).
"""

from __future__ import annotations

_SKIP_PATH_FRAGMENTS = (
    "/prompts.py",  # prompt text: every word is a mutable "constant"
    "/config.py",  # model-shape tables
    "/tests/",
    # graftlint's embedded must-fail fixtures are deliberately-broken
    # code: mutating them only produces "differently broken", and a
    # mutant that ACCIDENTALLY fixes one breaks the self-test for the
    # wrong reason. tools/lint_all.py asserts this entry stays.
    "/tools/graftlint/",
    # The lockdep sanitizer's violation formatting (stack capture,
    # message assembly) is diagnostics for humans: mutants there either
    # trip its own self-test trivially or change only report prose.
    "/resilience/lockdep.py",
)

_SKIP_LINE_MARKERS = (
    "print(",  # logging/stderr output
    "_err(",
    "description=",  # argparse help strings
    "help=",
    "indent=",  # cosmetic JSON pretty-printing width
)


def pre_mutation(context) -> None:
    path = (context.filename or "").replace("\\", "/")
    if any(frag in path for frag in _SKIP_PATH_FRAGMENTS):
        context.skip = True
        return
    line = context.current_source_line or ""
    if any(marker in line for marker in _SKIP_LINE_MARKERS):
        context.skip = True
