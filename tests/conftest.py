"""Test bootstrap.

Runs on CPU with a virtual 8-device mesh (SURVEY §4: the reference mocks its
transport seam and runs everything above it for real; our analogs are the
mock engine plus ``--xla_force_host_platform_device_count=8`` so sharding
code executes real collectives in one process). Env vars must be set before
jax initializes, hence at conftest import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The whole suite runs with the lockdep sanitizer armed: every
# declared lock becomes a TrackedLock, the acquisition-order graph is
# live, and any inversion fails the test that caused it (the fixture
# below asserts zero violations at teardown). Must be set before the
# package imports — make_lock() reads it at lock construction.
os.environ.setdefault("ADVSPEC_LOCKDEP", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


def _force_cpu_only_backends() -> None:
    """Drop every non-CPU PJRT backend before first jax use.

    The environment may inject a TPU-tunnel plugin via sitecustomize into
    every interpreter (importing jax before this file runs, so env vars
    are already snapshotted); its client init dials a remote service and
    can block the whole test run if the tunnel is wedged. Tests are
    CPU-only by contract, so force the platform list via jax.config and
    unregister the other factories while backends are uninitialized.
    """
    try:
        import jax
    except ImportError:
        return
    # NOTE: do NOT unregister the non-CPU backend factories — their
    # registration is what makes the "tpu" platform *known* to the MLIR
    # lowering registry, and Pallas imports register tpu lowering rules.
    # Restricting jax_platforms is sufficient to keep the remote backend
    # uninitialized (its client is only dialed at init).
    jax.config.update("jax_platforms", "cpu")
    # Pin the env var too: utils/jaxenv.configure_jax (invoked lazily at
    # first tpu-engine use) mirrors JAX_PLATFORMS into jax.config, and the
    # surrounding environment may preset it to an accelerator value —
    # without this pin that mirror would override the CPU-only test
    # contract mid-suite.
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Persistent XLA compile cache — the same location configure_jax
    # points every CLI/ladder child at. The suite and its subprocess
    # children (ladder children, fleet workers, serve daemons) compile
    # the same tiny-model programs over and over; warm entries take
    # whole compiles off the tier-1 wall, and cache keys fingerprint
    # the computation so a code change can never serve a stale binary.
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    # configure_jax's 1.0s floor is tuned for real-model programs; the
    # suite's tiny-model compiles mostly land under it, so cache them
    # all — the point here is aggregate wall across hundreds of tests.
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass


_force_cpu_only_backends()


@pytest.fixture(autouse=True)
def _isolate_state(tmp_path, monkeypatch):
    """Point every persistence dir at tmp and reset engine singletons."""
    from adversarial_spec_tpu.debate import session, profiles
    from adversarial_spec_tpu.engine import registry, dispatch
    from adversarial_spec_tpu.resilience import breaker, faults, injector

    monkeypatch.setattr(session, "SESSIONS_DIR", tmp_path / "sessions")
    monkeypatch.setattr(session, "CHECKPOINTS_DIR", tmp_path / "checkpoints")
    monkeypatch.setattr(profiles, "PROFILES_DIR", tmp_path / "profiles")
    monkeypatch.setattr(
        profiles, "GLOBAL_CONFIG_PATH", tmp_path / "config.json"
    )
    monkeypatch.setattr(registry, "REGISTRY_PATH", tmp_path / "registry.json")
    # Resilience state is process-global by design (breakers must outlive
    # a round); between tests it must not leak. Chaos env vars from the
    # invoking shell must not reach the suite either.
    monkeypatch.delenv("ADVSPEC_CHAOS", raising=False)
    monkeypatch.delenv("ADVSPEC_CHAOS_SEED", raising=False)
    monkeypatch.delenv("ADVSPEC_BREAKER_THRESHOLD", raising=False)
    monkeypatch.delenv("ADVSPEC_BREAKER_COOLDOWN", raising=False)
    breaker.reset_default_registry()
    faults.reset()
    injector.reset()
    dispatch.clear_engine_cache()
    # Prefix-cache config/stats are process-global by design (the cache
    # outlives a round); tests must not leak a --no-prefix-cache or a
    # page cap into each other.
    from adversarial_spec_tpu.engine import prefix_cache

    prefix_cache.configure(enabled=True, max_pages=0)
    prefix_cache.reset_stats()
    # Tiered-KV config/stats are process-global by design (the tiers
    # live on persistent batchers); tests must not leak a store dir,
    # a host budget, or swap counts into each other. Tiering is pinned
    # OFF suite-wide (the PR 6 speculation-off precedent: per-insert
    # chain hashing and per-eviction demotion gathers in every batcher/
    # mock test are pure wall cost when the subject is orthogonal —
    # tier coverage of the same paths lives in tests/test_kv_tier.py,
    # which opts in explicitly, as do CLI tests of the env default).
    from adversarial_spec_tpu.engine import kvtier

    monkeypatch.setenv("ADVSPEC_KV_TIER", "0")
    monkeypatch.delenv("ADVSPEC_KV_HOST_MB", raising=False)
    monkeypatch.delenv("ADVSPEC_KV_STORE_DIR", raising=False)
    monkeypatch.delenv("ADVSPEC_KV_FLUSH_BLOCKS", raising=False)
    kvtier.configure(
        enabled=False,
        host_mb=kvtier.DEFAULT_HOST_MB,
        store_dir="",
        flush_blocks=0,
    )
    kvtier.reset_stats()
    # Weight-residency config/stats are process-global by design (the
    # ledger lives on each engine); tests must not leak a host budget,
    # swap counts, or — critically — an explicit HBM budget (the mock
    # engine's residency simulation arms only under
    # ADVSPEC_HBM_BUDGET_BYTES, keeping pre-residency mock event
    # streams byte-identical).
    from adversarial_spec_tpu.engine import weightres

    monkeypatch.delenv("ADVSPEC_WEIGHT_RES", raising=False)
    monkeypatch.delenv("ADVSPEC_WEIGHT_HOST_MB", raising=False)
    monkeypatch.delenv("ADVSPEC_HBM_BUDGET_BYTES", raising=False)
    weightres.configure(enabled=True, host_mb=weightres.DEFAULT_HOST_MB)
    weightres.reset_stats()
    # Fleet config/stats are process-global by design (the replica
    # topology outlives a round); tests must not leak an armed fleet,
    # spawned replicas, or routing counts into each other. Fleet OFF
    # is the product default — fleet coverage opts in explicitly in
    # tests/test_fleet.py (clear_engine_cache above already tears the
    # process fleet engine down).
    from adversarial_spec_tpu import fleet

    monkeypatch.delenv("ADVSPEC_FLEET", raising=False)
    monkeypatch.delenv("ADVSPEC_FLEET_REPLICAS", raising=False)
    monkeypatch.delenv("ADVSPEC_FLEET_TRANSPORT", raising=False)
    monkeypatch.delenv("ADVSPEC_FLEET_AUTOSCALE", raising=False)
    monkeypatch.delenv("ADVSPEC_FLEET_MIN", raising=False)
    monkeypatch.delenv("ADVSPEC_FLEET_MAX", raising=False)
    monkeypatch.delenv("ADVSPEC_FLEET_SCALE_COOLDOWN_S", raising=False)
    monkeypatch.delenv("ADVSPEC_FLEET_SCALE_INTERVAL_S", raising=False)
    monkeypatch.delenv("ADVSPEC_REPLICA_KILL_AFTER", raising=False)
    monkeypatch.delenv("ADVSPEC_PREFILL_KILL_AFTER", raising=False)
    monkeypatch.delenv("ADVSPEC_FLEET_PREFILL_REPLICAS", raising=False)
    monkeypatch.delenv("ADVSPEC_FLEET_HANDOFF_THRESHOLD", raising=False)
    fleet.configure(
        enabled=False,
        replicas=fleet.DEFAULT_REPLICAS,
        transport="inproc",
        autoscale=False,
        min_replicas=fleet.DEFAULT_MIN_REPLICAS,
        max_replicas=fleet.DEFAULT_MAX_REPLICAS,
        scale_cooldown_s=fleet.DEFAULT_SCALE_COOLDOWN_S,
        scale_interval_s=fleet.DEFAULT_SCALE_INTERVAL_S,
        prefill_replicas=fleet.DEFAULT_PREFILL_REPLICAS,
        handoff_threshold_tokens=fleet.DEFAULT_HANDOFF_THRESHOLD_TOKENS,
        min_prefill_replicas=fleet.DEFAULT_MIN_PREFILL_REPLICAS,
        max_prefill_replicas=fleet.DEFAULT_MAX_PREFILL_REPLICAS,
    )
    fleet.reset_stats()
    # Streaming config/stats are process-global by design (the CLI arms
    # them per round); tests must not leak a --no-stream / cancel
    # counts into each other. Defaults (stream + early-cancel on) are
    # the product defaults — streaming tests exercise both sides.
    from adversarial_spec_tpu.engine import streaming

    monkeypatch.delenv("ADVSPEC_STREAM", raising=False)
    monkeypatch.delenv("ADVSPEC_EARLY_CANCEL", raising=False)
    streaming.configure(enabled=True, early_cancel=True)
    streaming.reset_stats()
    # Serve-daemon state is process-global by design (the daemon arms
    # it once at startup); tests must not leak tightened admission
    # caps, quotas, counters, or — critically — an installed scheduler
    # gate (a leaked gate would route every later test's engine calls
    # through a dead scheduler).
    from adversarial_spec_tpu import serve
    from adversarial_spec_tpu.serve import gate as serve_gate

    for var in (
        "ADVSPEC_SERVE_QUEUE_DEPTH",
        "ADVSPEC_SERVE_BACKLOG_TOKENS",
        "ADVSPEC_SERVE_QUOTA_TOKENS",
        "ADVSPEC_SERVE_DRAIN_DEADLINE_S",
        "ADVSPEC_SERVE_TTFT_SLO_MS",
        "ADVSPEC_SERVE_SOCKET",
    ):
        monkeypatch.delenv(var, raising=False)
    serve_gate.uninstall()
    serve.configure(
        max_queue_depth=serve.DEFAULT_QUEUE_DEPTH,
        max_backlog_tokens=serve.DEFAULT_BACKLOG_TOKENS,
        tenant_quota_tokens=0,
        drain_deadline_s=serve.DEFAULT_DRAIN_DEADLINE_S,
        brownout_enter_fraction=serve.DEFAULT_BROWNOUT_ENTER_FRACTION,
        brownout_exit_fraction=serve.DEFAULT_BROWNOUT_EXIT_FRACTION,
        brownout_gamma=serve.DEFAULT_BROWNOUT_GAMMA,
        preempt_grace_s=0.0,
        interactive_ttft_slo_ms=0.0,
        max_dispatch_batch=4,
        max_debates_in_flight=32,
    )
    serve.reset_stats()
    # Observability state is process-global by design (the recorder and
    # metric handles outlive a round); tests must not leak an armed
    # events_out path, a shrunken ring, or recorded events.
    from adversarial_spec_tpu import obs

    monkeypatch.delenv("ADVSPEC_OBS", raising=False)
    monkeypatch.delenv("ADVSPEC_EVENTS_OUT", raising=False)
    monkeypatch.delenv("ADVSPEC_FLIGHT_RECORDER_SIZE", raising=False)
    monkeypatch.delenv("ADVSPEC_OBS_ARRIVALS", raising=False)
    obs.configure(
        enabled=True,
        recorder_size=obs.DEFAULT_RECORDER_SIZE,
        events_out="",
        dump_on_fault=True,
        arrivals=False,
    )
    obs.reset_stats()
    # Full retrace clear (reset() deliberately keeps compile baselines
    # for warm per-round accounting; tests want cold-start isolation).
    obs.retrace.clear()
    # Lockdep state is process-global by design (the order graph spans
    # every lock in the process); tests must not leak edges — or,
    # worse, a recorded violation — into each other.
    from adversarial_spec_tpu.resilience import lockdep

    lockdep.reset()
    yield
    leaked = lockdep.violations()
    assert not leaked, (
        "lock-order violation(s) recorded during this test:\n"
        + "\n\n".join(str(v) for v in leaked)
    )
    lockdep.reset()
    serve_gate.uninstall()
    serve.configure(
        max_queue_depth=serve.DEFAULT_QUEUE_DEPTH,
        max_backlog_tokens=serve.DEFAULT_BACKLOG_TOKENS,
        tenant_quota_tokens=0,
        drain_deadline_s=serve.DEFAULT_DRAIN_DEADLINE_S,
        preempt_grace_s=0.0,
        interactive_ttft_slo_ms=0.0,
        max_dispatch_batch=4,
    )
    serve.reset_stats()
    dispatch.clear_engine_cache()
    fleet.configure(
        enabled=False,
        replicas=fleet.DEFAULT_REPLICAS,
        transport="inproc",
        autoscale=False,
        min_replicas=fleet.DEFAULT_MIN_REPLICAS,
        max_replicas=fleet.DEFAULT_MAX_REPLICAS,
        scale_cooldown_s=fleet.DEFAULT_SCALE_COOLDOWN_S,
        scale_interval_s=fleet.DEFAULT_SCALE_INTERVAL_S,
        prefill_replicas=fleet.DEFAULT_PREFILL_REPLICAS,
        handoff_threshold_tokens=fleet.DEFAULT_HANDOFF_THRESHOLD_TOKENS,
        min_prefill_replicas=fleet.DEFAULT_MIN_PREFILL_REPLICAS,
        max_prefill_replicas=fleet.DEFAULT_MAX_PREFILL_REPLICAS,
    )
    fleet.reset_stats()
    breaker.reset_default_registry()
    prefix_cache.configure(enabled=True, max_pages=0)
    prefix_cache.reset_stats()
    kvtier.configure(
        enabled=False,
        host_mb=kvtier.DEFAULT_HOST_MB,
        store_dir="",
        flush_blocks=0,
    )
    kvtier.reset_stats()
    weightres.configure(enabled=True, host_mb=weightres.DEFAULT_HOST_MB)
    weightres.reset_stats()
    streaming.configure(enabled=True, early_cancel=True)
    streaming.reset_stats()
    obs.configure(
        enabled=True,
        recorder_size=obs.DEFAULT_RECORDER_SIZE,
        events_out="",
        dump_on_fault=True,
        arrivals=False,
    )
    obs.reset_stats()
    obs.retrace.clear()
    faults.reset()
    injector.reset()
