"""Test bootstrap.

Runs on CPU with a virtual 8-device mesh (SURVEY §4: the reference mocks its
transport seam and runs everything above it for real; our analogs are the
mock engine plus ``--xla_force_host_platform_device_count=8`` so sharding
code executes real collectives in one process). Env vars must be set before
jax initializes, hence at conftest import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolate_state(tmp_path, monkeypatch):
    """Point every persistence dir at tmp and reset engine singletons."""
    from adversarial_spec_tpu.debate import session, profiles
    from adversarial_spec_tpu.engine import registry, dispatch

    monkeypatch.setattr(session, "SESSIONS_DIR", tmp_path / "sessions")
    monkeypatch.setattr(session, "CHECKPOINTS_DIR", tmp_path / "checkpoints")
    monkeypatch.setattr(profiles, "PROFILES_DIR", tmp_path / "profiles")
    monkeypatch.setattr(
        profiles, "GLOBAL_CONFIG_PATH", tmp_path / "config.json"
    )
    monkeypatch.setattr(registry, "REGISTRY_PATH", tmp_path / "registry.json")
    dispatch.clear_engine_cache()
    yield
    dispatch.clear_engine_cache()
