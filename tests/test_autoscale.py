"""Elastic-fleet autoscaler tests (fleet/autoscale.py).

Everything here is deterministic: the drills inject a mock clock, a
recording ``sleep``, and a scripted pressure snapshot, then call
``tick()`` directly — the same entry point the loop thread uses. The
contracts pinned:

- **warm-before-ring**: a scale-out replica is spawned, warmed (hottest
  models from the scheduler's mix), and pinged BEFORE ring admission —
  no request can ever route to a cold replica;
- **spawn hardening**: bounded jittered retry, a typed ``SpawnFailed``
  counted and cooled down (never a hot loop), and a replica that dies
  WHILE warming decommissioned without ever entering the ring;
- **lose-nothing scale-in**: least-affine victim, un-ring → drain →
  retire, zero duplicated completions;
- **flap control**: hysteresis streaks + cooldown bound membership
  churn under an oscillating pressure trace;
- **one lifecycle machine**: every exit reaches ``_decommission``
  (graftlint's fifth GL-LIFECYCLE machine, live-fire tested on the
  real source);
- the **mock-clock scale-storm** (``chaos`` marker): the deterministic
  variant of ``tools/chaos_run.py --scale-storm`` — grow to ceiling,
  shrink to floor, ~1/N key movement per membership change.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from adversarial_spec_tpu import fleet as fleet_mod
from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu import serve as serve_mod
from adversarial_spec_tpu.fleet import replica as replica_mod
from adversarial_spec_tpu.fleet.autoscale import (
    DRAINING,
    PROVISIONING,
    RETIRED,
    SERVING,
    WARMING,
    Autoscaler,
)
from adversarial_spec_tpu.fleet.hashring import HashRing
from adversarial_spec_tpu.fleet.replica import ReplicaDead, SpawnFailed
from adversarial_spec_tpu.fleet.router import FleetEngine


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _pressure(
    backlog=0, brownout=False, draining=False, keys=(), mix=None
):
    """A scripted pressure_snapshot provider (constant)."""
    snap = {
        "backlog_tokens": backlog,
        "brownout": brownout,
        "draining": draining,
        "active_keys": list(keys),
        "model_mix": dict(mix or {}),
    }
    return lambda: dict(snap)


def _elastic_cfg(**kw):
    base = dict(
        enabled=True,
        replicas=1,
        transport="inproc",
        autoscale=True,
        min_replicas=1,
        max_replicas=3,
        scale_out_fraction=0.6,
        scale_in_fraction=0.15,
        scale_out_ticks=1,
        scale_in_ticks=1,
        scale_cooldown_s=0.0,
        scale_interval_s=0.01,
    )
    base.update(kw)
    return fleet_mod.configure(**base)


def _scale_ops(replica=None):
    return [
        (e["op"], e["replica"], e["reason"])
        for e in obs_mod.recorder.events()
        if e["type"] == "scale"
        and (replica is None or e["replica"] == replica)
    ]


class TestScaleOut:
    def test_warm_before_ring_with_hot_model_preload(self):
        """THE scale-out contract: the new replica is warmed (with the
        hottest models from the scheduler's mix, capped at the top-K)
        and pinged while still INVISIBLE to the ring — admission is the
        last step, so no request ever routes to a cold replica."""
        _elastic_cfg()
        obs_mod.reset_stats()
        eng = FleetEngine(replicas=1)
        # 6 models, hottest first — the warm-up must take the top 4.
        mix = {f"mock://critic?v={k}": 9 - k for k in range(6)}
        scaler = Autoscaler(
            eng,
            pressure=_pressure(backlog=10**6, brownout=True, mix=mix),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        ringed_at_warm: list[bool] = []
        warmed_with: list[list[str]] = []
        orig_spawn = eng.spawn_replica

        def spawn(rid=None, **kw):
            rep = orig_spawn(rid, **kw)
            orig_warm = rep.warm

            def warm(models):
                ringed_at_warm.append(rep.id in eng.router.alive_ids())
                warmed_with.append(list(models))
                return orig_warm(models)

            rep.warm = warm
            return rep

        eng.spawn_replica = spawn
        try:
            assert scaler.tick() is True
            assert ringed_at_warm == [False]
            assert warmed_with == [
                [f"mock://critic?v={k}" for k in range(4)]
            ]
            assert sorted(eng.router.alive_ids()) == ["r0", "r1"]
            assert scaler.member_state("r1") == SERVING
            assert fleet_mod.stats.scale_outs == 1
            # The lifecycle edges, in order, in the flight recorder.
            assert [op for op, _, _ in _scale_ops("r1")] == [
                "provision",
                "warming",
                "serving",
            ]
            # Counter + gauge pair: scale total by (direction, reason),
            # desired tracking actual.
            assert (
                obs_mod.hot.fleet_scale("out", "brownout").value == 1.0
            )
            assert obs_mod.hot.fleet_replicas_desired.value == 2.0
            assert obs_mod.hot.fleet_replicas_alive.value == 2.0
        finally:
            scaler.shutdown()
            eng.shutdown()

    def test_ceiling_is_hard(self):
        _elastic_cfg(max_replicas=2)
        eng = FleetEngine(replicas=2)
        spawns: list[str] = []
        eng.spawn_replica = lambda rid=None, **kw: spawns.append(rid)
        scaler = Autoscaler(
            eng,
            pressure=_pressure(backlog=10**9, brownout=True),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            for _ in range(5):
                assert scaler.tick() is False
            assert spawns == []
            assert len(eng.router.alive_ids()) == 2
        finally:
            scaler.shutdown()
            eng.shutdown()

    def test_daemon_drain_freezes_scaling(self):
        """A draining daemon must not grow the fleet it is abandoning."""
        _elastic_cfg()
        eng = FleetEngine(replicas=1)
        scaler = Autoscaler(
            eng,
            pressure=_pressure(backlog=10**9, brownout=True, draining=True),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            assert scaler.tick() is False
            assert len(eng.router.alive_ids()) == 1
        finally:
            scaler.shutdown()
            eng.shutdown()


class TestSpawnHardening:
    def test_bounded_retry_backoff_is_jittered_and_typed(self, monkeypatch):
        """spawn_replica semantics: each failed attempt tears down and
        retries after ``base * 2^k * (0.5 + U[0,1))``; after the
        retries exhaust the typed SpawnFailed carries the attempt
        count. Injected sleep/rng make the jitter exact."""

        class _NeverUp:
            def __init__(self, rid, engine_factory=None, role=""):
                self.id = rid
                self.role = role
                self.closed = False

            def ping(self):
                return False

            def close(self):
                self.closed = True

        monkeypatch.setattr(replica_mod, "InProcessReplica", _NeverUp)
        sleeps: list[float] = []
        with pytest.raises(SpawnFailed) as ei:
            replica_mod.spawn_replica(
                "r9",
                "inproc",
                retries=2,
                backoff_base_s=0.05,
                sleep=sleeps.append,
                rng=lambda: 0.5,
            )
        assert ei.value.attempts == 3
        assert ei.value.replica == "r9"
        assert sleeps == pytest.approx([0.05, 0.1])  # 0.05*2^k*(0.5+0.5)

    def test_spawn_failed_counted_and_cooled_never_hot_loops(self):
        """A broken spawn path must not be retried every tick: the
        failure enters cooldown exactly like a membership change, so
        the retry rate is bounded by scale_cooldown_s."""
        _elastic_cfg(scale_cooldown_s=5.0)
        obs_mod.reset_stats()
        eng = FleetEngine(replicas=1)
        attempts: list[str] = []

        def failing_spawn(rid=None, **kw):
            attempts.append(rid)
            raise SpawnFailed(rid, 4, "scripted")

        eng.spawn_replica = failing_spawn
        clock = FakeClock()
        scaler = Autoscaler(
            eng,
            pressure=_pressure(brownout=True),
            clock=clock,
            sleep=lambda s: None,
        )
        try:
            assert scaler.tick() is False
            assert fleet_mod.stats.spawn_failures == 1
            assert attempts == ["r1"]
            assert scaler.member_state("r1") == RETIRED
            assert "r1" not in eng.router.alive_ids()
            assert scaler.desired == 1  # target restored
            # Still inside the cooldown: pressure persists but no new
            # spawn attempt happens — the veto is counted as a
            # suppressed flap.
            clock.advance(1.0)
            assert scaler.tick() is False
            assert attempts == ["r1"]
            assert fleet_mod.stats.flaps_suppressed == 1
            # Past the cooldown the controller tries again.
            clock.advance(5.0)
            scaler.tick()
            assert len(attempts) == 2
            assert fleet_mod.stats.spawn_failures == 2
            assert ("spawn_failed", "r1", "spawn_failed") in _scale_ops()
        finally:
            scaler.shutdown()
            eng.shutdown()

    def test_dies_while_warming_decommissioned_never_ringed(self):
        """Regression pin: a replica that dies BETWEEN spawn and ring
        admission is decommissioned through the surgery — transport
        closed, member RETIRED — and the ring never saw it."""
        _elastic_cfg()
        obs_mod.reset_stats()
        eng = FleetEngine(replicas=1)
        spawned = []
        orig_spawn = eng.spawn_replica

        def spawn(rid=None, **kw):
            rep = orig_spawn(rid, **kw)

            def dying_warm(models):
                raise ReplicaDead(rep.id, "died mid-warm")

            rep.warm = dying_warm
            spawned.append(rep)
            return rep

        eng.spawn_replica = spawn
        scaler = Autoscaler(
            eng,
            pressure=_pressure(brownout=True),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            assert scaler.tick() is False
            (rep,) = spawned
            assert rep.id not in eng.router.alive_ids()
            assert scaler.member_state(rep.id) == RETIRED
            assert rep.closed  # decommission closed the transport
            assert fleet_mod.stats.scale_outs == 0
            assert [op for op, _, _ in _scale_ops(rep.id)] == [
                "provision",
                "warming",
                "spawn_failed",
                "retired",
            ]
            retired = [
                e
                for e in obs_mod.recorder.events()
                if e["type"] == "scale" and e["op"] == "retired"
            ]
            assert retired[0]["reason"] == "warm_failed"
            # The router never emitted "ready" for it: never routable.
            readies = [
                e["replica"]
                for e in obs_mod.recorder.events()
                if e["type"] == "replica" and e["op"] == "ready"
            ]
            assert rep.id not in readies
        finally:
            scaler.shutdown()
            eng.shutdown()


class TestFlapControl:
    def test_hysteresis_requires_consecutive_ticks(self):
        """An oscillating pressure trace (pressure every OTHER tick)
        never reaches a 2-tick streak: zero membership changes."""
        _elastic_cfg(scale_out_ticks=2)
        eng = FleetEngine(replicas=1)
        snap = {"backlog_tokens": 0, "brownout": False}
        scaler = Autoscaler(
            eng,
            pressure=lambda: dict(snap),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            for i in range(8):
                snap["brownout"] = i % 2 == 0
                assert scaler.tick() is False
            assert fleet_mod.stats.scale_outs == 0
            assert len(eng.router.alive_ids()) == 1
            # Sustained pressure DOES cross the streak.
            snap["brownout"] = True
            assert scaler.tick() is False
            assert scaler.tick() is True
            assert len(eng.router.alive_ids()) == 2
        finally:
            scaler.shutdown()
            eng.shutdown()

    def test_cooldown_vetoes_and_counts_flaps(self):
        _elastic_cfg(scale_cooldown_s=10.0)
        eng = FleetEngine(replicas=1)
        clock = FakeClock()
        scaler = Autoscaler(
            eng,
            pressure=_pressure(brownout=True),
            clock=clock,
            sleep=lambda s: None,
        )
        try:
            assert scaler.tick() is True  # first change is free
            for _ in range(4):
                clock.advance(1.0)
                assert scaler.tick() is False
            assert fleet_mod.stats.flaps_suppressed == 4
            assert fleet_mod.stats.scale_outs == 1
            assert len(eng.router.alive_ids()) == 2
            clock.advance(10.0)  # past the cooldown: allowed again
            assert scaler.tick() is True
            assert len(eng.router.alive_ids()) == 3
        finally:
            scaler.shutdown()
            eng.shutdown()

    def test_out_and_in_thresholds_cannot_overlap(self):
        """want_in measures against the SHRUNK capacity (n-1), so for
        any backlog at most one of want_out/want_in can hold — no
        pressure value oscillates the controller by itself."""
        _elastic_cfg(scale_out_ticks=1, scale_in_ticks=1)
        eng = FleetEngine(replicas=2)
        cfg = fleet_mod.config()
        per = serve_mod.config().max_backlog_tokens
        out_at = cfg.scale_out_fraction * per * 2
        in_at = cfg.scale_in_fraction * per * 1
        assert in_at < out_at  # the dead band exists
        # A backlog inside the band: neither direction fires.
        scaler = Autoscaler(
            eng,
            pressure=_pressure(backlog=int((in_at + out_at) / 2)),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            for _ in range(5):
                assert scaler.tick() is False
            assert len(eng.router.alive_ids()) == 2
        finally:
            scaler.shutdown()
            eng.shutdown()


class TestScaleIn:
    def test_least_affine_victim_drains_then_retires(self):
        """Scale-in order: the victim (owning the FEWEST active keys)
        leaves the ring first, in-flight units drain while survivors
        take new work, then the lifecycle retires it — and the whole
        handoff duplicates nothing."""
        _elastic_cfg(replicas=3, scale_cooldown_s=1.0)
        obs_mod.reset_stats()
        eng = FleetEngine(replicas=3)
        keys = [f"debate-{i}" for i in range(60)]
        load = eng.router.affinity_load(keys)
        expected = min(
            eng.router.alive_ids(),
            key=lambda rid: (load.get(rid, 0), -int(rid[1:])),
        )
        clock = FakeClock()
        # The victim reports in-flight work for 3 drain polls; each
        # poll must observe it OUT of the ring with its transport OPEN.
        state = {"polls": 3, "observed": []}

        def inflight(rid):
            state["observed"].append(
                (
                    rid in eng.router.alive_ids(),
                    eng.router.replica(rid).closed,
                )
            )
            if state["polls"] > 0:
                state["polls"] -= 1
                return 1
            return 0

        eng.router.inflight = inflight
        sleeps: list[float] = []

        def sleep(s):
            sleeps.append(s)
            clock.advance(s)

        scaler = Autoscaler(
            eng,
            pressure=_pressure(backlog=0, keys=keys),
            clock=clock,
            sleep=sleep,
        )
        try:
            assert scaler.tick() is True
            assert expected not in eng.router.alive_ids()
            assert len(eng.router.alive_ids()) == 2
            assert scaler.member_state(expected) == RETIRED
            assert fleet_mod.stats.scale_ins == 1
            assert fleet_mod.stats.duplicated_completions == 0
            assert len(sleeps) == 3  # drained, not deadline-killed
            # Every drain poll saw: un-ringed, transport still open.
            assert state["observed"][:3] == [(False, False)] * 3
            assert [op for op, _, _ in _scale_ops(expected)] == [
                "draining",
                "retired",
            ]
            assert obs_mod.hot.fleet_scale("in", "idle").value == 1.0
        finally:
            scaler.shutdown()
            eng.shutdown()

    def test_floor_is_hard(self):
        _elastic_cfg(min_replicas=1)
        eng = FleetEngine(replicas=1)
        scaler = Autoscaler(
            eng,
            pressure=_pressure(backlog=0),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            for _ in range(5):
                assert scaler.tick() is False
            assert len(eng.router.alive_ids()) == 1
            assert fleet_mod.stats.scale_ins == 0
        finally:
            scaler.shutdown()
            eng.shutdown()

    def test_stalled_victim_is_retired_at_the_drain_deadline(self):
        """A victim that never drains is retired mid-batch — the
        planned handoff degrades to the ReplicaDead-remainder path
        instead of wedging the controller."""
        _elastic_cfg(replicas=2, min_replicas=1, scale_cooldown_s=0.05)
        eng = FleetEngine(replicas=2)
        eng.router.inflight = lambda rid: 1  # never drains
        clock = FakeClock()
        scaler = Autoscaler(
            eng,
            pressure=_pressure(backlog=0),
            clock=clock,
            sleep=lambda s: clock.advance(s),
        )
        try:
            assert scaler.tick() is True
            assert len(eng.router.alive_ids()) == 1
            assert fleet_mod.stats.scale_ins == 1
        finally:
            scaler.shutdown()
            eng.shutdown()


class TestLifecycle:
    def test_reconcile_funnels_router_retirements(self):
        """The router retiring a member behind the controller's back
        (heartbeat miss) reaches the SAME surgery on the next tick, so
        the two machines never disagree about who is alive."""
        _elastic_cfg(replicas=2)
        obs_mod.reset_stats()
        eng = FleetEngine(replicas=2)
        scaler = Autoscaler(
            eng,
            pressure=_pressure(),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            eng.router._retire_replica("r0", "heartbeat")
            scaler.tick()
            assert scaler.member_state("r0") == RETIRED
            retired = [
                e
                for e in obs_mod.recorder.events()
                if e["type"] == "scale"
                and e["op"] == "retired"
                and e["replica"] == "r0"
            ]
            assert retired and retired[0]["reason"] == "heartbeat"
        finally:
            scaler.shutdown()
            eng.shutdown()

    def test_shutdown_decommissions_mid_transition_members_only(self):
        """Exit path: shutdown closes never-ringed pending transports
        and retires draining members, but leaves SERVING members to the
        fleet engine's own shutdown (they are the fleet, not the
        controller's transients)."""
        _elastic_cfg()
        eng = FleetEngine(replicas=1)

        class _Transport:
            closed = False

            def close(self):
                self.closed = True

        t = _Transport()
        scaler = Autoscaler(
            eng,
            pressure=_pressure(),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        scaler._members["r9"] = WARMING
        scaler._pending["r9"] = t
        scaler.shutdown()
        assert t.closed
        assert scaler.member_state("r9") == RETIRED
        assert scaler.member_state("r0") == SERVING
        assert eng.router.alive_ids() == ["r0"]
        eng.shutdown()

    def test_decommission_is_idempotent(self):
        _elastic_cfg()
        eng = FleetEngine(replicas=1)
        scaler = Autoscaler(
            eng,
            pressure=_pressure(),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            scaler._decommission("r0", "scale_in", direction="in")
            before = eng.router._dead.get("r0")
            scaler._decommission("r0", "other")  # second is a no-op
            assert eng.router._dead["r0"] == before == "scale_in"
            assert scaler.member_state("r0") == RETIRED
        finally:
            scaler.shutdown()
            eng.shutdown()


class TestServeCoupling:
    def test_capacity_provider_stretches_admission_and_brownout(self):
        """The elastic half of admission control: the backlog cap (and
        with it the brownout thresholds) scales with the routable
        replica count; a broken provider fails safe to factor 1."""
        from adversarial_spec_tpu.serve.sched import ServeScheduler

        serve_mod.configure(max_backlog_tokens=1000)
        sched = ServeScheduler()
        shed = sched.try_admit("t0", "interactive", "d1", 1500)
        assert shed is not None and shed.reason == "backlog"
        sched.set_capacity_provider(lambda: 2)
        assert sched.try_admit("t0", "interactive", "d1", 1500) is None
        snap = sched.pressure_snapshot()
        assert snap["capacity_tokens"] == 2000
        assert snap["backlog_tokens"] == 1500
        assert "d1" in snap["active_keys"]
        sched.set_capacity_provider(lambda: 1 / 0)
        assert sched._capacity_tokens(serve_mod.config()) == 1000

    def test_model_mix_feeds_the_warm_preload_hottest_first(self):
        from adversarial_spec_tpu.serve.sched import ServeScheduler

        serve_mod.configure(max_backlog_tokens=10**6)
        sched = ServeScheduler()
        assert (
            sched.try_admit(
                "t0", "batch", "d1", 10, models=["m-b", "m-a"]
            )
            is None
        )
        assert (
            sched.try_admit("t0", "batch", "d2", 10, models=["m-a"])
            is None
        )
        mix = sched.pressure_snapshot()["model_mix"]
        assert list(mix) == ["m-a", "m-b"]  # hottest first, name ties
        assert mix == {"m-a": 2, "m-b": 1}


class TestScaleEvents:
    def test_scale_event_validation(self):
        from adversarial_spec_tpu.obs.events import (
            SCALE_DIRECTIONS,
            SCALE_OPS,
            ScaleEvent,
            event_to_dict,
            validate_event,
        )

        good = event_to_dict(
            1,
            ScaleEvent(
                replica="r1",
                op="serving",
                direction="out",
                reason="backlog",
                desired=2,
                alive=2,
                backlog_tokens=4096,
            ),
        )
        assert validate_event(json.loads(json.dumps(good))) == []
        assert validate_event(event_to_dict(2, ScaleEvent(op="grew")))
        assert validate_event(
            event_to_dict(3, ScaleEvent(direction="sideways"))
        )
        # The wire enum mirrors the lifecycle states (the provision
        # EDGE is named for the transition, not the state), plus the
        # one non-state edge.
        for state in (WARMING, SERVING, DRAINING, RETIRED):
            assert state in SCALE_OPS
        assert "provision" in SCALE_OPS
        assert "spawn_failed" in SCALE_OPS
        assert PROVISIONING == "provisioning"
        assert SCALE_DIRECTIONS == ("out", "in", "")


class TestAutoscaleLifecycleLint:
    def test_exit_skipping_the_decommission_surgery_fires(self):
        """GL-LIFECYCLE's autoscaler machine is LIVE on the real
        source: a scale-in exit that marks the member RETIRED directly
        instead of funnelling through _decommission is permanently
        caught."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        src = Path("adversarial_spec_tpu/fleet/autoscale.py").read_text(
            encoding="utf-8"
        )
        broken = src.replace(
            '        self._decommission(rid, "scale_in", direction="in")\n',
            "        self._members[rid] = RETIRED\n",
        )
        assert broken != src, "scale-in surgery call not found to strip"
        cfg = GraftlintConfig(package="pkg")
        findings = lint_sources(
            {"pkg/autoscale.py": broken}, rules=["GL-LIFECYCLE"], cfg=cfg
        )
        msgs = [f.message for f in findings]
        assert any(
            "Autoscaler._finish_scale_in never reaches" in m for m in msgs
        ), msgs
        # The committed source is clean under the same config.
        assert (
            lint_sources(
                {"pkg/autoscale.py": src}, rules=["GL-LIFECYCLE"], cfg=cfg
            )
            == []
        )


def _movement(before: list[str], after: list[str]) -> float:
    """Fraction of a fixed key sample whose primary owner changes
    between two memberships (real HashRing math — mirrors
    tools/chaos_run.py _ring_movement)."""
    ra, rb = HashRing(before), HashRing(after)
    n = 2000
    moved = sum(
        1
        for k in range(n)
        if ra.primary(f"debate-{k}") != rb.primary(f"debate-{k}")
    )
    return moved / n


@pytest.mark.chaos
class TestMockClockScaleStorm:
    """The deterministic variant of ``tools/chaos_run.py
    --scale-storm``: a scripted backlog step drives the controller to
    the ceiling, the trough drives it back to the floor, and every
    membership change moves ~1/N of the keyspace — on a mock clock, so
    the whole storm is replayable tick for tick."""

    def test_storm_grows_to_ceiling_shrinks_to_floor(self):
        _elastic_cfg(
            scale_out_ticks=2,
            scale_in_ticks=3,
            scale_cooldown_s=1.0,
            max_replicas=3,
        )
        eng = FleetEngine(replicas=1)
        clock = FakeClock()
        snap = {
            "backlog_tokens": 0,
            "brownout": False,
            "active_keys": [],
            "model_mix": {},
        }
        ringed_at_warm: list[bool] = []
        orig_spawn = eng.spawn_replica

        def spawn(rid=None, **kw):
            rep = orig_spawn(rid, **kw)
            orig_warm = rep.warm

            def warm(models):
                ringed_at_warm.append(rep.id in eng.router.alive_ids())
                return orig_warm(models)

            rep.warm = warm
            return rep

        eng.spawn_replica = spawn
        scaler = Autoscaler(
            eng,
            pressure=lambda: dict(snap),
            clock=clock,
            sleep=lambda s: clock.advance(s),
        )
        memberships = [sorted(eng.router.alive_ids())]

        def tick():
            changed = scaler.tick()
            clock.advance(0.5)
            if changed:
                memberships.append(sorted(eng.router.alive_ids()))

        per = serve_mod.config().max_backlog_tokens
        try:
            # The step: sustained heavy backlog -> grow to the ceiling.
            snap["backlog_tokens"] = 10 * per
            for _ in range(10):
                tick()
            assert len(eng.router.alive_ids()) == 3
            # Warm-before-ring held for every growth step.
            assert ringed_at_warm == [False, False]
            # The trough: backlog drains -> shrink to the floor.
            snap["backlog_tokens"] = 0
            for _ in range(20):
                tick()
            assert len(eng.router.alive_ids()) == 1
            # Exactly the 4 planned changes — no flapping beyond them.
            assert fleet_mod.stats.scale_outs == 2
            assert fleet_mod.stats.scale_ins == 2
            assert fleet_mod.stats.duplicated_completions == 0
            # ~1/N of the keyspace moved per membership change.
            assert len(memberships) == 5
            for before, after in zip(memberships, memberships[1:]):
                n_ref = max(len(before), len(after))
                frac = _movement(before, after)
                assert 0.5 / n_ref <= frac <= min(1.0, 2.0 / n_ref), (
                    before,
                    after,
                    frac,
                )
            # Survivor invariants clean (the drill's `check` op).
            eng.router.check_invariants()
        finally:
            scaler.shutdown()
            eng.shutdown()


class TestRoleAwareScaling:
    """Disaggregated fleets scale per role: prefill-token backlog sizes
    the prefill pool, the decode remainder sizes the decode pool, each
    under its own floor/ceiling (docs/fleet.md "Disaggregation")."""

    def _role_pressure(self, prefill=0, decode=0, brownout=False):
        snap = {
            "backlog_tokens": prefill + decode,
            "prefill_backlog_tokens": prefill,
            "decode_backlog_tokens": decode,
            "brownout": brownout,
            "draining": False,
            "active_keys": [],
            "model_mix": {},
        }
        return lambda: dict(snap)

    def test_prefill_backlog_grows_only_the_prefill_pool(self):
        _elastic_cfg(
            replicas=2,
            max_replicas=4,
            min_prefill_replicas=1,
            max_prefill_replicas=2,
        )
        obs_mod.reset_stats()
        eng = FleetEngine(replicas=2, prefill_replicas=1)
        scaler = Autoscaler(
            eng,
            pressure=self._role_pressure(prefill=10**6, decode=0),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            assert scaler.tick() is True
            assert sorted(eng.router.alive_ids("prefill")) == ["r0", "r2"]
            assert eng.router.alive_ids("decode") == ["r1"]  # untouched
            # Ceiling is per-pool: the prefill pool is now full, so the
            # same pressure cannot grow it past max_prefill_replicas.
            assert scaler.tick() is False
        finally:
            scaler.shutdown()
            eng.shutdown()

    def test_idle_decode_pool_shrinks_to_its_own_floor(self):
        _elastic_cfg(
            replicas=3,
            min_replicas=1,
            max_replicas=4,
            min_prefill_replicas=1,
            max_prefill_replicas=2,
        )
        obs_mod.reset_stats()
        eng = FleetEngine(replicas=3, prefill_replicas=1)
        scaler = Autoscaler(
            eng,
            pressure=self._role_pressure(prefill=0, decode=0),
            clock=FakeClock(),
            sleep=lambda s: None,
        )
        try:
            # Decode pool (r1, r2) is idle above its floor: one leaves.
            assert scaler.tick() is True
            assert len(eng.router.alive_ids("decode")) == 1
            # Both pools now sit AT their floors: idleness changes
            # nothing — disaggregation never scales a pool to zero.
            assert scaler.tick() is False
            assert eng.router.alive_ids("prefill") == ["r0"]
            assert len(eng.router.alive_ids("decode")) == 1
        finally:
            scaler.shutdown()
            eng.shutdown()
