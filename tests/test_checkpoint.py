"""Native (Orbax) checkpoint cache tests: HF converts once, restores fast
and bit-identically thereafter."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine import checkpoint as ckpt_mod
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config


@pytest.fixture()
def hf_tiny_checkpoint(tmp_path):
    torch = pytest.importorskip("torch")
    import transformers

    cfg = get_config("llama", "tiny")
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        intermediate_size=cfg.ffn_dim,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_eps,
        max_position_embeddings=256,
    )
    torch.manual_seed(0)
    model = transformers.AutoModelForCausalLM.from_config(hf_cfg)
    ckpt = tmp_path / "hf"
    model.save_pretrained(ckpt, safe_serialization=True)
    return str(ckpt)


class TestNativeCacheRoundtrip:
    def test_save_load_identical(self, tmp_path):
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        cache_dir = tmp_path / "native" / "abc"
        ckpt_mod.save_native(params, cache_dir)
        assert ckpt_mod.has_native(cache_dir)
        restored = ckpt_mod.load_native(
            cache_dir, ckpt_mod.abstract_like(params)
        )
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_fingerprint_distinguishes_configs(self):
        a = ckpt_mod.cache_dir_for("/x", "llama", "8b", "bfloat16")
        b = ckpt_mod.cache_dir_for("/x", "llama", "8b", "bfloat16", "int8")
        c = ckpt_mod.cache_dir_for("/x", "llama", "70b", "bfloat16")
        assert len({a.name, b.name, c.name}) == 3
        assert a.parent == b.parent == c.parent

    def test_fingerprint_transposed_head_flag(self, monkeypatch):
        """The ADVSPEC_TRANSPOSED_HEAD toggle changes the pytree layout
        ONLY for tied-embedding configs — the fingerprint must follow
        exactly that (ADVICE r2: a template/cache layout mismatch caused
        permanent cache thrash; an untied flag-sensitivity would cause
        spurious reconversion)."""
        kw = dict(dtype="bfloat16", tied_embeddings=True)
        monkeypatch.setenv("ADVSPEC_TRANSPOSED_HEAD", "1")
        tied_on = ckpt_mod.cache_dir_for("/x", "llama", "1b", **kw)
        untied_on = ckpt_mod.cache_dir_for("/x", "llama", "1b", "bfloat16")
        monkeypatch.setenv("ADVSPEC_TRANSPOSED_HEAD", "0")
        tied_off = ckpt_mod.cache_dir_for("/x", "llama", "1b", **kw)
        untied_off = ckpt_mod.cache_dir_for("/x", "llama", "1b", "bfloat16")
        assert tied_on.name != tied_off.name  # layout differs → new dir
        assert untied_on.name == untied_off.name  # same layout → same dir
        assert tied_off.name == untied_off.name  # both lack lm_head_t

    def test_atomic_save_no_tmp_left(self, tmp_path):
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        cache_dir = tmp_path / "n" / "fp"
        ckpt_mod.save_native(params, cache_dir)
        assert not (tmp_path / "n" / "fp.tmp").exists()

    def test_save_sweeps_stale_abandoned_tmp(self, tmp_path):
        """A writer killed mid-save (daemon thread at exit, OOM-kill)
        leaves its tmp dir; the next save removes day-old orphans but
        never a fresh sibling (a live concurrent writer's)."""
        import os
        import time

        parent = tmp_path / "n"
        parent.mkdir()
        stale = parent / "fp.tmp-999-aaaaaa"
        fresh = parent / "fp.tmp-998-bbbbbb"
        stale.mkdir()
        fresh.mkdir()
        old = time.time() - 2 * 86400
        os.utime(stale, (old, old))

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        ckpt_mod.save_native(params, parent / "fp")
        assert not stale.exists()
        assert fresh.exists()


class TestCacheRobustness:
    def test_fingerprint_changes_when_weights_replaced(self, tmp_path):
        ckpt = tmp_path / "hf"
        ckpt.mkdir()
        f = ckpt / "model.safetensors"
        f.write_bytes(b"v1-weights")
        a = ckpt_mod.cache_dir_for(str(ckpt), "llama", "8b", "bfloat16")
        f.write_bytes(b"v2-weights-longer")  # in-place update
        b = ckpt_mod.cache_dir_for(str(ckpt), "llama", "8b", "bfloat16")
        assert a.name != b.name

    def test_corrupt_cache_falls_back_to_hf(
        self, hf_tiny_checkpoint, monkeypatch, capsys
    ):
        from adversarial_spec_tpu.engine.registry import (
            ModelSpec,
            save_registry_entry,
        )
        from adversarial_spec_tpu.engine.tpu import TpuEngine
        from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

        save_registry_entry(
            ModelSpec(
                alias="hf-tiny2",
                family="llama",
                size="tiny",
                checkpoint=hf_tiny_checkpoint,
                dtype="float32",
            )
        )
        cache_path = ckpt_mod.cache_dir_for(
            hf_tiny_checkpoint, "llama", "tiny", "float32", ""
        )
        cache_path.mkdir(parents=True)
        (cache_path / "garbage").write_text("not an orbax checkpoint")

        comp = TpuEngine().chat(
            [ChatRequest(model="tpu://hf-tiny2", system="s", user="u")],
            SamplingParams(max_new_tokens=4, greedy=True),
        )[0]
        assert comp.ok, comp.error  # fell back to HF conversion
        err = capsys.readouterr().err
        assert "cache unreadable" in err


class TestEngineUsesNativeCache:
    def test_second_load_hits_cache_and_matches(
        self, hf_tiny_checkpoint, monkeypatch
    ):
        from adversarial_spec_tpu.engine import loader as loader_mod
        from adversarial_spec_tpu.engine.registry import (
            ModelSpec,
            save_registry_entry,
        )
        from adversarial_spec_tpu.engine.tpu import TpuEngine
        from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

        save_registry_entry(
            ModelSpec(
                alias="hf-tiny",
                family="llama",
                size="tiny",
                checkpoint=hf_tiny_checkpoint,
                dtype="float32",
            )
        )
        params = SamplingParams(max_new_tokens=4, greedy=True)
        req = ChatRequest(model="tpu://hf-tiny", system="s", user="u")

        eng1 = TpuEngine()
        first = eng1.chat([req], params)[0]
        assert first.ok, first.error
        cache_path = ckpt_mod.cache_dir_for(
            hf_tiny_checkpoint, "llama", "tiny", "float32", ""
        )
        assert ckpt_mod.has_native(cache_path)

        # Fresh engine: safetensors conversion must NOT run again.
        def boom(*a, **k):
            raise AssertionError("HF conversion ran despite native cache")

        monkeypatch.setattr(loader_mod, "load_hf_checkpoint", boom)
        eng2 = TpuEngine()
        second = eng2.chat([req], params)[0]
        assert second.ok, second.error
        assert second.text == first.text  # identical params → identical greedy
