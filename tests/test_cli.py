"""CLI tests (reference analog: tests/test_cli.py — argv/stdin/stdout
patching around main(), JSON schema assertions, exit codes)."""

import io
import json

import pytest

from adversarial_spec_tpu import cli
from adversarial_spec_tpu.debate.session import SessionState
from adversarial_spec_tpu.debate import session as session_mod

SPEC = "# Cache Service\n\nA read-through cache."


def run_cli(argv, stdin=None, monkeypatch=None, capsys=None):
    assert monkeypatch is not None and capsys is not None
    if stdin is not None:
        monkeypatch.setattr("sys.stdin", io.StringIO(stdin))
    code = cli.main(argv)
    out, err = capsys.readouterr()
    return code, out, err


class TestCritique:
    def test_text_output(self, monkeypatch, capsys):
        code, out, err = run_cli(
            ["critique", "--models", "mock://agree,mock://critic"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "=== Round 1 Results" in out
        assert "mock://agree" in out
        assert "Critiqued: mock://critic" in out
        assert "querying 2 model(s)" in err  # progress goes to stderr

    def test_json_schema(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["critique", "--models", "mock://critic", "--json", "--doc-type", "tech"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        data = json.loads(out)
        # Schema parity with reference debate.py:909-941.
        for key in (
            "all_agreed",
            "round",
            "doc_type",
            "models",
            "focus",
            "persona",
            "preserve_intent",
            "session",
            "results",
            "cost",
        ):
            assert key in data, key
        r = data["results"][0]
        for key in (
            "model",
            "agreed",
            "response",
            "spec",
            "error",
            "input_tokens",
            "output_tokens",
            "cost",
        ):
            assert key in r, key
        assert data["doc_type"] == "tech"
        assert data["all_agreed"] is False

    def test_all_agree_banner(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["critique", "--models", "mock://agree"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert "=== ALL MODELS AGREE ===" in out

    def test_empty_stdin_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["critique"], stdin="", monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 2
        assert "no spec" in err

    def test_unknown_provider_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["critique", "--models", "openai/gpt-4o"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2
        assert "validation error" in err

    def test_unknown_tpu_alias_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["critique", "--models", "tpu://nope"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2
        assert "unknown tpu model alias" in err

    def test_show_cost(self, monkeypatch, capsys):
        _, out, _ = run_cli(
            ["critique", "--models", "mock://critic", "--show-cost"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert "Cost summary:" in out

    def test_failed_model_warns_but_succeeds(self, monkeypatch, capsys):
        code, out, err = run_cli(
            ["critique", "--models", "mock://agree,mock://error"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "warning: mock://error failed" in err
        assert "ERROR:" in out


class TestSessions:
    def test_session_saved_and_resumable(self, monkeypatch, capsys):
        code, _, _ = run_cli(
            [
                "critique",
                "--models",
                "mock://critic",
                "--session",
                "s1",
                "--doc-type",
                "tech",
                "--focus",
                "security",
            ],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        state = SessionState.load("s1")
        assert state.round == 2  # advanced past round 1
        assert state.models == ["mock://critic"]
        assert state.focus == "security"
        assert "Revision note" in state.spec  # revised spec carried forward

        # Resume: no stdin needed, args restored from session.
        code2, out2, _ = run_cli(
            ["critique", "--resume", "s1", "--json"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code2 == 0
        data = json.loads(out2)
        assert data["round"] == 2
        assert data["doc_type"] == "tech"
        assert data["session"] == "s1"

    def test_checkpoint_written(self, monkeypatch, capsys):
        run_cli(
            ["critique", "--models", "mock://critic", "--session", "ck"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        ckpt = session_mod.CHECKPOINTS_DIR / "ck-round-1.md"
        assert ckpt.is_file()
        assert ckpt.read_text() == SPEC

    def test_sessions_listing(self, monkeypatch, capsys):
        SessionState(session_id="listed", spec="s").save()
        code, out, _ = run_cli(
            ["sessions"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "listed" in out


class TestInfoActions:
    def test_focus_areas(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["focus-areas", "--json"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert set(json.loads(out)) == {
            "security",
            "scalability",
            "performance",
            "ux",
            "reliability",
            "cost",
        }

    def test_personas(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["personas", "--json"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert len(json.loads(out)) == 10

    def test_providers_lists_builtin_registry(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["providers", "--json"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        data = json.loads(out)
        models = {e["model"] for e in data["tpu"]}
        assert "tpu://random-tiny" in models
        assert all(e["available"] for e in data["tpu"] if "random" in e["model"])


class TestProfiles:
    def test_save_and_use_profile(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            [
                "save-profile",
                "--name",
                "secfast",
                "--models",
                "mock://agree",
                "--focus",
                "security",
                "--doc-type",
                "prd",
            ],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0

        code2, out2, err2 = run_cli(
            ["critique", "--profile", "secfast", "--json"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code2 == 0
        data = json.loads(out2)
        assert data["models"] == ["mock://agree"]
        assert data["focus"] == "security"
        assert data["doc_type"] == "prd"

    def test_profile_does_not_override_flags(self, monkeypatch, capsys):
        run_cli(
            ["save-profile", "--name", "p", "--doc-type", "prd"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        code, out, _ = run_cli(
            [
                "critique",
                "--profile",
                "p",
                "--doc-type",
                "tech",
                "--models",
                "mock://agree",
                "--json",
            ],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert json.loads(out)["doc_type"] == "tech"

    def test_missing_profile_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["critique", "--profile", "ghost"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2


class TestDiff:
    def test_diff_action(self, tmp_path, monkeypatch, capsys):
        a = tmp_path / "a.md"
        b = tmp_path / "b.md"
        a.write_text("line one\n")
        b.write_text("line two\n")
        code, out, _ = run_cli(
            ["diff", "--previous", str(a), "--current", str(b)],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "-line one" in out and "+line two" in out

    def test_diff_missing_args_exits_2(self, monkeypatch, capsys):
        code, _, _ = run_cli(
            ["diff"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 2


class TestExportTasks:
    def test_export_tasks_json(self, monkeypatch, capsys):
        # The mock critic doesn't emit [TASK] blocks; patch the engine seam
        # (the reference's pattern: mock transport, run everything above).
        from adversarial_spec_tpu.engine import dispatch
        from adversarial_spec_tpu.engine.types import Completion
        from adversarial_spec_tpu.debate.usage import Usage

        class TaskEngine:
            def validate(self, model):
                return None

            def chat(self, requests, params):
                text = (
                    "[TASK]\ntitle: Build schema\npriority: high\n[/TASK]\n"
                    "[TASK]\ntitle: Write API\ndependencies: Build schema\n[/TASK]"
                )
                return [Completion(text=text, usage=Usage())] * len(requests)

        monkeypatch.setitem(dispatch._ENGINE_CACHE, "mock", TaskEngine())
        code, out, _ = run_cli(
            ["export-tasks", "--models", "mock://critic", "--json"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        tasks = json.loads(out)
        assert [t["title"] for t in tasks] == ["Build schema", "Write API"]
        assert tasks[1]["dependencies"] == ["Build schema"]


class TestRegistry:
    def test_validate_and_parser_stay_jax_free(self):
        """Registry preflight (quant vocabulary included) and parser
        construction must not import jax: mock-only and registry-
        management CLI flows pay no multi-second jax init. Subprocess —
        the suite's own process loaded jax long ago."""
        import subprocess
        import sys as _sys

        proc = subprocess.run(
            [
                _sys.executable,
                "-c",
                "import sys\n"
                "from adversarial_spec_tpu.engine import registry\n"
                "assert registry.validate_tpu_model('tpu://random-tiny') "
                "is None\n"
                "from adversarial_spec_tpu import cli\n"
                "cli.create_parser()\n"
                "assert 'jax' not in sys.modules, 'jax imported'\n",
            ],
            capture_output=True,
            text=True,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]

    def test_add_list_remove(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            [
                "registry",
                "add-model",
                "mymodel",
                "--family",
                "mistral",
                "--size",
                "tiny",
            ],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["registry", "list-models", "--json"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        data = json.loads(out)
        assert "mymodel" in data
        assert data["mymodel"]["family"] == "mistral"
        code, out, _ = run_cli(
            ["registry", "remove-model", "mymodel"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["registry", "list-models", "--json"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert "mymodel" not in json.loads(out)

    def test_alias_subcommand(self, monkeypatch, capsys):
        run_cli(
            ["registry", "add-model", "base", "--family", "gemma2",
             "--size", "9b"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        code, out, _ = run_cli(
            ["registry", "alias", "judge", "base"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["registry", "list-models", "--json"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        data = json.loads(out)
        assert data["judge"]["family"] == "gemma2"
        assert data["judge"]["size"] == "9b"

    def test_alias_of_missing_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["registry", "alias", "x", "ghost"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2

    def test_paged_int8_kv_combo_accepted(self, monkeypatch, capsys):
        """paged + int8 KV is a supported composition (int8 pages +
        scale pages) — registration must succeed."""
        code, _, err = run_cli(
            [
                "registry",
                "add-model",
                "pq8",
                "--kv",
                "paged",
                "--kv-dtype",
                "int8",
            ],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        from adversarial_spec_tpu.engine.registry import load_registry

        spec = load_registry()["pq8"]
        assert spec.kv == "paged" and spec.kv_dtype == "int8"

    def test_remove_missing_exits_2(self, monkeypatch, capsys):
        code, _, _ = run_cli(
            ["registry", "remove-model", "ghost"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2


class TestDefaultModels:
    def test_defaults_to_mock_when_no_real_checkpoints(self):
        assert cli.get_default_models() == ["mock://critic?agree_after=3"]

    def test_prefers_largest_real_checkpoint(self, tmp_path):
        from adversarial_spec_tpu.engine.registry import (
            ModelSpec,
            save_registry_entry,
        )

        ckpt = tmp_path / "ckpt"
        ckpt.mkdir()
        save_registry_entry(
            ModelSpec(alias="small", size="1b", checkpoint=str(ckpt))
        )
        save_registry_entry(
            ModelSpec(alias="big", size="8b", checkpoint=str(ckpt))
        )
        save_registry_entry(
            ModelSpec(alias="broken", size="70b", checkpoint="/nope")
        )
        assert cli.get_default_models() == ["tpu://big"]


class TestParser:
    def test_invalid_action_rejected(self):
        with pytest.raises(SystemExit):
            cli.create_parser().parse_args(["explode"])

    def test_press_flag(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["critique", "--models", "mock://critic", "--press", "--json"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0


class TestResilienceFlags:
    """--chaos / --breaker-* wiring plus the --json resilience report."""

    def test_json_report_carries_resilience_section(
        self, monkeypatch, capsys
    ):
        code, out, _ = run_cli(
            ["critique", "--models", "mock://agree", "--json"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        res = json.loads(out)["perf"]["resilience"]
        assert res["faults"] == {}  # clean round: nothing classified
        # The mock model's success was recorded into its breaker.
        assert res["breakers"]["mock://agree"]["state"] == "closed"

    def test_chaos_flag_arms_the_process_injector(self, monkeypatch):
        from adversarial_spec_tpu.resilience import injector
        from adversarial_spec_tpu.resilience.faults import FaultKind

        args, _ = cli.create_parser().parse_known_args(
            ["critique", "--chaos", "oom@scheduler_chunk:after=1:times=2",
             "--chaos-seed", "7"]
        )
        cli._configure_resilience(args)
        rules = injector.active().rules
        assert len(rules) == 1
        assert rules[0].kind is FaultKind.OOM
        assert (rules[0].seam, rules[0].after, rules[0].times) == (
            "scheduler_chunk", 1, 2,
        )

    def test_breaker_flags_tune_the_default_registry(self, monkeypatch):
        from adversarial_spec_tpu.resilience import breaker

        args, _ = cli.create_parser().parse_known_args(
            ["critique", "--breaker-threshold", "5",
             "--breaker-cooldown", "120"]
        )
        cli._configure_resilience(args)
        reg = breaker.default_registry()
        assert reg.threshold == 5 and reg.cooldown_s == 120.0
        assert reg.enabled

        args, _ = cli.create_parser().parse_known_args(
            ["critique", "--no-breaker"]
        )
        cli._configure_resilience(args)
        assert not breaker.default_registry().enabled

    def test_breaker_state_persists_across_cli_invocations(
        self, monkeypatch, capsys
    ):
        """One CLI invocation is one round: a circuit opened by round N
        must skip the model in round N+1 via the session snapshot."""
        code, out, _ = run_cli(
            ["critique", "--models", "tpu://random-tiny", "--json",
             "--session", "brk", "--greedy", "--max-new-tokens", "4",
             "--chaos", "bug@generate", "--breaker-threshold", "1",
             "--breaker-cooldown", "3600"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        data = json.loads(out)
        assert data["results"][0]["error"]  # injected bug degraded it
        assert (
            data["perf"]["resilience"]["breakers"]["tpu://random-tiny"][
                "state"
            ]
            == "open"
        )
        saved = json.loads(
            (session_mod.SESSIONS_DIR / "brk.json").read_text()
        )
        assert saved["breakers"]["tpu://random-tiny"]["state"] == "open"

        # Next invocation (fresh process state: conftest reset the
        # default registry; chaos no longer armed): still skipped, and
        # crucially WITHOUT touching the engine at all.
        from adversarial_spec_tpu.resilience import breaker, injector

        breaker.reset_default_registry()
        injector.reset()
        code2, out2, _ = run_cli(
            ["critique", "--resume", "brk", "--json"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code2 == 0
        err2 = json.loads(out2)["results"][0]["error"]
        assert "circuit open" in err2

    def test_bad_chaos_spec_is_a_loud_error(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["critique", "--models", "mock://agree",
             "--chaos", "kaboom@generate"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == cli.EXIT_ERROR
        assert "unknown fault kind" in err

    def test_bad_chaos_env_spec_fails_at_startup_too(
        self, monkeypatch, capsys
    ):
        """ADVSPEC_CHAOS typos must fail as loudly as --chaos typos —
        not surface later as swallowed per-model BUG completions."""
        monkeypatch.setenv("ADVSPEC_CHAOS", "kaboom@generate")
        code, _, err = run_cli(
            ["critique", "--models", "mock://agree"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == cli.EXIT_ERROR
        assert "unknown fault kind" in err


class TestHumanReadableOutputs:
    """The non-JSON print branches of the informational actions: display
    code crashes (bad f-string, missing key) must not hide behind the
    --json-only test coverage."""

    def test_providers_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["providers"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "TPU models (local registry):" in out
        assert "Mock models (always available):" in out
        assert "mock://agree" in out

    def test_focus_areas_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["focus-areas"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "security" in out

    def test_personas_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["personas"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "security-engineer" in out

    def test_profiles_plain_empty_and_populated(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["profiles"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        code, _, _ = run_cli(
            ["save-profile", "--name", "hr", "--models", "mock://agree"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["profiles"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "hr:" in out

    def test_sessions_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["sessions"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        run_cli(
            ["critique", "--models", "mock://agree", "--session", "hrsess"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        code, out, _ = run_cli(
            ["sessions"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "hrsess" in out

    def test_export_tasks_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["export-tasks", "--models", "mock://tasks"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "1. [" in out  # numbered, prioritized task lines

    def test_default_models_message(self, monkeypatch, capsys):
        """No --models: the fallback is announced on stderr and the
        round still runs against it."""
        code, out, err = run_cli(
            ["critique"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "no --models given; defaulting to" in err


class TestMutationHardening:
    """Pins that kill the cli.py mutation-sweep survivors
    (tools/mutation_run.py; each block names the mutant class it kills)."""

    def test_exit_codes_and_action_set(self):
        """Exit codes are the documented 0/1/2 contract; the action list
        and default opponent are the CLI's public surface."""
        assert cli.EXIT_OK == 0
        assert cli.EXIT_ERROR == 1
        assert cli.EXIT_VALIDATION == 2
        assert cli.ACTIONS == [
            "critique",
            "providers",
            "send-final",
            "diff",
            "export-tasks",
            "focus-areas",
            "personas",
            "profiles",
            "save-profile",
            "sessions",
            "registry",
            "serve",
        ]
        assert cli.DEFAULT_MODELS == ["mock://critic?agree_after=3"]

    def test_size_rank_table(self):
        """Default-opponent auto-detection ranks by model size."""
        assert cli._SIZE_RANK == {
            "70b": 6, "9b": 5, "8b": 4, "7b": 3, "3b": 2, "1b": 1,
            "tiny": 0,
        }

    def test_parser_accepts_every_flag(self):
        """One full-vector parse: a mutated flag name, choice, or
        default breaks this round-trip."""
        p = cli.create_parser()
        args = p.parse_args([
            "critique",
            "--models", "mock://agree", "--doc-type", "prd",
            "--round", "3", "--focus", "security", "--persona", "qa",
            "--preserve-intent", "--press",
            "--context", "a.md", "--context", "b.md",
            "--session", "s1", "--profile", "pr", "--name", "nm",
            "--json", "--show-cost", "--previous", "p.md",
            "--current", "c.md", "--notify", "--feedback-timeout", "9",
            "--profile-dir", "/tmp/tr",
            "--max-new-tokens", "64", "--temperature", "0.5", "--greedy",
            "--seed", "7", "--timeout", "12.5",
            "--checkpoint", "/ckpt", "--family", "qwen2", "--size", "8b",
            "--tokenizer", "/tok", "--dtype", "bfloat16", "--tp", "2",
            "--quant", "int8", "--kv", "paged", "--kv-dtype", "int8",
        ])
        assert args.models == "mock://agree" and args.doc_type == "prd"
        assert args.round == 3 and args.focus == "security"
        assert args.persona == "qa" and args.preserve_intent and args.press
        assert args.context == ["a.md", "b.md"]
        assert args.session == "s1" and args.profile == "pr"
        assert args.name == "nm" and args.json and args.show_cost
        assert args.previous == "p.md" and args.current == "c.md"
        assert args.notify and args.feedback_timeout == 9
        assert args.profile_dir == "/tmp/tr"
        assert args.max_new_tokens == 64 and args.temperature == 0.5
        assert args.greedy and args.seed == 7 and args.timeout == 12.5
        assert args.checkpoint == "/ckpt" and args.family == "qwen2"
        assert args.size == "8b" and args.tokenizer == "/tok"
        assert args.dtype == "bfloat16" and args.tp == 2
        assert args.quant == "int8" and args.kv == "paged"
        assert args.kv_dtype == "int8"
        # Short aliases and defaults.
        d = p.parse_args(["critique", "-m", "x", "-j"])
        assert d.models == "x" and d.json
        assert d.round == 1 and d.feedback_timeout == 0
        assert d.family == "llama" and d.size == "tiny"
        assert d.kv == "dense" and d.quant == "" and d.kv_dtype == ""

    def test_parse_models_splits_and_strips(self):
        p = cli.create_parser()
        args = p.parse_args(["critique", "--models", " a , b ,,c "])
        assert cli.parse_models(args) == ["a", "b", "c"]

    def test_sampling_defaults_and_explicit_zeros(self):
        """max_new default 1024, temp default 0.7 — but an EXPLICIT
        temperature 0.0 is the user's (is-None check, not truthiness);
        timeout defaults 600 and clamps negatives to 0."""
        p = cli.create_parser()
        s = cli._sampling_from_args(p.parse_args(["critique"]))
        assert s.max_new_tokens == 1024
        assert s.temperature == 0.7
        assert s.timeout_s == 600.0
        s2 = cli._sampling_from_args(
            p.parse_args(["critique", "--temperature", "0.0",
                          "--timeout", "-5"])
        )
        assert s2.temperature == 0.0
        assert s2.timeout_s == 0.0

    def test_validation_error_format(self):
        """Errors carry 'model: reason' and exit code 2."""
        errs = cli.validate_models_before_run(["tpu://no-such-alias"])
        assert len(errs) == 1
        assert errs[0].startswith("tpu://no-such-alias: ")

    def test_json_schema_exact_keys(self, monkeypatch, capsys):
        """The --json contract: EXACT top-level and per-result key sets
        (presence-only checks let renamed keys slip through)."""
        code, out, _ = run_cli(
            ["critique", "--models", "mock://agree", "--json"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        data = json.loads(out)
        assert set(data) == {
            "all_agreed", "round", "doc_type", "trace_id", "models",
            "focus", "persona", "preserve_intent", "session", "results",
            "cost", "perf",
        }
        assert data["all_agreed"] is True
        assert data["round"] == 1
        assert data["doc_type"] == "generic"
        assert data["preserve_intent"] is False
        # Deterministic causal-trace ids (obs/trace.py): round 1's
        # first trace, span per opponent index.
        assert data["trace_id"] == "tr-001-01"
        assert set(data["results"][0]) == {
            "model", "agreed", "response", "spec", "error", "span_id",
            "input_tokens", "output_tokens", "cached_tokens",
            "prefill_time_s", "decode_time_s", "cost",
        }
        assert data["results"][0]["span_id"] == "tr-001-01/s00"

    def test_providers_json_schema(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["providers", "--json"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        data = json.loads(out)
        assert set(data) == {"tpu", "mock", "devices"}
        assert [m["model"] for m in data["mock"]] == [
            "mock://agree",
            "mock://critic",
            "mock://critic?agree_after=N",
        ]
        assert all(m["available"] is True for m in data["mock"])
        assert set(data["devices"]) == {"platform", "device_count"}

    def test_device_info_error_path(self, monkeypatch):
        from adversarial_spec_tpu.utils import jaxenv

        def boom():
            raise RuntimeError("no backend")

        monkeypatch.setattr(jaxenv, "configure_jax", boom)
        info = cli._device_info()
        assert info == {"platform": "unavailable", "error": "no backend"}

    def test_resume_restores_joined_models(self, monkeypatch):
        """Resume rebuilds --models as a comma join of the saved list."""
        SessionState(
            session_id="rj", spec="# S", models=["mock://a", "mock://b"]
        ).save()
        p = cli.create_parser()
        args = p.parse_args(["critique", "--resume", "rj"])
        spec, state = cli.load_or_resume_session(args)
        assert spec == "# S"
        assert args.models == "mock://a,mock://b"
        assert args.session == "rj"

    def test_new_session_doc_type_default(self, monkeypatch):
        p = cli.create_parser()
        args = p.parse_args(["critique", "--session", "nd"])
        monkeypatch.setattr("sys.stdin", io.StringIO("# S"))
        spec, state = cli.load_or_resume_session(args)
        assert state.doc_type == "generic"
        assert state.round == 1

    def test_export_tasks_sampling_defaults(self, monkeypatch, capsys):
        """export-tasks decodes at 2048 tokens / temp 0.3 by default
        (an explicit 0.0 temperature again wins over the default)."""
        captured = {}
        real_get_engine = cli.get_engine

        def spy(model):
            eng = real_get_engine(model)
            real_chat = eng.chat

            def chat(batch, params):
                captured["params"] = params
                return real_chat(batch, params)

            monkeypatch.setattr(eng, "chat", chat)
            return eng

        monkeypatch.setattr(cli, "get_engine", spy)
        code, out, _ = run_cli(
            ["export-tasks", "--models", "mock://tasks", "--json"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        assert captured["params"].max_new_tokens == 2048
        assert captured["params"].temperature == 0.3

    def test_registry_status_line_format(self, monkeypatch, capsys):
        """Text listing pins the alias/family/size/checkpoint line."""
        code, _, _ = run_cli(
            ["registry", "add-model", "pin-me", "--checkpoint", "random",
             "--family", "gemma2", "--size", "9b"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["registry", "status"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        assert (
            f"  {'pin-me':24s} family={'gemma2':8s} size={'9b':5s} "
            f"checkpoint=random"
        ) in out


class TestMutationHardeningRound2:
    """Second-pass cli.py pins: dispatch strings, return-code sites,
    default-resolution operators, and wire schemas the first pass
    missed."""

    def test_parser_prog_groups_and_short_flags(self):
        p = cli.create_parser()
        assert p.prog == "debate"
        help_text = p.format_help()
        for group in ("debate:", "session:", "output:", "decode:",
                      "registry:"):
            assert group in help_text
        opts = {s for a in p._actions for s in a.option_strings}
        assert {"-m", "--models", "-j", "--json"} <= opts

    def test_every_choice_value_parses(self):
        p = cli.create_parser()
        for dt in ("prd", "tech", "generic"):
            assert p.parse_args(["critique", "--doc-type", dt]).doc_type == dt
        for fam in ("llama", "mistral", "gemma2", "qwen2"):
            assert p.parse_args(["registry", "--family", fam]).family == fam
        for kv in ("dense", "paged"):
            assert p.parse_args(["registry", "--kv", kv]).kv == kv
        for q in ("", "int8", "int4"):
            assert p.parse_args(["registry", "--quant", q]).quant == q
        for q in ("", "int8"):  # KV quantization has no int4 format
            assert p.parse_args(["registry", "--kv-dtype", q]).kv_dtype == q

    def test_validate_uses_registry_path_once(self, monkeypatch):
        """tpu:// models go through validate_tpu_model with ONE registry
        load shared across models; the error text is the registry's own
        message verbatim."""
        from adversarial_spec_tpu.engine import registry as reg_mod

        loads = []
        real_load = reg_mod.load_registry

        def counting_load(*a, **k):
            loads.append(1)
            return real_load(*a, **k)

        monkeypatch.setattr(cli.model_registry, "load_registry", counting_load)
        errs = cli.validate_models_before_run(
            ["tpu://no-such-alias", "tpu://also-missing"]
        )
        assert len(loads) == 1
        expected = reg_mod.validate_tpu_model(
            "tpu://no-such-alias", registry=real_load()
        )
        assert errs[0] == f"tpu://no-such-alias: {expected}"

    def test_perf_block_wiring(self, monkeypatch, capsys):
        """Tracer span/counter names feed the perf block: spans must
        carry validate/round/decode and the rate must be a nonzero
        1-decimal number."""
        code, out, _ = run_cli(
            ["critique", "--models", "mock://critic?tps=1000", "--json"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        perf = json.loads(out)["perf"]
        assert {"validate", "round", "decode"} <= set(perf["spans"])
        tps = perf["decode_tokens_per_sec"]
        assert tps > 0
        assert tps == round(tps, 1)

    def test_round_config_defaults_reach_run_round(self, monkeypatch, capsys):
        """doc_type falls back to the string 'generic' and context_files
        to an empty LIST on the cfg handed to run_round."""
        seen = {}
        real = cli.run_round

        def spy(spec, models, round_num=1, cfg=None):
            seen["cfg"] = cfg
            return real(spec, models, round_num=round_num, cfg=cfg)

        monkeypatch.setattr(cli, "run_round", spy)
        run_cli(
            ["critique", "--models", "mock://agree"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert seen["cfg"].doc_type == "generic"
        assert seen["cfg"].context_files == []

    def test_session_history_entry_exact(self, monkeypatch, capsys):
        run_cli(
            ["critique", "--models", "mock://agree", "--session", "hx"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        state = SessionState.load("hx")
        assert state.history == [
            {
                "round": 1,
                "all_agreed": True,
                "models": {"mock://agree": True},
            }
        ]

    def test_notify_unconfigured_warns(self, monkeypatch, capsys):
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        monkeypatch.delenv("TELEGRAM_CHAT_ID", raising=False)
        code, _, err = run_cli(
            ["critique", "--models", "mock://agree", "--notify"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        assert "Telegram not configured" in err

    def test_notify_feedback_lands_in_json(self, monkeypatch, capsys):
        from adversarial_spec_tpu.debate import telegram

        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "t")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "c")
        monkeypatch.setattr(
            telegram, "notify_round", lambda *a, **k: "use more retries"
        )
        code, out, _ = run_cli(
            ["critique", "--models", "mock://agree", "--notify", "--json"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        data = json.loads(out)
        assert data["user_feedback"] == "use more retries"

    def test_text_header_names_doc_type(self, monkeypatch, capsys):
        from adversarial_spec_tpu.debate import prompts

        code, out, _ = run_cli(
            ["critique", "--models", "mock://agree"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        name = prompts.get_doc_type_name("generic")
        assert f"=== Round 1 Results ({name}) ===" in out

    def test_export_tasks_validates_only_first_model(
        self, monkeypatch, capsys
    ):
        code, out, _ = run_cli(
            ["export-tasks", "--models", "mock://tasks,tpu://no-such",
             "--json"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0  # only models[:1] is validated
        code2, _, err2 = run_cli(
            ["export-tasks", "--models", "tpu://no-such,mock://tasks"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code2 == 2

    def test_export_tasks_error_and_empty_paths(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["export-tasks", "--models", "mock://error"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 1
        code2, out2, _ = run_cli(
            ["export-tasks", "--models", "mock://agree"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code2 == 0
        assert "No [TASK] blocks found" in out2

    def test_diff_missing_flags_and_files(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["diff", "--previous", "only.md"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 2
        code2, _, _ = run_cli(
            ["diff", "--previous", "/no/a.md", "--current", "/no/b.md"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code2 == 2

    def test_providers_entry_schema_and_status_text(
        self, monkeypatch, capsys
    ):
        run_cli(
            ["registry", "add-model", "broken", "--checkpoint",
             "/no/such/ckpt"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        code, out, _ = run_cli(
            ["providers", "--json"], monkeypatch=monkeypatch, capsys=capsys
        )
        data = json.loads(out)
        assert all(
            set(e) == {"model", "family", "size", "checkpoint",
                       "available", "error"}
            for e in data["tpu"]
        )
        broken = next(
            e for e in data["tpu"] if e["model"] == "tpu://broken"
        )
        assert broken["available"] is False
        code, out, _ = run_cli(
            ["providers"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert "[ok]" in out
        assert f"[UNAVAILABLE: {broken['error']}]" in out

    def test_device_info_empty_devices(self, monkeypatch):
        import jax

        monkeypatch.setattr(jax, "devices", lambda: [])
        assert cli._device_info() == {
            "platform": "none",
            "device_count": 0,
        }

    def test_registry_bare_action_is_status(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["registry"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "Registry:" in out

    def test_registry_return_codes_and_defaults(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["registry", "add-model"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 2  # missing alias
        code, _, _ = run_cli(
            ["registry", "add-model", "dflt", "--tp", "2"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["registry", "list-models", "--json"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        entry = json.loads(out)["dflt"]
        assert entry["checkpoint"] == "random"
        assert entry["dtype"] == "bfloat16"
        assert entry["mesh"] == {"tp": 2}
        code, _, _ = run_cli(
            ["registry", "remove-model"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 2
        code, _, _ = run_cli(
            ["registry", "remove-model", "ghost-entry"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 2
        code, _, _ = run_cli(
            ["registry", "alias", "only-two"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 2
        code, _, _ = run_cli(
            ["registry", "alias", "cp", "ghost-entry"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 2
        code, _, _ = run_cli(
            ["registry", "bogus-sub"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 2

    def test_send_final_paths(self, monkeypatch, capsys):
        from adversarial_spec_tpu.debate import telegram

        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        monkeypatch.delenv("TELEGRAM_CHAT_ID", raising=False)
        code, _, err = run_cli(
            ["send-final"], stdin="# Done",
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 2
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "t")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "c")
        sent = []
        monkeypatch.setattr(
            telegram,
            "send_long_message",
            lambda cfg, text: sent.append(text) or 1,
        )
        code, _, _ = run_cli(
            ["send-final"], stdin="# Done",
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        assert sent == ["FINAL DOCUMENT\n\n# Done"]

    def test_focus_areas_values_are_first_lines(self, monkeypatch, capsys):
        from adversarial_spec_tpu.debate import prompts

        code, out, _ = run_cli(
            ["focus-areas", "--json"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        data = json.loads(out)
        for k, v in data.items():
            assert v == prompts.FOCUS_AREAS[k].strip().splitlines()[0]

    def test_save_profile_settings_exact(self, monkeypatch, capsys):
        from adversarial_spec_tpu.debate.profiles import load_profile

        code, _, err = run_cli(
            ["save-profile"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 2
        code, _, _ = run_cli(
            ["save-profile", "--name", "full", "--models", "a, b",
             "--doc-type", "prd", "--focus", "security", "--persona", "qa",
             "--preserve-intent", "--max-new-tokens", "64",
             "--temperature", "0.0"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        assert load_profile("full") == {
            "models": ["a", "b"],
            "doc_type": "prd",
            "focus": "security",
            "persona": "qa",
            "preserve_intent": True,
            "max_new_tokens": 64,
            "temperature": 0.0,
        }
        run_cli(
            ["save-profile", "--name", "min"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert load_profile("min") == {}

    def test_profile_applies_to_critique_flags_win(self, monkeypatch, capsys):
        from adversarial_spec_tpu.debate.profiles import save_profile

        save_profile("opp", {"models": ["mock://agree", "mock://critic"]})
        code, out, err = run_cli(
            ["critique", "--profile", "opp", "--json"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        assert json.loads(out)["models"] == [
            "mock://agree", "mock://critic",
        ]
        assert "no --models given" not in err
        code, out, _ = run_cli(
            ["critique", "--profile", "opp", "--models", "mock://agree",
             "--json"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert json.loads(out)["models"] == ["mock://agree"]

    def test_main_exit_code_translation(self, monkeypatch, capsys):
        """A bare SystemExit from a handler maps to 0 (e.code or 0);
        handler crashes map to EXIT_ERROR with the exception named."""

        def bail(args):
            raise SystemExit  # code None -> 0

        monkeypatch.setattr(cli, "run_critique", bail)
        monkeypatch.setattr("sys.stdin", io.StringIO(SPEC))
        assert cli.main(["critique", "--models", "mock://agree"]) == 0
        capsys.readouterr()

        def boom(args):
            raise RuntimeError("kaput")

        monkeypatch.setattr(cli, "run_critique", boom)
        monkeypatch.setattr("sys.stdin", io.StringIO(SPEC))
        assert cli.main(["critique", "--models", "mock://agree"]) == 1
        assert "error: RuntimeError: kaput" in capsys.readouterr().err

    def test_module_entrypoint(self):
        import os
        import subprocess
        import sys as _sys
        from pathlib import Path

        if os.environ.get("ADVSPEC_MUTATION") == "1":
            pytest.skip("interpreter boot per mutant; pinned outside sweeps")
        repo_root = str(Path(__file__).resolve().parent.parent)
        r = subprocess.run(
            [_sys.executable, "-m", "adversarial_spec_tpu.cli"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": repo_root},
        )
        assert r.returncode == 2  # argparse: action is required
        assert "usage:" in r.stderr


class TestMutationHardeningRound3:
    """Final cli.py pins (each names its mutant)."""

    def test_engine_construction_failure_surfaces(self, monkeypatch):
        """A registry-valid model whose engine refuses to build still
        produces a validation error (the err-is-None gate)."""

        def refuse(model):
            raise ValueError("engine boom")

        monkeypatch.setattr(cli, "get_engine", refuse)
        errs = cli.validate_models_before_run(["tpu://random-tiny"])
        assert errs == ["tpu://random-tiny: engine boom"]

    def test_perf_rate_rounds_to_one_decimal(self, monkeypatch, capsys):
        """tps=123.456 -> the reported rate is 123.5, not 123.46."""
        code, out, _ = run_cli(
            ["critique", "--models", "mock://critic?tps=123.456", "--json"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        tps = json.loads(out)["perf"]["decode_tokens_per_sec"]
        assert tps == 123.5

    def test_text_header_respects_explicit_doc_type(
        self, monkeypatch, capsys
    ):
        from adversarial_spec_tpu.debate import prompts

        code, out, _ = run_cli(
            ["critique", "--models", "mock://agree", "--doc-type", "tech"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        name = prompts.get_doc_type_name("tech")
        assert f"=== Round 1 Results ({name}) ===" in out

    def test_diff_missing_flag_message(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["diff", "--previous", "only.md"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 2
        assert "diff requires --previous and --current" in err

    def test_device_info_single_device(self, monkeypatch):
        import jax

        class Dev:
            platform = "tpu"

        monkeypatch.setattr(jax, "devices", lambda: [Dev()])
        assert cli._device_info() == {
            "platform": "tpu",
            "device_count": 1,
        }

    def test_alias_onto_existing_refused(self, monkeypatch, capsys):
        for name in ("src-m", "dst-m"):
            run_cli(
                ["registry", "add-model", name],
                monkeypatch=monkeypatch, capsys=capsys,
            )
        code, _, err = run_cli(
            ["registry", "alias", "dst-m", "src-m"],
            monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 2
        assert "already exists" in err

    def test_profile_applies_to_export_tasks(self, monkeypatch, capsys):
        from adversarial_spec_tpu.debate.profiles import save_profile

        save_profile("tasks-opp", {"models": ["mock://tasks"]})
        code, out, err = run_cli(
            ["export-tasks", "--profile", "tasks-opp", "--json"],
            stdin=SPEC, monkeypatch=monkeypatch, capsys=capsys,
        )
        assert code == 0
        assert "no --models given" not in err
        assert json.loads(out)  # mock://tasks yields at least one task
