"""CLI tests (reference analog: tests/test_cli.py — argv/stdin/stdout
patching around main(), JSON schema assertions, exit codes)."""

import io
import json

import pytest

from adversarial_spec_tpu import cli
from adversarial_spec_tpu.debate.session import SessionState
from adversarial_spec_tpu.debate import session as session_mod

SPEC = "# Cache Service\n\nA read-through cache."


def run_cli(argv, stdin=None, monkeypatch=None, capsys=None):
    assert monkeypatch is not None and capsys is not None
    if stdin is not None:
        monkeypatch.setattr("sys.stdin", io.StringIO(stdin))
    code = cli.main(argv)
    out, err = capsys.readouterr()
    return code, out, err


class TestCritique:
    def test_text_output(self, monkeypatch, capsys):
        code, out, err = run_cli(
            ["critique", "--models", "mock://agree,mock://critic"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "=== Round 1 Results" in out
        assert "mock://agree" in out
        assert "Critiqued: mock://critic" in out
        assert "querying 2 model(s)" in err  # progress goes to stderr

    def test_json_schema(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["critique", "--models", "mock://critic", "--json", "--doc-type", "tech"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        data = json.loads(out)
        # Schema parity with reference debate.py:909-941.
        for key in (
            "all_agreed",
            "round",
            "doc_type",
            "models",
            "focus",
            "persona",
            "preserve_intent",
            "session",
            "results",
            "cost",
        ):
            assert key in data, key
        r = data["results"][0]
        for key in (
            "model",
            "agreed",
            "response",
            "spec",
            "error",
            "input_tokens",
            "output_tokens",
            "cost",
        ):
            assert key in r, key
        assert data["doc_type"] == "tech"
        assert data["all_agreed"] is False

    def test_all_agree_banner(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["critique", "--models", "mock://agree"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert "=== ALL MODELS AGREE ===" in out

    def test_empty_stdin_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["critique"], stdin="", monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 2
        assert "no spec" in err

    def test_unknown_provider_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["critique", "--models", "openai/gpt-4o"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2
        assert "validation error" in err

    def test_unknown_tpu_alias_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["critique", "--models", "tpu://nope"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2
        assert "unknown tpu model alias" in err

    def test_show_cost(self, monkeypatch, capsys):
        _, out, _ = run_cli(
            ["critique", "--models", "mock://critic", "--show-cost"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert "Cost summary:" in out

    def test_failed_model_warns_but_succeeds(self, monkeypatch, capsys):
        code, out, err = run_cli(
            ["critique", "--models", "mock://agree,mock://error"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "warning: mock://error failed" in err
        assert "ERROR:" in out


class TestSessions:
    def test_session_saved_and_resumable(self, monkeypatch, capsys):
        code, _, _ = run_cli(
            [
                "critique",
                "--models",
                "mock://critic",
                "--session",
                "s1",
                "--doc-type",
                "tech",
                "--focus",
                "security",
            ],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        state = SessionState.load("s1")
        assert state.round == 2  # advanced past round 1
        assert state.models == ["mock://critic"]
        assert state.focus == "security"
        assert "Revision note" in state.spec  # revised spec carried forward

        # Resume: no stdin needed, args restored from session.
        code2, out2, _ = run_cli(
            ["critique", "--resume", "s1", "--json"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code2 == 0
        data = json.loads(out2)
        assert data["round"] == 2
        assert data["doc_type"] == "tech"
        assert data["session"] == "s1"

    def test_checkpoint_written(self, monkeypatch, capsys):
        run_cli(
            ["critique", "--models", "mock://critic", "--session", "ck"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        ckpt = session_mod.CHECKPOINTS_DIR / "ck-round-1.md"
        assert ckpt.is_file()
        assert ckpt.read_text() == SPEC

    def test_sessions_listing(self, monkeypatch, capsys):
        SessionState(session_id="listed", spec="s").save()
        code, out, _ = run_cli(
            ["sessions"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "listed" in out


class TestInfoActions:
    def test_focus_areas(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["focus-areas", "--json"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert set(json.loads(out)) == {
            "security",
            "scalability",
            "performance",
            "ux",
            "reliability",
            "cost",
        }

    def test_personas(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["personas", "--json"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert len(json.loads(out)) == 10

    def test_providers_lists_builtin_registry(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["providers", "--json"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        data = json.loads(out)
        models = {e["model"] for e in data["tpu"]}
        assert "tpu://random-tiny" in models
        assert all(e["available"] for e in data["tpu"] if "random" in e["model"])


class TestProfiles:
    def test_save_and_use_profile(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            [
                "save-profile",
                "--name",
                "secfast",
                "--models",
                "mock://agree",
                "--focus",
                "security",
                "--doc-type",
                "prd",
            ],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0

        code2, out2, err2 = run_cli(
            ["critique", "--profile", "secfast", "--json"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code2 == 0
        data = json.loads(out2)
        assert data["models"] == ["mock://agree"]
        assert data["focus"] == "security"
        assert data["doc_type"] == "prd"

    def test_profile_does_not_override_flags(self, monkeypatch, capsys):
        run_cli(
            ["save-profile", "--name", "p", "--doc-type", "prd"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        code, out, _ = run_cli(
            [
                "critique",
                "--profile",
                "p",
                "--doc-type",
                "tech",
                "--models",
                "mock://agree",
                "--json",
            ],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert json.loads(out)["doc_type"] == "tech"

    def test_missing_profile_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["critique", "--profile", "ghost"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2


class TestDiff:
    def test_diff_action(self, tmp_path, monkeypatch, capsys):
        a = tmp_path / "a.md"
        b = tmp_path / "b.md"
        a.write_text("line one\n")
        b.write_text("line two\n")
        code, out, _ = run_cli(
            ["diff", "--previous", str(a), "--current", str(b)],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "-line one" in out and "+line two" in out

    def test_diff_missing_args_exits_2(self, monkeypatch, capsys):
        code, _, _ = run_cli(
            ["diff"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 2


class TestExportTasks:
    def test_export_tasks_json(self, monkeypatch, capsys):
        # The mock critic doesn't emit [TASK] blocks; patch the engine seam
        # (the reference's pattern: mock transport, run everything above).
        from adversarial_spec_tpu.engine import dispatch
        from adversarial_spec_tpu.engine.types import Completion
        from adversarial_spec_tpu.debate.usage import Usage

        class TaskEngine:
            def validate(self, model):
                return None

            def chat(self, requests, params):
                text = (
                    "[TASK]\ntitle: Build schema\npriority: high\n[/TASK]\n"
                    "[TASK]\ntitle: Write API\ndependencies: Build schema\n[/TASK]"
                )
                return [Completion(text=text, usage=Usage())] * len(requests)

        monkeypatch.setitem(dispatch._ENGINE_CACHE, "mock", TaskEngine())
        code, out, _ = run_cli(
            ["export-tasks", "--models", "mock://critic", "--json"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        tasks = json.loads(out)
        assert [t["title"] for t in tasks] == ["Build schema", "Write API"]
        assert tasks[1]["dependencies"] == ["Build schema"]


class TestRegistry:
    def test_add_list_remove(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            [
                "registry",
                "add-model",
                "mymodel",
                "--family",
                "mistral",
                "--size",
                "tiny",
            ],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["registry", "list-models", "--json"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        data = json.loads(out)
        assert "mymodel" in data
        assert data["mymodel"]["family"] == "mistral"
        code, out, _ = run_cli(
            ["registry", "remove-model", "mymodel"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["registry", "list-models", "--json"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert "mymodel" not in json.loads(out)

    def test_alias_subcommand(self, monkeypatch, capsys):
        run_cli(
            ["registry", "add-model", "base", "--family", "gemma2",
             "--size", "9b"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        code, out, _ = run_cli(
            ["registry", "alias", "judge", "base"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["registry", "list-models", "--json"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        data = json.loads(out)
        assert data["judge"]["family"] == "gemma2"
        assert data["judge"]["size"] == "9b"

    def test_alias_of_missing_exits_2(self, monkeypatch, capsys):
        code, _, err = run_cli(
            ["registry", "alias", "x", "ghost"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2

    def test_paged_int8_kv_combo_accepted(self, monkeypatch, capsys):
        """paged + int8 KV is a supported composition (int8 pages +
        scale pages) — registration must succeed."""
        code, _, err = run_cli(
            [
                "registry",
                "add-model",
                "pq8",
                "--kv",
                "paged",
                "--kv-dtype",
                "int8",
            ],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        from adversarial_spec_tpu.engine.registry import load_registry

        spec = load_registry()["pq8"]
        assert spec.kv == "paged" and spec.kv_dtype == "int8"

    def test_remove_missing_exits_2(self, monkeypatch, capsys):
        code, _, _ = run_cli(
            ["registry", "remove-model", "ghost"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 2


class TestDefaultModels:
    def test_defaults_to_mock_when_no_real_checkpoints(self):
        assert cli.get_default_models() == ["mock://critic?agree_after=3"]

    def test_prefers_largest_real_checkpoint(self, tmp_path):
        from adversarial_spec_tpu.engine.registry import (
            ModelSpec,
            save_registry_entry,
        )

        ckpt = tmp_path / "ckpt"
        ckpt.mkdir()
        save_registry_entry(
            ModelSpec(alias="small", size="1b", checkpoint=str(ckpt))
        )
        save_registry_entry(
            ModelSpec(alias="big", size="8b", checkpoint=str(ckpt))
        )
        save_registry_entry(
            ModelSpec(alias="broken", size="70b", checkpoint="/nope")
        )
        assert cli.get_default_models() == ["tpu://big"]


class TestParser:
    def test_invalid_action_rejected(self):
        with pytest.raises(SystemExit):
            cli.create_parser().parse_args(["explode"])

    def test_press_flag(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["critique", "--models", "mock://critic", "--press", "--json"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0


class TestHumanReadableOutputs:
    """The non-JSON print branches of the informational actions: display
    code crashes (bad f-string, missing key) must not hide behind the
    --json-only test coverage."""

    def test_providers_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["providers"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "TPU models (local registry):" in out
        assert "Mock models (always available):" in out
        assert "mock://agree" in out

    def test_focus_areas_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["focus-areas"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "security" in out

    def test_personas_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["personas"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "security-engineer" in out

    def test_profiles_plain_empty_and_populated(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["profiles"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        code, _, _ = run_cli(
            ["save-profile", "--name", "hr", "--models", "mock://agree"],
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        code, out, _ = run_cli(
            ["profiles"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "hr:" in out

    def test_sessions_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["sessions"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        run_cli(
            ["critique", "--models", "mock://agree", "--session", "hrsess"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        code, out, _ = run_cli(
            ["sessions"], monkeypatch=monkeypatch, capsys=capsys
        )
        assert code == 0
        assert "hrsess" in out

    def test_export_tasks_plain(self, monkeypatch, capsys):
        code, out, _ = run_cli(
            ["export-tasks", "--models", "mock://tasks"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "1. [" in out  # numbered, prioritized task lines

    def test_default_models_message(self, monkeypatch, capsys):
        """No --models: the fallback is announced on stderr and the
        round still runs against it."""
        code, out, err = run_cli(
            ["critique"],
            stdin=SPEC,
            monkeypatch=monkeypatch,
            capsys=capsys,
        )
        assert code == 0
        assert "no --models given; defaulting to" in err
