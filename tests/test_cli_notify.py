"""CLI ↔ Telegram integration paths (notify, send-final, discovery) with
the telegram transport faked at the urlopen/module seam."""

import io
import json

from adversarial_spec_tpu import cli
from adversarial_spec_tpu.debate import telegram

SPEC = "# Spec\nBody."


class TestNotifyFlow:
    def test_notify_unconfigured_warns_and_continues(
        self, monkeypatch, capsys
    ):
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        monkeypatch.delenv("TELEGRAM_CHAT_ID", raising=False)
        monkeypatch.setattr("sys.stdin", io.StringIO(SPEC))
        code = cli.main(
            ["critique", "--models", "mock://agree", "--notify", "--json"]
        )
        out, err = capsys.readouterr()
        assert code == 0
        assert "Telegram not configured" in err
        assert json.loads(out)["all_agreed"] is True

    def test_notify_feedback_lands_in_output(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        sent = []
        monkeypatch.setattr(
            telegram, "send_long_message", lambda cfg, text: sent.append(text)
        )
        monkeypatch.setattr(telegram, "send_message", lambda cfg, text: None)
        monkeypatch.setattr(telegram, "get_last_update_id", lambda cfg: 0)
        monkeypatch.setattr(
            telegram,
            "poll_for_reply",
            lambda cfg, after, timeout: "tighten the SLO section",
        )
        monkeypatch.setattr("sys.stdin", io.StringIO(SPEC))
        code = cli.main(
            [
                "critique",
                "--models",
                "mock://critic",
                "--notify",
                "--feedback-timeout",
                "30",
                "--json",
            ]
        )
        out, _ = capsys.readouterr()
        assert code == 0
        data = json.loads(out)
        assert data["user_feedback"] == "tighten the SLO section"
        assert any("Debate round 1" in s for s in sent)

    def test_notify_failure_never_kills_round(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")

        def boom(*a, **k):
            raise RuntimeError("network down")

        monkeypatch.setattr(telegram, "notify_round", boom)
        monkeypatch.setattr("sys.stdin", io.StringIO(SPEC))
        code = cli.main(
            ["critique", "--models", "mock://agree", "--notify", "--json"]
        )
        out, err = capsys.readouterr()
        assert code == 0
        assert "Telegram notify failed" in err


class TestSendFinal:
    def test_send_final_chunks_document(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        sent = []
        monkeypatch.setattr(
            telegram,
            "send_long_message",
            lambda cfg, text, **k: sent.append(text) or 1,
        )
        monkeypatch.setattr("sys.stdin", io.StringIO("# Final doc"))
        code = cli.main(["send-final"])
        out, _ = capsys.readouterr()
        assert code == 0
        assert "Final document sent." in out
        assert sent and "FINAL DOCUMENT" in sent[0]


class TestDiscovery:
    def test_discover_chat_id_most_recent(self, monkeypatch):
        monkeypatch.setattr(
            telegram,
            "api_call",
            lambda token, method, params=None: [
                {"update_id": 1, "message": {"chat": {"id": 11}}},
                {"update_id": 2, "message": {"chat": {"id": 22}}},
            ],
        )
        assert telegram.discover_chat_id("tok") == "22"

    def test_discover_none_when_no_messages(self, monkeypatch):
        monkeypatch.setattr(
            telegram, "api_call", lambda token, method, params=None: []
        )
        assert telegram.discover_chat_id("tok") is None

    def test_setup_subcommand(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setattr(telegram, "discover_chat_id", lambda tok: "777")
        assert telegram._cli(["setup"]) == 0
        assert "TELEGRAM_CHAT_ID=777" in capsys.readouterr().out

    def test_setup_without_token_exit_2(self, monkeypatch, capsys):
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        assert telegram._cli(["setup"]) == 2
