"""Durability tests — crash-safe round journal, mid-round resume,
per-request watchdog deadlines, hedged re-admission, and the
kill-chaos recovery contract (docs/resilience.md "Durability and
recovery").

The headline coverage: a real subprocess round SIGKILLed the moment
its 2nd opponent's journal record becomes durable, resumed in-process
— only unfinished opponents re-issue, journal-served transcripts are
byte-identical to an uninterrupted run, and the mock engine's
allocator invariants are clean post-recovery.
"""

import io
import json
import os
import signal
import subprocess
import sys
from pathlib import Path

import pytest

from adversarial_spec_tpu.debate import core
from adversarial_spec_tpu.debate import journal as journal_mod
from adversarial_spec_tpu.debate import session as session_mod
from adversarial_spec_tpu.debate.core import RoundConfig, run_round
from adversarial_spec_tpu.debate.journal import (
    JOURNAL_VERSION,
    RoundJournal,
    completion_from_record,
    spec_sha,
    validate_record,
)
from adversarial_spec_tpu.debate.session import (
    CorruptSessionState,
    SessionState,
    save_checkpoint,
)
from adversarial_spec_tpu.debate.usage import Usage
from adversarial_spec_tpu.engine.types import Completion, SamplingParams
from adversarial_spec_tpu.resilience import breaker as breaker_mod
from adversarial_spec_tpu.resilience import faults as faults_mod
from adversarial_spec_tpu.resilience import injector as injector_mod
from adversarial_spec_tpu.resilience.faults import FaultKind
from adversarial_spec_tpu.resilience.injector import FaultInjector, FaultRule

REPO = Path(__file__).resolve().parent.parent

SPEC = "# Cache Service\n\nA read-through cache with bounded staleness."


@pytest.fixture(autouse=True)
def _spec_off(monkeypatch):
    """This module pins journal/watchdog/recovery semantics; speculation
    is default-on and would only multiply the jit programs the watchdog
    batchers compile (the PR 6 suite-budget precedent). The one
    spec-on watchdog case opts back in explicitly."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)


def _completion(text="1. Critique.\n", out_tokens=12) -> Completion:
    return Completion(text=text, usage=Usage(output_tokens=out_tokens))


class TestJournalUnit:
    def test_append_replay_roundtrip(self):
        j = RoundJournal("t1")
        assert j.ensure_round_start(1, SPEC, ["m1", "m2"], {"doc_type": "t"})
        j.log_completion(1, 0, "m1", _completion("alpha"), 0.25)
        j.log_completion(1, 1, "m2", _completion("beta", 7), 0.5)
        served = j.replay(1, SPEC, ["m1", "m2"])
        assert sorted(served) == [0, 1]
        comp, latency = completion_from_record(served[1])
        assert comp.text == "beta"
        assert comp.usage.output_tokens == 7
        assert latency == 0.5

    def test_replay_guards_spec_hash(self):
        j = RoundJournal("t2")
        j.ensure_round_start(1, SPEC, ["m1"], {})
        j.log_completion(1, 0, "m1", _completion(), 0.1)
        assert j.replay(1, SPEC + " REVISED", ["m1"]) == {}
        assert j.replay(2, SPEC, ["m1"]) == {}

    def test_replay_guards_model_identity(self):
        j = RoundJournal("t3")
        j.ensure_round_start(1, SPEC, ["m1", "m2"], {})
        j.log_completion(1, 0, "m1", _completion(), 0.1)
        served = j.replay(1, SPEC, ["OTHER", "m2"])
        assert served == {}  # the model SET changed: clean full refusal

    def test_permuted_pool_serves_each_completion_to_its_model(self):
        """A resume whose opponent-pool ORDER changed (same models,
        permuted) still serves every completion — re-homed to its
        model's new index, decided by the per-index model match."""
        j = RoundJournal("t3p")
        models = ["m1", "m2", "m3"]
        j.ensure_round_start(1, SPEC, models, {})
        for i, m in enumerate(models):
            j.log_completion(1, i, m, _completion(f"text-{m}"), 0.1)
        permuted = ["m3", "m1", "m2"]
        served = j.replay(1, SPEC, permuted)
        assert sorted(served) == [0, 1, 2]
        for new_idx, model in enumerate(permuted):
            comp, _ = completion_from_record(served[new_idx])
            assert comp.text == f"text-{model}"  # the RIGHT model's text

    def test_permuted_pool_partial_records_rehome_too(self):
        """Only some opponents completed before the crash: the ones
        that did re-home; the rest re-issue at their new indices."""
        j = RoundJournal("t3q")
        j.ensure_round_start(1, SPEC, ["m1", "m2", "m3"], {})
        j.log_completion(1, 0, "m1", _completion("text-m1"), 0.1)
        j.log_completion(1, 2, "m3", _completion("text-m3"), 0.1)
        served = j.replay(1, SPEC, ["m2", "m3", "m1"])
        assert sorted(served) == [1, 2]  # m3 at 1, m1 at 2; m2 re-issues
        assert completion_from_record(served[1])[0].text == "text-m3"
        assert completion_from_record(served[2])[0].text == "text-m1"

    def test_duplicate_model_ids_keep_the_strict_index_match(self):
        """Duplicated ids make re-homing ambiguous: only records whose
        recorded index still names their model replay."""
        j = RoundJournal("t3r")
        j.ensure_round_start(1, SPEC, ["dup", "dup", "m3"], {})
        j.log_completion(1, 0, "dup", _completion("a"), 0.1)
        j.log_completion(1, 1, "dup", _completion("b"), 0.1)
        j.log_completion(1, 2, "m3", _completion("c"), 0.1)
        served = j.replay(1, SPEC, ["dup", "m3", "dup"])
        # dup@0 matches in place; m3 re-homes to 1; the second dup is
        # ambiguous (count != 1) and re-issues.
        assert sorted(served) == [0, 1]
        assert completion_from_record(served[0])[0].text == "a"
        assert completion_from_record(served[1])[0].text == "c"

    def test_changed_model_set_refuses_replay_cleanly(self):
        """A grown/shrunk/substituted pool invalidates the ROUND's
        records wholesale — no crash, no half-replay."""
        j = RoundJournal("t3s")
        j.ensure_round_start(1, SPEC, ["m1", "m2"], {})
        j.log_completion(1, 0, "m1", _completion(), 0.1)
        j.log_completion(1, 1, "m2", _completion(), 0.1)
        assert j.replay(1, SPEC, ["m1", "m2", "m3"]) == {}  # grown
        assert j.replay(1, SPEC, ["m1"]) == {}  # shrunk
        assert j.replay(1, SPEC, ["m1", "mX"]) == {}  # substituted
        # The unchanged pool (any order) still replays everything.
        assert sorted(j.replay(1, SPEC, ["m2", "m1"])) == [0, 1]

    def test_torn_tail_tolerated(self):
        j = RoundJournal("t4")
        j.ensure_round_start(1, SPEC, ["m1"], {})
        j.log_completion(1, 0, "m1", _completion(), 0.1)
        # A crash mid-append leaves a half-written final line.
        with open(j.path, "a") as f:
            f.write('{"v": 1, "type": "completio')
        records, skipped = j.read()
        assert [r["type"] for r in records] == ["round_start", "completion"]
        assert skipped == 1
        assert sorted(j.replay(1, SPEC, ["m1"])) == [0]

    def test_records_after_torn_tail_stay_replayable(self):
        """A realistic tear (half-written line, NO trailing newline)
        must not cost the records appended after it: the next append
        heals the tear with a leading newline, the reader skips the
        confined garbage alone, and a SECOND crash in the same round
        still replays every post-tear completion — durability does not
        silently stop at the first crash."""
        j = RoundJournal("t-torn-multi")
        j.ensure_round_start(1, SPEC, ["m1", "m2"], {})
        j.log_completion(1, 0, "m1", _completion("alpha"), 0.1)
        with open(j.path, "a") as f:
            f.write('{"v": 1, "type": "completio')  # crash: no newline
        # The resumed process re-issues the missing opponent and its
        # completion must become durable DESPITE the tear before it.
        j2 = RoundJournal("t-torn-multi")
        j2.log_completion(1, 1, "m2", _completion("beta"), 0.1)
        j2.log_round_commit(1, all_agreed=False)
        records, skipped = j.read()
        assert [r["type"] for r in records] == [
            "round_start",
            "completion",
            "completion",
            "round_commit",
        ]
        assert skipped == 1  # exactly the confined torn line
        served = j.replay(1, SPEC, ["m1", "m2"])
        assert sorted(served) == [0, 1]
        assert served[1]["text"] == "beta"

    def test_foreign_versions_interleaved_mid_stream(self):
        """Foreign-version records INTERLEAVED between valid ones are
        each skipped alone — unlike a tear, a complete append from a
        future writer does not invalidate what follows it."""
        j = RoundJournal("t-foreign-mid")
        j.ensure_round_start(1, SPEC, ["m1", "m2", "m3"], {})
        foreign = (
            json.dumps(
                {"v": JOURNAL_VERSION + 1, "type": "future", "x": 1}
            )
            + "\n"
        )
        j.log_completion(1, 0, "m1", _completion("a"), 0.1)
        with open(j.path, "a") as f:
            f.write(foreign)
        j.log_completion(1, 1, "m2", _completion("b"), 0.1)
        with open(j.path, "a") as f:
            f.write(foreign)
        j.log_completion(1, 2, "m3", _completion("c"), 0.1)
        records, skipped = j.read()
        assert skipped == 2
        assert [r["type"] for r in records] == [
            "round_start",
            "completion",
            "completion",
            "completion",
        ]
        served = j.replay(1, SPEC, ["m1", "m2", "m3"])
        assert sorted(served) == [0, 1, 2]

    def test_round_commit_torn_at_fsync_boundary(self):
        """A round_commit torn exactly at the fsync boundary (the line
        half-written, no newline durable) never became a commit: the
        reader discards it, the round's completions stay replayable,
        and a resume of the SAME round appends no new marker — it
        re-synthesizes from the journal and re-commits."""
        j = RoundJournal("t-commit-torn")
        j.ensure_round_start(1, SPEC, ["m1"], {})
        j.log_completion(1, 0, "m1", _completion("alpha"), 0.1)
        full = json.dumps(
            {"v": JOURNAL_VERSION, "type": "round_commit", "round": 1,
             "all_agreed": True}
        )
        with open(j.path, "a") as f:
            f.write(full[: len(full) // 2])  # crash mid-write, no \n
        records, skipped = j.read()
        assert [r["type"] for r in records] == [
            "round_start",
            "completion",
        ]
        assert skipped == 1
        # The resume path: same round, same spec — marker already
        # durable (no fresh truncation), completion served from the
        # journal with zero engine work, and the re-commit LANDS: the
        # append heals the newline-less tear first, so the new commit
        # sits on its own line instead of fusing into the garbage.
        j2 = RoundJournal("t-commit-torn")
        assert not j2.ensure_round_start(1, SPEC, ["m1"], {})
        served = j2.replay(1, SPEC, ["m1"])
        assert sorted(served) == [0]
        j2.log_round_commit(1, all_agreed=True)
        records, skipped = j2.read()
        assert [r["type"] for r in records] == [
            "round_start",
            "completion",
            "round_commit",
        ]
        assert records[-1]["all_agreed"] is True
        assert skipped == 1  # the confined torn half-commit

    def test_foreign_version_skipped_not_fatal(self):
        j = RoundJournal("t5")
        j.ensure_round_start(1, SPEC, ["m1"], {})
        with open(j.path, "a") as f:
            f.write(
                json.dumps(
                    {"v": JOURNAL_VERSION + 1, "type": "future", "x": 1}
                )
                + "\n"
            )
        j.log_completion(1, 0, "m1", _completion(), 0.1)
        records, skipped = j.read()
        assert skipped == 1
        assert [r["type"] for r in records] == ["round_start", "completion"]

    def test_partial_records_never_served(self):
        j = RoundJournal("t6")
        j.ensure_round_start(1, SPEC, ["m1"], {})
        j.log_partial(
            1, 0, "m1", Completion(text="parti", error="DEADLINE_EXCEEDED")
        )
        assert j.replay(1, SPEC, ["m1"]) == {}
        records, _ = j.read()
        assert records[-1]["type"] == "partial"
        assert records[-1]["error"] == "DEADLINE_EXCEEDED"

    def test_round_start_idempotent_then_truncates_next_round(self):
        j = RoundJournal("t7")
        assert j.ensure_round_start(1, SPEC, ["m1"], {})
        j.log_completion(1, 0, "m1", _completion(), 0.1)
        # Resume of the SAME round: no new marker, completions survive.
        assert not j.ensure_round_start(1, SPEC, ["m1"], {})
        assert sorted(j.replay(1, SPEC, ["m1"])) == [0]
        j.log_round_commit(1, all_agreed=False)
        # A NEW round truncates: the committed round's records are dead
        # weight (history lives on SessionState).
        assert j.ensure_round_start(2, "spec v2", ["m1"], {})
        records, _ = j.read()
        assert [r["type"] for r in records] == ["round_start"]
        assert records[0]["round"] == 2

    def test_multi_crash_accumulates_completions(self):
        j = RoundJournal("t8")
        j.ensure_round_start(1, SPEC, ["m1", "m2", "m3"], {})
        j.log_completion(1, 0, "m1", _completion("a"), 0.1)
        # Second process, same round: marker skipped, records append.
        j2 = RoundJournal("t8")
        j2.ensure_round_start(1, SPEC, ["m1", "m2", "m3"], {})
        j2.log_completion(1, 1, "m2", _completion("b"), 0.1)
        assert sorted(j2.replay(1, SPEC, ["m1", "m2", "m3"])) == [0, 1]

    def test_self_check_clean_and_validator_fires(self):
        assert journal_mod.self_check() == []
        good = {
            "v": JOURNAL_VERSION,
            "type": "round_commit",
            "round": 1,
            "all_agreed": True,
        }
        assert validate_record(good) == []
        assert validate_record({**good, "round": "one"})
        assert validate_record({**good, "v": 99})
        assert validate_record({**good, "mystery": 1})

    def test_fsync_events_and_metrics_emitted(self):
        from adversarial_spec_tpu import obs

        j = RoundJournal("t9")
        j.ensure_round_start(1, SPEC, ["m1"], {})
        j.log_completion(1, 0, "m1", _completion(), 0.1)
        kinds = [
            (e["op"], e["rtype"])
            for e in obs.recorder.events()
            if e["type"] == "journal"
        ]
        assert ("append", "round_start") in kinds
        assert ("append", "completion") in kinds
        snap = obs.metrics.snapshot()
        assert (
            snap.get('advspec_journal_records_total{type="completion"}', 0)
            == 1
        )
        assert snap["advspec_journal_fsync_seconds"]["count"] >= 2

    def test_journal_event_schema_validates(self):
        from adversarial_spec_tpu.obs import (
            JournalEvent,
            RecoveryEvent,
            validate_event,
        )
        from adversarial_spec_tpu.obs.events import event_to_dict

        for ev in (
            JournalEvent(op="append", rtype="completion", round_num=1),
            RecoveryEvent(round_num=1, served=2, reissued=2),
        ):
            obj = json.loads(json.dumps(event_to_dict(1, ev)))
            assert validate_event(obj) == []


class TestSessionDurability:
    def test_save_crash_window_old_file_intact_no_orphan(self, monkeypatch):
        st = SessionState(session_id="cw", spec="v1")
        path = st.save()
        before = path.read_text()
        monkeypatch.setattr(
            "os.replace",
            lambda *a: (_ for _ in ()).throw(
                OSError("crash inside the rename window")
            ),
        )
        st.spec = "v2"
        with pytest.raises(OSError):
            st.save()
        monkeypatch.undo()
        assert path.read_text() == before  # --resume still has a round
        assert not list(path.parent.glob("*.tmp"))  # no orphan tmp

    def test_checkpoint_crash_window(self, monkeypatch, tmp_path):
        path = save_checkpoint("v1", 1, "ck", checkpoints_dir=tmp_path)
        monkeypatch.setattr(
            "os.replace",
            lambda *a: (_ for _ in ()).throw(OSError("crash")),
        )
        with pytest.raises(OSError):
            save_checkpoint("v2", 1, "ck", checkpoints_dir=tmp_path)
        monkeypatch.undo()
        assert path.read_text() == "v1"
        assert not list(tmp_path.glob("*.tmp"))

    def test_load_corrupt_quarantines_with_clear_error(self):
        st = SessionState(session_id="corr", spec="v1")
        path = st.save()
        path.write_text('{"session_id": "corr", "spec": "v1", "rou')
        with pytest.raises(CorruptSessionState) as ei:
            SessionState.load("corr")
        msg = str(ei.value)
        assert str(path) in msg
        assert "quarantined" in msg
        assert "--session corr" in msg  # names the recovery option
        assert not path.exists()
        quarantine = path.with_name(path.name + ".corrupt")
        assert quarantine.exists()
        # The quarantined file does not shadow future sessions.
        assert SessionState.list_sessions() == []

    @pytest.mark.parametrize(
        "payload",
        [b'["valid", "json", "wrong", "shape"]', b"\xff\xfe garbage \x80"],
        ids=["non-object-json", "non-utf8-bytes"],
    )
    def test_load_quarantines_every_corruption_shape(self, payload):
        # Corruption is not always a JSONDecodeError: bad storage can
        # leave non-UTF-8 bytes, and a rewritten file can be valid JSON
        # of the wrong shape — all must quarantine, none may escape as
        # a raw stack trace.
        st = SessionState(session_id="corr2", spec="v1")
        path = st.save()
        path.write_bytes(payload)
        with pytest.raises(CorruptSessionState) as ei:
            SessionState.load("corr2")
        assert "quarantined" in str(ei.value)
        assert not path.exists()
        assert path.with_name(path.name + ".corrupt").exists()

    def test_cli_corrupt_resume_is_validation_error(
        self, monkeypatch, capsys
    ):
        from adversarial_spec_tpu import cli

        st = SessionState(session_id="cx", spec="v1")
        path = st.save()
        path.write_text("{torn")
        code = cli.main(["critique", "--resume", "cx"])
        _, err = capsys.readouterr()
        assert code == cli.EXIT_VALIDATION
        assert "quarantined" in err


class TestRunRoundJournal:
    def test_round_journals_start_completions(self):
        j = RoundJournal("rr1")
        cfg = RoundConfig(journal=j)
        result = run_round(SPEC, ["mock://critic?j=1", "mock://agree"], cfg=cfg)
        assert all(r.ok for r in result.responses)
        records, skipped = j.read()
        assert skipped == 0
        assert [r["type"] for r in records] == [
            "round_start",
            "completion",
            "completion",
        ]
        assert records[0]["spec_sha"] == spec_sha(SPEC)
        assert records[1]["text"] == result.responses[0].critique

    def test_resume_serves_from_journal_with_zero_engine_calls(self):
        from adversarial_spec_tpu.engine.dispatch import get_engine

        models = ["mock://critic?j=2", "mock://critic?j=3"]
        r1 = run_round(SPEC, models, cfg=RoundConfig(journal=RoundJournal("rr2")))
        engine = get_engine(models[0])
        calls_before = dict(engine._calls)
        r2 = run_round(SPEC, models, cfg=RoundConfig(journal=RoundJournal("rr2")))
        # Byte-identical service with ZERO engine work re-paid.
        assert [r.critique for r in r2.responses] == [
            r.critique for r in r1.responses
        ]
        assert engine._calls == calls_before
        assert r2.tracer.counters.get("journal.served") == 2
        assert r2.tracer.counters.get("attempts." + models[0]) is None

    def test_partial_resume_reissues_only_missing(self):
        models = ["mock://critic?j=4", "mock://critic?j=5"]
        # Simulate the crashed process: only opponent 0's record durable.
        j = RoundJournal("rr3")
        j.ensure_round_start(1, SPEC, models, {})
        j.log_completion(1, 0, models[0], _completion("from-journal"), 0.1)
        result = run_round(SPEC, models, cfg=RoundConfig(journal=RoundJournal("rr3")))
        assert result.responses[0].critique == "from-journal"
        assert result.responses[1].ok
        assert result.tracer.counters.get("journal.served") == 1
        assert result.tracer.counters.get(f"attempts.{models[1]}") == 1
        # The re-issued opponent's completion is journaled too: a second
        # crash-resume now serves BOTH.
        served = RoundJournal("rr3").replay(1, SPEC, models)
        assert sorted(served) == [0, 1]

    def test_recovery_event_reports_read_stats(self):
        from adversarial_spec_tpu import obs

        models = ["mock://critic?j=9", "mock://critic?j=10"]
        j = RoundJournal("rrev")
        j.ensure_round_start(1, SPEC, models, {})
        j.log_completion(1, 0, models[0], _completion(), 0.1)
        with open(j.path, "a") as f:
            f.write('{"v": 1, "type": "completio')  # torn tail
        run_round(SPEC, models, cfg=RoundConfig(journal=RoundJournal("rrev")))
        ev = [e for e in obs.recorder.events() if e["type"] == "recovery"]
        assert ev and ev[-1]["served"] == 1 and ev[-1]["reissued"] == 1
        # records = every readable journal record, skipped = the torn
        # line — the two fields exist to show data was discarded.
        assert ev[-1]["records"] == 2
        assert ev[-1]["skipped"] == 1

    def test_breaker_open_still_skips_on_journal_resume(self):
        """Satellite: an open circuit persisted on SessionState.breakers
        must keep skipping the failing model when the round is resumed
        from the journal — recovery must not grant a broken model a
        fresh retry ladder."""
        good, bad = "mock://critic?j=6", "mock://error"
        j = RoundJournal("rr4")
        j.ensure_round_start(1, SPEC, [good, bad], {})
        j.log_completion(1, 0, good, _completion("durable"), 0.1)
        reg = breaker_mod.BreakerRegistry(threshold=1, cooldown_s=300.0)
        reg.restore(
            {
                bad: {
                    "state": "open",
                    "failures": 3,
                    "cooldown_remaining": 300.0,
                    "last_fault": "bug",
                }
            }
        )
        result = run_round(
            SPEC,
            [good, bad],
            cfg=RoundConfig(journal=RoundJournal("rr4"), breakers=reg),
        )
        assert result.responses[0].critique == "durable"
        assert "circuit open" in result.responses[1].error
        # ZERO engine attempts anywhere: one served, one breaker-skipped.
        assert not [
            k for k in result.tracer.counters if k.startswith("attempts.")
        ]

    def test_journal_failure_contained_round_survives(self):
        # Every append faults at the crash seam: the round must resolve
        # every opponent cleanly anyway (durability lost, service kept).
        injector_mod.install(
            FaultInjector([FaultRule(kind=FaultKind.BUG, seam="crash")])
        )
        try:
            result = run_round(
                SPEC,
                ["mock://critic?j=7"],
                cfg=RoundConfig(journal=RoundJournal("rr5")),
            )
        finally:
            injector_mod.install(None)
        assert result.responses[0].ok
        assert faults_mod.snapshot().get("crash.bug", 0) >= 1
        assert RoundJournal("rr5").replay(1, SPEC, ["mock://critic?j=7"]) == {}

    @pytest.mark.chaos
    def test_crash_seam_fuzz_no_response_lost(self):
        """Random faults at the journal-append seam mid-round: every
        opponent still resolves (no response lost), and whatever subset
        of records became durable is readable and replayable."""
        import random

        models = ["mock://critic?f=1", "mock://critic?f=2", "mock://agree"]
        for seed in (0, 1, 2):
            rng = random.Random(seed)
            rules = [
                FaultRule(
                    kind=rng.choice(list(FaultKind)), seam="crash", p=0.5
                )
            ]
            injector_mod.install(FaultInjector(rules, seed=seed))
            try:
                result = run_round(
                    SPEC,
                    models,
                    cfg=RoundConfig(journal=RoundJournal(f"fz{seed}")),
                )
            finally:
                injector_mod.install(None)
            assert len(result.responses) == len(models), f"seed {seed}"
            assert all(r.ok for r in result.responses), f"seed {seed}"
            served = RoundJournal(f"fz{seed}").replay(1, SPEC, models)
            for i, rec in served.items():
                comp, _ = completion_from_record(rec)
                assert comp.text == result.responses[i].critique


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    return cfg, params


class TestWatchdogDeadline:
    """Per-request watchdog (SchedRequest.deadline_s): one hung/slow
    request evicts as TIMEOUT through the shared _release_slot surgery
    while co-residents keep decoding."""

    def _batcher(self, tiny_model, **kw):
        from adversarial_spec_tpu.engine.scheduler import ContinuousBatcher

        cfg, params = tiny_model
        kw.setdefault("max_batch", 2)
        kw.setdefault("max_new_cap", 64)
        kw.setdefault("chunk", 4)
        return ContinuousBatcher(params, cfg, **kw)

    @pytest.mark.parametrize("interleave", [True, False])
    def test_deadline_evicts_only_the_expired_slot(
        self, tiny_model, interleave
    ):
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        b = self._batcher(tiny_model, interleave=interleave)
        total_pages = b.allocator.free_pages
        deliveries = []
        b.submit(
            SchedRequest(
                req_id=0,
                prompt_ids=[1, 2, 3, 4] * 8,
                max_new_tokens=64,
                deadline_s=0.05,
                on_tokens=lambda t: deliveries.append(len(t)) or True,
            )
        )
        b.submit(
            SchedRequest(
                req_id=1, prompt_ids=[5, 6, 7] * 8, max_new_tokens=8
            )
        )
        res = {r.req_id: r for r in b.run_all()}
        # The expired slot: TIMEOUT fault, partial tokens, no requeue.
        assert res[0].fault_kind == "timeout"
        assert "watchdog deadline" in res[0].error
        assert res[0].n_generated < 64
        # The co-resident is untouched and the pool is whole again.
        assert res[1].error is None and res[1].n_generated == 8
        b.allocator.check_invariants()
        assert b.allocator.free_pages == total_pages
        # Partial text reached the stream consumer before the evict.
        if res[0].n_generated:
            assert deliveries[-1] == res[0].n_generated
        else:
            assert not deliveries

    def test_queued_request_past_deadline_resolves(self, tiny_model):
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        b = self._batcher(tiny_model, max_batch=1, max_new_cap=32)
        b.submit(
            SchedRequest(req_id=0, prompt_ids=[1, 2, 3, 4], max_new_tokens=32)
        )
        b.submit(
            SchedRequest(
                req_id=1,
                prompt_ids=[5, 6, 7, 8],
                max_new_tokens=32,
                deadline_s=1e-6,
            )
        )
        res = {r.req_id: r for r in b.run_all()}
        assert res[0].error is None and res[0].n_generated == 32
        assert res[1].fault_kind == "timeout" and res[1].n_generated == 0
        b.allocator.check_invariants()

    def test_watchdog_fault_event_no_requeue(self, tiny_model):
        from adversarial_spec_tpu import obs
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        b = self._batcher(tiny_model)
        b.submit(
            SchedRequest(
                req_id=0,
                prompt_ids=[1, 2, 3, 4] * 4,
                max_new_tokens=64,
                deadline_s=1e-4,
            )
        )
        b.run_all()
        faults = [
            e for e in obs.recorder.events() if e["type"] == "fault"
        ]
        mine = [e for e in faults if e["seam"] == "watchdog"]
        assert mine and mine[-1]["kind"] == "timeout"
        # The budget is spent: no batcher-level requeue — the hedge is
        # the debate layer's decision.
        assert mine[-1]["requeued"] is False
        assert faults_mod.snapshot().get("watchdog.timeout", 0) >= 1

    def test_deadline_under_speculation(self, tiny_model, monkeypatch):
        from adversarial_spec_tpu.engine import spec as spec_mod
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        monkeypatch.setenv("ADVSPEC_SPECULATIVE", "1")
        spec_mod.configure(enabled=True, gamma=4)
        b = self._batcher(tiny_model, speculative=True, gamma=4)
        b.submit(
            SchedRequest(
                req_id=0,
                prompt_ids=[1, 2, 3, 4] * 8,
                max_new_tokens=64,
                deadline_s=0.05,
            )
        )
        res = b.run_all()
        assert res[0].fault_kind == "timeout"
        b.allocator.check_invariants()


class _HedgeEngine:
    """Engine fake: every request times out `fail_n` times at the
    watchdog, then succeeds. Records each call's request deadline."""

    def __init__(self, fail_n=1):
        self.fail_n = fail_n
        self.calls = []

    def chat(self, batch, params):
        self.calls.append((len(batch), params.request_deadline_s))
        if len(self.calls) <= self.fail_n:
            return [
                Completion(
                    text="1. partial cri",
                    error=(
                        "DEADLINE_EXCEEDED: per-request watchdog deadline "
                        "0.4s expired (mid-decode, req 0)"
                    ),
                    transient=True,
                )
                for _ in batch
            ]
        return [Completion(text="1. full critique") for _ in batch]

    def validate(self, model):
        return None


class TestHedgedReadmission:
    def _cfg(self, **kw):
        cfg = RoundConfig(
            sampling=SamplingParams(request_deadline_s=0.4),
            breakers=breaker_mod.BreakerRegistry(
                threshold=kw.pop("threshold", 3), cooldown_s=300.0
            ),
            **kw,
        )
        cfg.sleep = lambda s: None
        return cfg

    def test_single_hedge_with_tightened_budget(self, monkeypatch):
        eng = _HedgeEngine(fail_n=1)
        monkeypatch.setattr(core, "get_engine", lambda m: eng)
        result = run_round(SPEC, ["fake://m"], cfg=self._cfg())
        assert result.responses[0].ok
        assert result.responses[0].critique == "1. full critique"
        # Exactly one hedge, on HEDGE_BUDGET_FACTOR of the deadline.
        assert eng.calls == [(1, 0.4), (1, 0.4 * core.HEDGE_BUDGET_FACTOR)]
        assert result.tracer.counters.get("hedge.fake://m") == 1
        assert result.tracer.counters.get("attempts.fake://m") == 2

    def test_hedge_loses_keeps_original_partial_no_third_attempt(
        self, monkeypatch
    ):
        eng = _HedgeEngine(fail_n=99)
        monkeypatch.setattr(core, "get_engine", lambda m: eng)
        result = run_round(SPEC, ["fake://m"], cfg=self._cfg())
        assert len(eng.calls) == 2  # never a third
        assert "watchdog deadline" in result.responses[0].error

    def test_breaker_open_vetoes_the_hedge(self, monkeypatch):
        eng = _HedgeEngine(fail_n=99)
        monkeypatch.setattr(core, "get_engine", lambda m: eng)
        # threshold=1: the first watchdog timeout opens the circuit, so
        # the hedge must not fire at all.
        result = run_round(SPEC, ["fake://m"], cfg=self._cfg(threshold=1))
        assert len(eng.calls) == 1
        assert "watchdog deadline" in result.responses[0].error

    def test_timeout_without_deadline_takes_normal_retries(
        self, monkeypatch
    ):
        eng = _HedgeEngine(fail_n=99)
        monkeypatch.setattr(core, "get_engine", lambda m: eng)
        cfg = self._cfg()
        cfg.sampling = SamplingParams()  # request_deadline_s = 0
        result = run_round(SPEC, ["fake://m"], cfg=cfg)
        # Transient timeout without a watchdog armed: the classic
        # 3-attempt ladder, full budget each time, and the LAST
        # attempt's error is the surfaced one.
        assert [c[1] for c in eng.calls] == [0.0, 0.0, 0.0]
        assert "DEADLINE_EXCEEDED" in result.responses[0].error

    def test_deadline_evicted_partial_is_journaled(self, monkeypatch):
        eng = _HedgeEngine(fail_n=99)
        monkeypatch.setattr(core, "get_engine", lambda m: eng)
        cfg = self._cfg(journal=RoundJournal("hj"))
        run_round(SPEC, ["fake://m"], cfg=cfg)
        records, _ = RoundJournal("hj").read()
        partials = [r for r in records if r["type"] == "partial"]
        assert partials and partials[-1]["text"] == "1. partial cri"
        assert "DEADLINE_EXCEEDED" in partials[-1]["error"]


class TestKillRecoverySmoke:
    """The tier-1 kill-chaos smoke: a REAL subprocess round SIGKILLed
    the moment the 2nd opponent's record becomes durable, then resumed
    in-process (so the mock engine's allocator is reachable for the
    post-recovery invariants check)."""

    MODELS = [f"mock://critic?k={n}" for n in range(1, 5)]

    def test_sigkill_mid_round_then_resume(
        self, monkeypatch, capsys, tmp_path
    ):
        from adversarial_spec_tpu import cli
        from adversarial_spec_tpu.engine.dispatch import get_engine

        sessions = tmp_path / "sessions"
        env = {
            **os.environ,
            "PYTHONPATH": str(REPO),
            "JAX_PLATFORMS": "cpu",
            "ADVSPEC_SESSIONS_DIR": str(sessions),
            "ADVSPEC_JOURNAL_KILL_AFTER": "2",
        }
        victim = subprocess.run(
            [
                sys.executable,
                "-m",
                "adversarial_spec_tpu.cli",
                "critique",
                "--session",
                "ks",
                "--models",
                ",".join(self.MODELS),
                "--json",
            ],
            input=SPEC,
            text=True,
            capture_output=True,
            # tmp cwd: the CLI writes cwd-relative spec checkpoints,
            # which must not litter the repo (PYTHONPATH in env makes
            # the package importable from anywhere).
            cwd=tmp_path,
            env=env,
        )
        assert victim.returncode == -signal.SIGKILL, victim.stderr[-300:]
        journal = RoundJournal("ks", journal_dir=sessions)
        records, skipped = journal.read()
        assert skipped == 0
        assert [r["type"] for r in records] == [
            "round_start",
            "completion",
            "completion",
        ]

        # Resume in-process.
        monkeypatch.setattr(session_mod, "SESSIONS_DIR", sessions)
        code = cli.main(["critique", "--resume", "ks", "--json"])
        out, err = capsys.readouterr()
        assert code == 0
        assert "2 opponent(s) served from the round journal" in err
        data = json.loads(out)
        counters = data["perf"]["counters"]
        # Only unfinished opponents re-issue — no duplicated work.
        assert counters.get("debate/journal.served") == 2
        for i, model in enumerate(self.MODELS):
            want = 0 if i < 2 else 1
            assert counters.get(f"debate/attempts.{model}", 0) == want, model
        # Byte-identical to an uninterrupted run of the same round.
        reference = run_round(SPEC, list(self.MODELS), round_num=1)
        for i in range(len(self.MODELS)):
            assert (
                data["results"][i]["response"]
                == reference.responses[i].critique
            ), f"opponent {i}"
        # check_invariants clean post-recovery, and the round committed.
        engine = get_engine(self.MODELS[0])
        if engine._allocator is not None:
            engine._allocator.check_invariants()
        records, _ = journal.read()
        assert records[-1]["type"] == "round_commit"
        # No faults surfaced anywhere in the recovery round.
        assert data["perf"]["resilience"]["faults"] == {}


class TestCliJournalFlags:
    def _run(self, argv, monkeypatch, capsys, stdin=SPEC):
        from adversarial_spec_tpu import cli

        if stdin is not None:
            monkeypatch.setattr("sys.stdin", io.StringIO(stdin))
        code = cli.main(argv)
        out, err = capsys.readouterr()
        return code, out, err

    def test_journal_default_on_with_session(self, monkeypatch, capsys):
        code, _, _ = self._run(
            ["critique", "--models", "mock://critic", "--session", "cj"],
            monkeypatch,
            capsys,
        )
        assert code == 0
        assert RoundJournal("cj").path.is_file()
        records, _ = RoundJournal("cj").read()
        assert records[-1]["type"] == "round_commit"

    def test_no_journal_flag(self, monkeypatch, capsys):
        code, _, _ = self._run(
            [
                "critique",
                "--models",
                "mock://critic",
                "--session",
                "cj2",
                "--no-journal",
            ],
            monkeypatch,
            capsys,
        )
        assert code == 0
        assert not RoundJournal("cj2").path.exists()

    def test_env_default_off(self, monkeypatch, capsys):
        monkeypatch.setenv("ADVSPEC_JOURNAL", "0")
        code, _, _ = self._run(
            ["critique", "--models", "mock://critic", "--session", "cj3"],
            monkeypatch,
            capsys,
        )
        assert code == 0
        assert not RoundJournal("cj3").path.exists()

    def test_no_journal_without_session(self, monkeypatch, capsys):
        code, _, _ = self._run(
            ["critique", "--models", "mock://critic"], monkeypatch, capsys
        )
        assert code == 0
        # No session id = nothing to key the journal on.
        assert not list(Path(session_mod.SESSIONS_DIR).glob("*.journal.jsonl"))

    def test_request_deadline_flag_and_env(self, monkeypatch):
        from adversarial_spec_tpu import cli

        parser = cli.create_parser()
        args = parser.parse_args(
            ["critique", "--request-deadline-s", "2.5"]
        )
        assert cli._sampling_from_args(args).request_deadline_s == 2.5
        args = parser.parse_args(["critique"])
        assert cli._sampling_from_args(args).request_deadline_s == 0.0
        monkeypatch.setenv("ADVSPEC_REQUEST_DEADLINE_S", "7.5")
        assert cli._sampling_from_args(args).request_deadline_s == 7.5
        # Flag beats env.
        args = parser.parse_args(["critique", "--request-deadline-s", "1"])
        assert cli._sampling_from_args(args).request_deadline_s == 1.0


class TestBenchRecoverSchema:
    def test_bench_recover_json_schema_and_budget(self):
        from tools.bench_trend import collect

        rows, problems = collect(REPO)
        assert not [p for p in problems if "recover" in p], problems
        assert any(r["file"] == "BENCH_recover.json" for r in rows)
        payload = json.loads((REPO / "BENCH_recover.json").read_text())
        assert payload["metric"] == "recover_tokens_salvaged_fraction"
        assert payload["value"] >= 0.5
        assert payload["within_budget"] is True
        assert payload["victim_sigkilled"] is True
        assert payload["transcripts_byte_identical"] is True
