"""End-to-end debate-loop integration tests, driven the way the L5 agent
drives it: repeated `critique` CLI invocations with sessions, feeding each
round's revised spec forward until all models agree (BASELINE configs 1
and 4's loop shape, on the mock engine)."""

import io
import json

from adversarial_spec_tpu import cli
from adversarial_spec_tpu.debate.session import SessionState
from adversarial_spec_tpu.debate import session as session_mod

SPEC = """# Notification Service

Sends notifications to users over email and push.

## Scope
Initial version targets transactional messages only.
"""


def _round(monkeypatch, capsys, argv, stdin=None):
    if stdin is not None:
        monkeypatch.setattr("sys.stdin", io.StringIO(stdin))
    code = cli.main(argv)
    out, err = capsys.readouterr()
    assert code == 0, err
    return json.loads(out)


class TestFullDebateLoop:
    def test_converges_with_sessions_and_resume(self, monkeypatch, capsys):
        """Multi-round loop: 4 opponents with different agreement
        thresholds converge by round 3; every round resumes the session
        and carries the revised spec forward; checkpoints accumulate."""
        models = (
            "mock://agree,"
            "mock://critic?agree_after=2,"
            "mock://critic?agree_after=3,"
            # Transient failure on its first call, then a critic that
            # agrees from round 2 on.
            "mock://flaky?fail=1&agree_after=2"
        )
        data = _round(
            monkeypatch,
            capsys,
            [
                "critique",
                "--models",
                models,
                "--doc-type",
                "tech",
                "--session",
                "e2e",
                "--json",
            ],
            stdin=SPEC,
        )
        assert data["round"] == 1
        assert data["all_agreed"] is False

        rounds = [data]
        for _ in range(6):
            data = _round(
                monkeypatch, capsys, ["critique", "--resume", "e2e", "--json"]
            )
            rounds.append(data)
            if data["all_agreed"]:
                break
        assert data["all_agreed"] is True
        assert data["round"] == 3  # agree_after=3 is the last holdout

        # Spec evolved across rounds (revision notes accumulated).
        final_state = SessionState.load("e2e")
        assert "Revision note" in final_state.spec
        assert final_state.round == 4
        assert len(final_state.history) == 3

        # Per-round checkpoints exist for rollback.
        ckpts = sorted(
            p.name for p in session_mod.CHECKPOINTS_DIR.glob("e2e-round-*.md")
        )
        assert ckpts == ["e2e-round-1.md", "e2e-round-2.md", "e2e-round-3.md"]

    def test_press_round_after_quick_consensus(self, monkeypatch, capsys):
        """The L5 protocol's press rule: round-1 unanimous agreement is
        re-challenged with --press; the mock pool agrees again and the
        press prompt reached the models."""
        data = _round(
            monkeypatch,
            capsys,
            ["critique", "--models", "mock://agree,mock://agree", "--json"],
            stdin=SPEC,
        )
        assert data["all_agreed"] is True and data["round"] == 1

        pressed = _round(
            monkeypatch,
            capsys,
            [
                "critique",
                "--models",
                "mock://agree,mock://agree",
                "--press",
                "--round",
                "1",
                "--json",
            ],
            stdin=SPEC,
        )
        assert pressed["all_agreed"] is True

    def test_cost_accumulates_across_rounds(self, monkeypatch, capsys):
        total = 0.0
        for r in (1, 2):
            data = _round(
                monkeypatch,
                capsys,
                [
                    "critique",
                    "--models",
                    "mock://critic",
                    "--round",
                    str(r),
                    "--json",
                ],
                stdin=SPEC,
            )
            assert data["cost"]["total_cost_usd"] > 0
            total += data["cost"]["total_cost_usd"]
        assert total > 0

    def test_final_flow_export_tasks(self, monkeypatch, capsys):
        """Post-convergence: the final spec exports to structured tasks."""
        data = _round(
            monkeypatch,
            capsys,
            ["export-tasks", "--models", "mock://tasks", "--json"],
            stdin=SPEC,
        )
        assert len(data) == 3
        titles = [t["title"] for t in data]
        assert "Define data model" in titles
