"""Mock engine + round orchestration tests (reference analog:
tests/test_model_calls.py — mixed agree/critique/error rounds, retry
backoff sequencing)."""

from adversarial_spec_tpu.debate.core import (
    RoundConfig,
    build_request,
    load_context_files,
    run_round,
)
from adversarial_spec_tpu.debate.prompts import PRESS_PROMPT_TEMPLATE
from adversarial_spec_tpu.engine.mock import MockEngine
from adversarial_spec_tpu.engine.types import SamplingParams

import pytest

SPEC = "# Widget Service\n\nStores widgets."
PARAMS = SamplingParams(max_new_tokens=512)


def _req(model, round_num=1, spec=SPEC):
    return build_request(model, spec, round_num, RoundConfig(doc_type="tech"))


class TestMockEngine:
    def test_agree_model(self):
        comp = MockEngine().chat([_req("mock://agree")], PARAMS)[0]
        assert comp.ok
        assert "[AGREE]" in comp.text

    def test_critic_produces_spec_revision(self):
        comp = MockEngine().chat([_req("mock://critic")], PARAMS)[0]
        assert "[SPEC]" in comp.text and "[/SPEC]" in comp.text
        assert "[AGREE]" not in comp.text
        assert comp.usage.input_tokens > 0
        assert comp.usage.output_tokens > 0

    def test_agree_after_round_threshold(self):
        eng = MockEngine()
        model = "mock://critic?agree_after=3"
        assert "[AGREE]" not in eng.chat([_req(model, 1)], PARAMS)[0].text
        assert "[AGREE]" not in eng.chat([_req(model, 2)], PARAMS)[0].text
        assert "[AGREE]" in eng.chat([_req(model, 3)], PARAMS)[0].text

    def test_error_model_permanent(self):
        comp = MockEngine().chat([_req("mock://error")], PARAMS)[0]
        assert not comp.ok
        assert not comp.transient

    def test_flaky_recovers(self):
        eng = MockEngine()
        model = "mock://flaky?fail=2"
        first = eng.chat([_req(model)], PARAMS)[0]
        assert not first.ok and first.transient
        second = eng.chat([_req(model)], PARAMS)[0]
        assert not second.ok and second.transient
        third = eng.chat([_req(model)], PARAMS)[0]
        assert third.ok

    def test_simulated_tps_in_usage(self):
        comp = MockEngine().chat([_req("mock://critic?tps=100")], PARAMS)[0]
        assert comp.usage.decode_time_s > 0
        assert (
            abs(
                comp.usage.decode_tokens / comp.usage.decode_time_s - 100.0
            )
            < 1e-6
        )

    def test_batch_returns_one_completion_per_request(self):
        reqs = [_req("mock://agree"), _req("mock://critic")]
        comps = MockEngine().chat(reqs, PARAMS)
        assert len(comps) == 2

    def test_validate(self):
        assert MockEngine().validate("mock://agree") is None
        assert MockEngine().validate("tpu://x") is not None


class TestBuildRequest:
    def test_press_uses_press_template(self):
        cfg = RoundConfig(press=True)
        req = build_request("m", SPEC, 2, cfg)
        assert "PRESS ROUND" in req.user
        assert PRESS_PROMPT_TEMPLATE.splitlines()[0].startswith(
            "Debate round"
        )

    def test_round_number_embedded(self):
        req = _req("m", round_num=7)
        assert "Debate round 7" in req.user

    def test_context_files_injected(self, tmp_path):
        f = tmp_path / "notes.md"
        f.write_text("remember the API limits")
        cfg = RoundConfig(context_files=[str(f)])
        req = build_request("m", SPEC, 1, cfg)
        assert "CONTEXT FILE: notes.md" in req.user
        assert "remember the API limits" in req.user

    def test_missing_context_file_raises(self):
        with pytest.raises(FileNotFoundError):
            load_context_files(["/definitely/not/here.md"])


class TestRunRound:
    def test_mixed_agree_and_critique(self):
        result = run_round(
            SPEC, ["mock://agree", "mock://critic"], round_num=1
        )
        assert len(result.responses) == 2
        by_model = {r.model: r for r in result.responses}
        assert by_model["mock://agree"].agreed
        assert not by_model["mock://critic"].agreed
        assert by_model["mock://critic"].revised_spec is not None
        assert not result.all_agreed

    def test_all_agreed(self):
        result = run_round(SPEC, ["mock://agree", "mock://agree"], 1)
        assert result.all_agreed

    def test_failed_model_excluded_from_agreement(self):
        result = run_round(SPEC, ["mock://agree", "mock://error"], 1)
        assert len(result.failed) == 1
        assert result.all_agreed  # only successful responses count

    def test_all_failed_means_not_agreed(self):
        result = run_round(SPEC, ["mock://error"], 1)
        assert not result.all_agreed

    def test_transient_failure_retried_with_backoff(self, monkeypatch):
        delays = []
        cfg = RoundConfig()
        monkeypatch.setattr(RoundConfig, "sleep", staticmethod(delays.append))
        result = run_round(SPEC, ["mock://flaky?fail=2"], 1, cfg)
        assert result.responses[0].ok
        # Reference backoff policy: 1s then 2s (models.py:46-47).
        assert delays == [1.0, 2.0]

    def test_permanent_failure_not_retried(self, monkeypatch):
        delays = []
        monkeypatch.setattr(RoundConfig, "sleep", staticmethod(delays.append))
        result = run_round(SPEC, ["mock://error"], 1)
        assert delays == []
        assert not result.responses[0].ok

    def test_retries_exhausted(self, monkeypatch):
        monkeypatch.setattr(RoundConfig, "sleep", staticmethod(lambda _: None))
        result = run_round(SPEC, ["mock://flaky?fail=99"], 1)
        assert not result.responses[0].ok

    def test_usage_populated(self):
        result = run_round(SPEC, ["mock://critic"], 1)
        assert result.total_usage.total_tokens > 0
