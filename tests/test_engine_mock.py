"""Mock engine + round orchestration tests (reference analog:
tests/test_model_calls.py — mixed agree/critique/error rounds, retry
backoff sequencing)."""

from adversarial_spec_tpu.debate.core import (
    RoundConfig,
    build_request,
    load_context_files,
    run_round,
)
from adversarial_spec_tpu.debate.prompts import PRESS_PROMPT_TEMPLATE
from adversarial_spec_tpu.engine.mock import MockEngine
from adversarial_spec_tpu.engine.types import SamplingParams

import pytest

SPEC = "# Widget Service\n\nStores widgets."
PARAMS = SamplingParams(max_new_tokens=512)


def _req(model, round_num=1, spec=SPEC):
    return build_request(model, spec, round_num, RoundConfig(doc_type="tech"))


class TestMockEngine:
    def test_agree_model(self):
        comp = MockEngine().chat([_req("mock://agree")], PARAMS)[0]
        assert comp.ok
        assert "[AGREE]" in comp.text

    def test_critic_produces_spec_revision(self):
        comp = MockEngine().chat([_req("mock://critic")], PARAMS)[0]
        assert "[SPEC]" in comp.text and "[/SPEC]" in comp.text
        assert "[AGREE]" not in comp.text
        assert comp.usage.input_tokens > 0
        assert comp.usage.output_tokens > 0

    def test_agree_after_round_threshold(self):
        eng = MockEngine()
        model = "mock://critic?agree_after=3"
        assert "[AGREE]" not in eng.chat([_req(model, 1)], PARAMS)[0].text
        assert "[AGREE]" not in eng.chat([_req(model, 2)], PARAMS)[0].text
        assert "[AGREE]" in eng.chat([_req(model, 3)], PARAMS)[0].text

    def test_error_model_permanent(self):
        comp = MockEngine().chat([_req("mock://error")], PARAMS)[0]
        assert not comp.ok
        assert not comp.transient

    def test_flaky_recovers(self):
        eng = MockEngine()
        model = "mock://flaky?fail=2"
        first = eng.chat([_req(model)], PARAMS)[0]
        assert not first.ok and first.transient
        second = eng.chat([_req(model)], PARAMS)[0]
        assert not second.ok and second.transient
        third = eng.chat([_req(model)], PARAMS)[0]
        assert third.ok

    def test_simulated_tps_in_usage(self):
        comp = MockEngine().chat([_req("mock://critic?tps=100")], PARAMS)[0]
        assert comp.usage.decode_time_s > 0
        assert (
            abs(
                comp.usage.decode_tokens / comp.usage.decode_time_s - 100.0
            )
            < 1e-6
        )

    def test_batch_returns_one_completion_per_request(self):
        reqs = [_req("mock://agree"), _req("mock://critic")]
        comps = MockEngine().chat(reqs, PARAMS)
        assert len(comps) == 2

    def test_validate(self):
        assert MockEngine().validate("mock://agree") is None
        assert MockEngine().validate("tpu://x") is not None


class TestBuildRequest:
    def test_press_uses_press_template(self):
        cfg = RoundConfig(press=True)
        req = build_request("m", SPEC, 2, cfg)
        assert "PRESS ROUND" in req.user
        # Prefix-stable layout: the round-varying header trails the
        # document so cross-round prefix caching can hit.
        assert PRESS_PROMPT_TEMPLATE.index(
            "--- END DOCUMENT ---"
        ) < PRESS_PROMPT_TEMPLATE.index("Debate round")

    def test_round_number_embedded(self):
        req = _req("m", round_num=7)
        assert "Debate round 7" in req.user

    def test_context_files_injected(self, tmp_path):
        f = tmp_path / "notes.md"
        f.write_text("remember the API limits")
        cfg = RoundConfig(context_files=[str(f)])
        req = build_request("m", SPEC, 1, cfg)
        assert "CONTEXT FILE: notes.md" in req.user
        assert "remember the API limits" in req.user

    def test_missing_context_file_raises(self):
        with pytest.raises(FileNotFoundError):
            load_context_files(["/definitely/not/here.md"])


class TestRunRound:
    def test_mixed_agree_and_critique(self):
        result = run_round(
            SPEC, ["mock://agree", "mock://critic"], round_num=1
        )
        assert len(result.responses) == 2
        by_model = {r.model: r for r in result.responses}
        assert by_model["mock://agree"].agreed
        assert not by_model["mock://critic"].agreed
        assert by_model["mock://critic"].revised_spec is not None
        assert not result.all_agreed

    def test_all_agreed(self):
        result = run_round(SPEC, ["mock://agree", "mock://agree"], 1)
        assert result.all_agreed

    def test_failed_model_excluded_from_agreement(self):
        result = run_round(SPEC, ["mock://agree", "mock://error"], 1)
        assert len(result.failed) == 1
        assert result.all_agreed  # only successful responses count

    def test_all_failed_means_not_agreed(self):
        result = run_round(SPEC, ["mock://error"], 1)
        assert not result.all_agreed

    def test_transient_failure_retried_with_backoff(self, monkeypatch):
        delays = []
        cfg = RoundConfig()
        monkeypatch.setattr(RoundConfig, "sleep", staticmethod(delays.append))
        result = run_round(SPEC, ["mock://flaky?fail=2"], 1, cfg)
        assert result.responses[0].ok
        # Reference backoff policy: 1s then 2s (models.py:46-47).
        assert delays == [1.0, 2.0]

    def test_permanent_failure_not_retried(self, monkeypatch):
        delays = []
        monkeypatch.setattr(RoundConfig, "sleep", staticmethod(delays.append))
        result = run_round(SPEC, ["mock://error"], 1)
        assert delays == []
        assert not result.responses[0].ok

    def test_retries_exhausted(self, monkeypatch):
        monkeypatch.setattr(RoundConfig, "sleep", staticmethod(lambda _: None))
        result = run_round(SPEC, ["mock://flaky?fail=99"], 1)
        assert not result.responses[0].ok

    def test_usage_populated(self):
        result = run_round(SPEC, ["mock://critic"], 1)
        assert result.total_usage.total_tokens > 0


class TestMutationHardening:
    """Pins that kill the round-5 mutation-sweep survivors in core.py
    (tools/mutation_run.py; each assertion names the mutant it kills)."""

    def test_round_config_defaults(self):
        """Kills the RoundConfig default mutants (doc_type XX, press /
        preserve_intent flips)."""
        cfg = RoundConfig()
        assert cfg.doc_type == "generic"
        assert cfg.press is False
        assert cfg.preserve_intent is False

    def test_context_files_exact_format(self, tmp_path):
        """Kills the context-block string mutants: the labeled-block
        format is part of the prompt contract (reference
        models.py:130-146)."""
        from adversarial_spec_tpu.debate.core import load_context_files

        (tmp_path / "a.txt").write_text("AAA")
        (tmp_path / "b.txt").write_text("BBB")
        out = load_context_files(
            [str(tmp_path / "a.txt"), str(tmp_path / "b.txt")]
        )
        assert out == (
            "--- CONTEXT FILE: a.txt ---\nAAA\n\n"
            "--- CONTEXT FILE: b.txt ---\nBBB\n\n"
        )
        with pytest.raises(
            FileNotFoundError, match="context file not found: "
        ):
            load_context_files([str(tmp_path / "ghost.txt")])

    def test_malformed_spec_warning_text(self):
        """Kills the warning-string mutant (the CLI surfaces this text)."""
        from adversarial_spec_tpu.debate.core import _to_response
        from adversarial_spec_tpu.engine.types import Completion

        comp = Completion(text="critique [SPEC] never closed")
        resp = _to_response("m", comp, 0.1)
        assert resp.critique.endswith(
            "\n\n[warning: unterminated [SPEC] tag in response]"
        )

    def test_exactly_three_attempts_and_last_error_kept(self, monkeypatch):
        """Kills MAX_RETRIES 3->4, the deadline Add->Sub (a generous
        budget must not cut retries), and the last-attempt filter
        mutants (< -> <=, -1 -> +1): the final transient error text is
        kept, not replaced by 'retries exhausted'."""
        from adversarial_spec_tpu.engine.dispatch import get_engine
        from adversarial_spec_tpu.engine.types import SamplingParams

        model = "mock://flaky?fail=96"
        eng = get_engine(model)
        calls = []
        orig = eng.chat

        def counting_chat(batch, sampling):
            calls.append(len(batch))
            return orig(batch, sampling)

        monkeypatch.setattr(eng, "chat", counting_chat)
        monkeypatch.setattr(RoundConfig, "sleep", staticmethod(lambda _: None))
        cfg = RoundConfig(sampling=SamplingParams(timeout_s=3600.0))
        result = run_round(SPEC, [model], 1, cfg)
        assert calls == [1, 1, 1]  # exactly MAX_RETRIES batched attempts
        assert result.responses[0].error == "mock transient failure 3/96"

    def test_expired_budget_stops_retries(self, monkeypatch):
        """Kills the timeout_s guard mutant (> 0 -> > 1) and the
        'retries exhausted' string mutant: a sub-second budget arms the
        deadline, so only one attempt runs."""
        from adversarial_spec_tpu.engine.dispatch import get_engine
        from adversarial_spec_tpu.engine.types import SamplingParams

        model = "mock://flaky?fail=95"
        eng = get_engine(model)
        calls = []
        orig = eng.chat

        def counting_chat(batch, sampling):
            calls.append(len(batch))
            return orig(batch, sampling)

        monkeypatch.setattr(eng, "chat", counting_chat)
        monkeypatch.setattr(RoundConfig, "sleep", staticmethod(lambda _: None))
        cfg = RoundConfig(sampling=SamplingParams(timeout_s=1e-6))
        result = run_round(SPEC, [model], 1, cfg)
        assert calls == [1]
        assert result.responses[0].error == "retries exhausted"

    def test_latency_is_a_duration(self):
        """Kills the latency Sub->Add mutant (t1 + t0 is ~2x the
        monotonic clock, far above any sane round duration)."""
        result = run_round(SPEC, ["mock://agree"], 1)
        assert 0.0 <= result.responses[0].latency_s < 3600.0

    def test_run_round_default_round_num(self):
        """Kills the round_num default mutant (1 -> 2)."""
        result = run_round(SPEC, ["mock://agree"])
        assert result.round_num == 1


class TestTypesMutationHardening:
    """Pins for types.py survivors."""

    def test_model_response_defaults(self):
        from adversarial_spec_tpu.debate.types import ModelResponse

        r = ModelResponse(model="m")
        assert r.agreed is False
        assert r.ok is True
        assert r.critique == "" and r.revised_spec is None

    def test_to_dict_schema_and_rounding(self):
        """to_dict is the per-model block of the CLI --json output:
        exact keys, exact latency rounding (3 digits)."""
        from adversarial_spec_tpu.debate.types import ModelResponse
        from adversarial_spec_tpu.debate.usage import Usage

        r = ModelResponse(
            model="m",
            critique="c",
            agreed=True,
            revised_spec="s",
            usage=Usage(input_tokens=1, output_tokens=2),
            latency_s=0.123456,
        )
        assert r.to_dict() == {
            "model": "m",
            "agreed": True,
            "critique": "c",
            "revised_spec": "s",
            "error": None,
            "usage": {
                "input_tokens": 1,
                "output_tokens": 2,
                "total_tokens": 3,
                "cached_tokens": 0,
                "device_time_s": 0.0,
                "prefill_time_s": 0.0,
                "decode_time_s": 0.0,
            },
            "latency_s": 0.123,
            "span_id": "",
        }

    def test_round_result_partitions(self):
        """failed is the exact complement of successful (kills the
        dropped `not`), and round_num defaults to 1."""
        from adversarial_spec_tpu.debate.types import (
            ModelResponse,
            RoundResult,
        )

        ok = ModelResponse(model="a")
        bad = ModelResponse(model="b", error="boom")
        rr = RoundResult(responses=[ok, bad])
        assert rr.round_num == 1
        assert rr.successful == [ok]
        assert rr.failed == [bad]


class TestMutationHardeningRound2:
    def test_context_error_message_exact(self, tmp_path):
        """The missing path follows the label immediately (substring
        pins let a mutated label tail survive)."""
        import re

        from adversarial_spec_tpu.debate.core import load_context_files

        ghost = str(tmp_path / "ghost.txt")
        with pytest.raises(
            FileNotFoundError,
            match=rf"context file not found: {re.escape(ghost)}$",
        ):
            load_context_files([ghost])
