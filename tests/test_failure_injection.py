"""Failure detection / recovery tests with injected engine faults.

SURVEY §5: the reference's failure story is per-model retry with backoff,
errors captured not raised, and graceful round degradation; its fault
*injection* exists only as mock side_effects in tests. Same strategy here,
but the faults injected are the TPU engine's real failure modes
(RESOURCE_EXHAUSTED on OOM, transient device unavailability) at the
generate seam inside the real TpuEngine.
"""

from adversarial_spec_tpu.debate.core import RoundConfig, run_round
from adversarial_spec_tpu.engine import tpu as tpu_mod
from adversarial_spec_tpu.engine.dispatch import _ENGINE_CACHE
from adversarial_spec_tpu.engine.tpu import TpuEngine
from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

PARAMS = SamplingParams(max_new_tokens=8, greedy=True)


def _req(model="tpu://random-tiny"):
    return ChatRequest(model=model, system="s", user="u")


class TestEngineFaults:
    def test_oom_marked_transient(self, monkeypatch):
        def oom(*a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory on TPU")

        monkeypatch.setattr(tpu_mod, "generate", oom)
        comp = TpuEngine().chat([_req()], PARAMS)[0]
        assert not comp.ok
        assert comp.transient  # debate core will back off and retry

    def test_programming_error_permanent(self, monkeypatch):
        def bug(*a, **k):
            raise TypeError("bad argument")

        monkeypatch.setattr(tpu_mod, "generate", bug)
        comp = TpuEngine().chat([_req()], PARAMS)[0]
        assert not comp.ok
        assert not comp.transient  # no point retrying a bug

    def test_one_failing_group_does_not_kill_others(self, monkeypatch):
        real_generate = tpu_mod.generate
        calls = {"n": 0}

        def flaky_for_mistral(params, cfg, prompts, **kw):
            calls["n"] += 1
            if cfg.rope_theta == 10000.0:  # the mistral-tiny config
                raise RuntimeError("UNAVAILABLE: device lost")
            return real_generate(params, cfg, prompts, **kw)

        monkeypatch.setattr(tpu_mod, "generate", flaky_for_mistral)
        comps = TpuEngine().chat(
            [_req("tpu://random-tiny"), _req("tpu://random-mistral-tiny")],
            PARAMS,
        )
        assert comps[0].ok
        assert not comps[1].ok and comps[1].transient

    def test_round_recovers_after_transient_engine_fault(self, monkeypatch):
        """Full stack: first engine call OOMs, the debate core backs off
        and retries, the retry succeeds, the round completes."""
        real_generate = tpu_mod.generate
        attempts = {"n": 0}

        def oom_once(*a, **kw):
            attempts["n"] += 1
            if attempts["n"] == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: hbm")
            return real_generate(*a, **kw)

        monkeypatch.setattr(tpu_mod, "generate", oom_once)
        delays = []
        monkeypatch.setattr(
            RoundConfig, "sleep", staticmethod(delays.append)
        )
        _ENGINE_CACHE.pop("tpu", None)
        cfg = RoundConfig(sampling=PARAMS)
        result = run_round("# spec", ["tpu://random-tiny"], 1, cfg)
        assert result.responses[0].ok
        assert attempts["n"] == 2
        assert delays == [1.0]  # one backoff before the successful retry

    def test_load_failure_degrades_not_raises(self, monkeypatch):
        def explode(self, spec, dtype, mesh):
            raise RuntimeError("DEADLINE_EXCEEDED: checkpoint server")

        monkeypatch.setattr(TpuEngine, "_materialize", explode)
        comp = TpuEngine().chat([_req()], PARAMS)[0]
        assert not comp.ok
        assert comp.transient
