"""Failure detection / recovery tests with injected engine faults.

SURVEY §5: the reference's failure story is per-model retry with backoff,
errors captured not raised, and graceful round degradation; its fault
*injection* exists only as mock side_effects in tests. Two layers here:

- the legacy monkeypatch tests (TestEngineFaults) exercise raw exception
  classification at the generate seam inside the real TpuEngine;
- everything below them drives the FIRST-CLASS chaos injector
  (resilience/injector.py) — no monkeypatching — through the fault
  taxonomy, circuit-breaker state machine, scheduler slot eviction with
  partial-token results, and the full run_round breaker flow.
"""

import numpy as np
import pytest

from adversarial_spec_tpu.debate.core import RoundConfig, run_round
from adversarial_spec_tpu.engine import tpu as tpu_mod
from adversarial_spec_tpu.engine.dispatch import _ENGINE_CACHE
from adversarial_spec_tpu.engine.tpu import TpuEngine
from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams
from adversarial_spec_tpu.resilience import injector as injector_mod
from adversarial_spec_tpu.resilience.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerRegistry,
)
from adversarial_spec_tpu.resilience import faults as faults_mod
from adversarial_spec_tpu.resilience.faults import (
    FaultKind,
    classify,
    classify_message,
)
from adversarial_spec_tpu.resilience.injector import (
    FaultInjector,
    InjectedFault,
    parse_chaos_spec,
)

PARAMS = SamplingParams(max_new_tokens=8, greedy=True)


@pytest.fixture(autouse=True)
def _spec_off(monkeypatch):
    """This module pins fault classification/isolation semantics;
    speculation is default-on and only multiplies the jit programs each
    engine/batcher here compiles. Faults landing mid-verify (draft-page
    rollback on eviction, JSONL reconstruction) are pinned in
    tests/test_spec_batcher.py::TestSpecChaos."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)


def _req(model="tpu://random-tiny"):
    return ChatRequest(model=model, system="s", user="u")


class TestEngineFaults:
    def test_oom_marked_transient(self, monkeypatch):
        def oom(*a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory on TPU")

        monkeypatch.setattr(tpu_mod, "generate", oom)
        comp = TpuEngine().chat([_req()], PARAMS)[0]
        assert not comp.ok
        assert comp.transient  # debate core will back off and retry

    def test_programming_error_permanent(self, monkeypatch):
        def bug(*a, **k):
            raise TypeError("bad argument")

        monkeypatch.setattr(tpu_mod, "generate", bug)
        comp = TpuEngine().chat([_req()], PARAMS)[0]
        assert not comp.ok
        assert not comp.transient  # no point retrying a bug

    def test_one_failing_group_does_not_kill_others(self, monkeypatch):
        real_generate = tpu_mod.generate
        calls = {"n": 0}

        def flaky_for_mistral(params, cfg, prompts, **kw):
            calls["n"] += 1
            if cfg.rope_theta == 10000.0:  # the mistral-tiny config
                raise RuntimeError("UNAVAILABLE: device lost")
            return real_generate(params, cfg, prompts, **kw)

        monkeypatch.setattr(tpu_mod, "generate", flaky_for_mistral)
        comps = TpuEngine().chat(
            [_req("tpu://random-tiny"), _req("tpu://random-mistral-tiny")],
            PARAMS,
        )
        assert comps[0].ok
        assert not comps[1].ok and comps[1].transient

    def test_round_recovers_after_transient_engine_fault(self, monkeypatch):
        """Full stack: first engine call OOMs, the debate core backs off
        and retries, the retry succeeds, the round completes."""
        real_generate = tpu_mod.generate
        attempts = {"n": 0}

        def oom_once(*a, **kw):
            attempts["n"] += 1
            if attempts["n"] == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: hbm")
            return real_generate(*a, **kw)

        monkeypatch.setattr(tpu_mod, "generate", oom_once)
        delays = []
        monkeypatch.setattr(
            RoundConfig, "sleep", staticmethod(delays.append)
        )
        _ENGINE_CACHE.pop("tpu", None)
        cfg = RoundConfig(sampling=PARAMS)
        result = run_round("# spec", ["tpu://random-tiny"], 1, cfg)
        assert result.responses[0].ok
        assert attempts["n"] == 2
        assert delays == [1.0]  # one backoff before the successful retry

    def test_load_failure_degrades_not_raises(self, monkeypatch):
        def explode(self, spec, dtype, mesh):
            raise RuntimeError("DEADLINE_EXCEEDED: checkpoint server")

        monkeypatch.setattr(TpuEngine, "_materialize", explode)
        comp = TpuEngine().chat([_req()], PARAMS)[0]
        assert not comp.ok
        assert comp.transient


@pytest.mark.chaos
class TestFaultTaxonomy:
    """One classify() for every seam (replaces per-site marker lists)."""

    @pytest.mark.parametrize(
        "msg,kind",
        [
            ("RESOURCE_EXHAUSTED: out of memory on TPU", FaultKind.OOM),
            ("XlaRuntimeError: RESOURCE_EXHAUSTED: hbm", FaultKind.OOM),
            ("UNAVAILABLE: device lost", FaultKind.DEVICE_LOST),
            ("OUT_OF_RANGE: slice", FaultKind.DEVICE_LOST),
            ("ABORTED: preempted by scheduler", FaultKind.PREEMPTED),
            ("DEADLINE_EXCEEDED: step", FaultKind.TIMEOUT),
            ("TypeError: bad argument", FaultKind.BUG),
            ("something unrecognizable", FaultKind.BUG),
        ],
    )
    def test_message_table(self, msg, kind):
        assert classify_message(msg) is kind
        assert classify(RuntimeError(msg)) is kind

    def test_python_types_short_circuit(self):
        assert classify(TimeoutError("anything")) is FaultKind.TIMEOUT
        assert classify(MemoryError()) is FaultKind.OOM

    def test_oom_matches_only_as_uppercase_token(self):
        """'room'/'zoom' must not make a permanent bug retryable."""
        assert classify_message("hit OOM on device") is FaultKind.OOM
        assert classify_message("no room left for field") is FaultKind.BUG
        assert classify_message("zoom level invalid") is FaultKind.BUG
        assert classify_message("boom: oops") is FaultKind.BUG

    def test_only_bug_and_shed_are_permanent(self):
        """BUG (retrying a TypeError is noise) and SHED (a deliberate
        serving-policy answer — the client's retry_after_s is the
        retry contract, not our backoff ladder) never retry; every
        device-side kind does."""
        for kind in FaultKind:
            assert kind.transient == (
                kind not in (FaultKind.BUG, FaultKind.SHED)
            )

    def test_injected_faults_classify_exactly_and_textually(self):
        for kind in FaultKind:
            exc = InjectedFault(kind, "generate")
            assert classify(exc) is kind
            # String path must agree: engine boundaries stringify errors.
            assert classify_message(str(exc)) is kind

    def test_counters_accumulate(self):
        from adversarial_spec_tpu.resilience import faults

        faults.reset()
        faults.record(FaultKind.OOM, "scheduler_chunk")
        faults.record(FaultKind.OOM, "scheduler_chunk")
        faults.record(FaultKind.BUG, "generate")
        assert faults.snapshot() == {
            "scheduler_chunk.oom": 2,
            "generate.bug": 1,
        }
        faults.reset()
        assert faults.snapshot() == {}


@pytest.mark.chaos
class TestChaosSpec:
    def test_full_grammar(self):
        rules = parse_chaos_spec(
            "oom@scheduler_chunk:after=1:times=2:slot=1, "
            "device_lost@generate:p=0.25"
        )
        assert rules[0].kind is FaultKind.OOM
        assert (rules[0].after, rules[0].times, rules[0].slot) == (1, 2, 1)
        assert rules[1].seam == "generate" and rules[1].p == 0.25

    @pytest.mark.parametrize(
        "bad",
        ["oom", "oom@nowhere", "kaboom@generate", "oom@generate:p=x",
         "oom@generate:frequency=2"],
    )
    def test_bad_specs_fail_loudly(self, bad):
        with pytest.raises(ValueError):
            parse_chaos_spec(bad)

    def test_rule_arming(self):
        inj = FaultInjector(parse_chaos_spec("oom@kv_alloc:after=2:times=1"))
        inj.check("kv_alloc")
        inj.check("kv_alloc")
        with pytest.raises(InjectedFault):
            inj.check("kv_alloc")
        inj.check("kv_alloc")  # times=1: disarmed after one fire
        assert inj.fired == {"kv_alloc.oom": 1}

    def test_env_var_arms_process_injector(self, monkeypatch):
        monkeypatch.setenv("ADVSPEC_CHAOS", "bug@checkpoint_load:times=1")
        injector_mod.reset()
        with pytest.raises(InjectedFault):
            injector_mod.fire("checkpoint_load")
        injector_mod.fire("checkpoint_load")  # disarmed
        injector_mod.reset()


@pytest.mark.chaos
class TestCircuitBreaker:
    """closed → open → half-open → closed/open, on a fake clock."""

    def _registry(self, threshold=3, cooldown=30.0):
        clock = [0.0]
        reg = BreakerRegistry(
            threshold=threshold, cooldown_s=cooldown, clock=lambda: clock[0]
        )
        return reg, clock

    def test_opens_after_threshold_consecutive_failures(self):
        reg, _ = self._registry(threshold=3)
        for _ in range(2):
            reg.record("m", ok=False, kind=FaultKind.OOM)
        assert reg.breaker("m").state == CLOSED
        reg.record("m", ok=False, kind=FaultKind.OOM)
        assert reg.breaker("m").state == OPEN
        assert not reg.allow("m")

    def test_success_resets_the_streak(self):
        reg, _ = self._registry(threshold=2)
        reg.record("m", ok=False)
        reg.record("m", ok=True)
        reg.record("m", ok=False)
        assert reg.breaker("m").state == CLOSED

    def test_half_open_probe_recovers(self):
        reg, clock = self._registry(threshold=1, cooldown=10.0)
        reg.record("m", ok=False, kind=FaultKind.DEVICE_LOST)
        assert not reg.allow("m")
        clock[0] = 10.0
        assert reg.allow("m")  # the probe
        assert reg.breaker("m").state == HALF_OPEN
        assert not reg.allow("m")  # one probe at a time
        reg.record("m", ok=True)
        assert reg.breaker("m").state == CLOSED
        assert reg.allow("m")

    def test_failed_probe_reopens_with_fresh_cooldown(self):
        """A TRANSIENT probe failure re-enters the normal cooldown
        cycle — the fault may clear by itself, so re-probe on
        schedule."""
        reg, clock = self._registry(threshold=1, cooldown=10.0)
        reg.record("m", ok=False, kind=FaultKind.OOM)
        clock[0] = 10.0
        assert reg.allow("m")
        reg.record("m", ok=False, kind=FaultKind.OOM)
        assert reg.breaker("m").state == OPEN
        assert not reg.breaker("m").hard_open
        clock[0] = 19.0  # 9s into the NEW cooldown
        assert not reg.allow("m")
        clock[0] = 20.0
        assert reg.allow("m")

    def test_non_transient_probe_failure_opens_hard(self):
        """The satellite fix: a half-open probe failing with a
        NON-transient FaultKind (BUG — deterministic, waiting does not
        heal it) must not re-enter the normal cooldown like a
        transient one: the next probe waits HARD_OPEN_FACTOR (8x)
        cooldowns instead of burning one failed request per cycle."""
        from adversarial_spec_tpu.resilience.breaker import HARD_OPEN_FACTOR

        reg, clock = self._registry(threshold=1, cooldown=10.0)
        reg.record("m", ok=False, kind=FaultKind.OOM)
        clock[0] = 10.0
        assert reg.allow("m")  # the probe
        reg.record("m", ok=False, kind=FaultKind.BUG)  # deterministic
        b = reg.breaker("m")
        assert b.state == OPEN and b.hard_open
        # One normal cooldown later: still hard-open, NO probe.
        clock[0] = 20.0
        assert not reg.allow("m")
        assert reg.cooldown_remaining("m") == 10.0 * (HARD_OPEN_FACTOR - 1)
        # The scaled cooldown elapses: probe again (bugs do get fixed
        # by redeploys — rarely is not never).
        clock[0] = 10.0 + 10.0 * HARD_OPEN_FACTOR
        assert reg.allow("m")
        # A successful probe clears the hard flag entirely.
        reg.record("m", ok=True)
        assert b.state == CLOSED and not b.hard_open

    def test_hard_open_survives_the_session_snapshot(self):
        """The hard flag and its scaled remaining cooldown cross the
        process boundary with the rest of the breaker snapshot."""
        reg, clock = self._registry(threshold=1, cooldown=10.0)
        reg.record("m", ok=False, kind=FaultKind.OOM)
        clock[0] = 10.0
        assert reg.allow("m")
        reg.record("m", ok=False, kind=FaultKind.BUG)
        clock[0] = 30.0  # 20s into the 80s hard cooldown
        snap = reg.snapshot_for_resume()
        assert snap["m"]["hard"] is True
        assert snap["m"]["cooldown_remaining"] == 60.0

        reg2, clock2 = self._registry(threshold=1, cooldown=10.0)
        reg2.restore(snap)
        assert reg2.breaker("m").hard_open
        clock2[0] = 59.0
        assert not reg2.allow("m")
        clock2[0] = 60.0
        assert reg2.allow("m")

    def test_replica_key_namespaces_pairs(self):
        """The fleet generalization: (replica, model) pairs and bare
        model ids coexist in one registry without crosstalk."""
        from adversarial_spec_tpu.resilience.breaker import replica_key

        reg, _ = self._registry(threshold=1)
        pair = replica_key("r0", "tpu://m")
        assert pair == "r0::tpu://m"
        reg.record(pair, ok=False)
        assert not reg.allow(pair)
        assert reg.allow("tpu://m")  # the bare model is unaffected
        assert reg.allow(replica_key("r1", "tpu://m"))  # other replicas too

    def test_transition_counters_and_states(self):
        reg, clock = self._registry(threshold=1, cooldown=5.0)
        reg.record("m", ok=False, kind=FaultKind.PREEMPTED)
        clock[0] = 5.0
        reg.allow("m")
        reg.record("m", ok=True)
        assert reg.counters() == {
            "breaker.to_open": 1.0,
            "breaker.to_half_open": 1.0,
            "breaker.to_closed": 1.0,
        }
        snap = reg.states()["m"]
        assert snap["state"] == CLOSED and snap["last_fault"] is None

    def test_transition_counters_survive_heavy_flapping(self):
        """Counters are monotonic, not derived from the bounded debug
        log: 100 open/close cycles must report 100, not ~64."""
        reg, clock = self._registry(threshold=1, cooldown=1.0)
        for i in range(100):
            reg.record("m", ok=False)
            clock[0] += 1.0
            assert reg.allow("m")  # half-open probe
            reg.record("m", ok=True)
        assert reg.counters()["breaker.to_open"] == 100.0
        assert reg.counters()["breaker.to_closed"] == 100.0
        assert len(reg.breaker("m").transitions) <= 64  # log stays bounded

    def test_disabled_registry_always_allows(self):
        reg, _ = self._registry(threshold=1)
        reg.configure(enabled=False)
        reg.record("m", ok=False)
        assert reg.allow("m")
        assert reg.breaker("m").state == CLOSED

    def test_lost_probe_expires_after_one_cooldown(self):
        """A half-open probe whose outcome is never recorded (the caller
        died mid-round) must not ban the model forever."""
        reg, clock = self._registry(threshold=1, cooldown=10.0)
        reg.record("m", ok=False)
        clock[0] = 10.0
        assert reg.allow("m")  # probe granted, outcome never recorded
        assert not reg.allow("m")
        clock[0] = 20.0  # one full cooldown later: probe presumed lost
        assert reg.allow("m")
        reg.record("m", ok=True)
        assert reg.breaker("m").state == CLOSED

    def test_snapshot_restores_across_processes(self):
        """One CLI invocation is one round: an OPEN circuit must survive
        via the session snapshot, with the REMAINING cooldown (monotonic
        timestamps don't cross processes)."""
        reg, clock = self._registry(threshold=1, cooldown=30.0)
        reg.record("m", ok=False, kind=FaultKind.OOM)
        clock[0] = 10.0  # 20s of cooldown left at "process exit"
        snap = reg.snapshot_for_resume()
        assert snap["m"]["state"] == OPEN
        assert snap["m"]["cooldown_remaining"] == 20.0
        assert snap["m"]["last_fault"] == "oom"

        # "Next process": fresh registry, fresh clock epoch.
        reg2, clock2 = self._registry(threshold=1, cooldown=30.0)
        reg2.restore(snap)
        assert not reg2.allow("m")
        clock2[0] = 19.0
        assert not reg2.allow("m")
        clock2[0] = 20.0
        assert reg2.allow("m")  # half-open probe, right on schedule

    def test_snapshot_skips_clean_breakers_and_maps_half_open(self):
        reg, clock = self._registry(threshold=2, cooldown=10.0)
        reg.record("clean", ok=True)
        reg.record("failing", ok=False)  # 1 < threshold: still CLOSED
        reg.record("probing", ok=False)
        reg.record("probing", ok=False)
        clock[0] = 10.0
        assert reg.allow("probing")  # now HALF_OPEN, probe in flight
        snap = reg.snapshot_for_resume()
        assert "clean" not in snap
        assert snap["failing"]["failures"] == 1
        # Lost probe resumes as OPEN with nothing left to wait.
        assert snap["probing"]["state"] == OPEN
        assert snap["probing"]["cooldown_remaining"] == 0.0


@pytest.mark.chaos
class TestBreakerInRound:
    """Acceptance: a model whose breaker is open is skipped in the next
    run_round WITHOUT consuming its 3-retry budget, and recovers via the
    half-open probe — chaos injected at the generate seam, no
    monkeypatched engine internals."""

    def test_open_skip_and_half_open_recovery(self, monkeypatch):
        monkeypatch.setattr(
            RoundConfig, "sleep", staticmethod(lambda s: None)
        )
        clock = [0.0]
        reg = BreakerRegistry(
            threshold=3, cooldown_s=60.0, clock=lambda: clock[0]
        )
        cfg = RoundConfig(sampling=PARAMS, breakers=reg)
        model = "tpu://random-tiny"
        inj = FaultInjector(parse_chaos_spec("oom@generate"))
        injector_mod.install(inj)

        r1 = run_round("# spec", [model], 1, cfg)
        assert not r1.responses[0].ok
        assert reg.breaker(model).state == OPEN
        # Transient fault: the reference's full 3-attempt budget ran.
        hits_r1 = inj.seam_hits["generate"]
        assert hits_r1 == 3

        r2 = run_round("# spec", [model], 2, cfg)
        assert "circuit open" in r2.responses[0].error
        # Skipped up front: ZERO engine calls, no retry budget consumed.
        assert inj.seam_hits["generate"] == hits_r1

        clock[0] = 61.0
        injector_mod.reset()  # chaos off: the half-open probe can succeed
        r3 = run_round("# spec", [model], 3, cfg)
        assert r3.responses[0].ok
        assert reg.breaker(model).state == CLOSED

    def test_failed_probe_costs_one_attempt_not_three(self, monkeypatch):
        """The half-open probe is ONE attempt: when it fails, the
        reopened circuit must stop the remaining retry budget (the whole
        point of the breaker) instead of backing off twice more."""
        monkeypatch.setattr(
            RoundConfig, "sleep", staticmethod(lambda s: None)
        )
        clock = [0.0]
        reg = BreakerRegistry(
            threshold=1, cooldown_s=30.0, clock=lambda: clock[0]
        )
        model = "tpu://random-tiny"
        reg.record(model, ok=False, kind=FaultKind.OOM)  # circuit opens
        clock[0] = 30.0  # cooldown elapsed: next round is a probe round
        inj = FaultInjector(parse_chaos_spec("oom@generate"))
        injector_mod.install(inj)
        cfg = RoundConfig(sampling=PARAMS, breakers=reg)
        result = run_round("# spec", [model], 1, cfg)
        assert not result.responses[0].ok
        assert "RESOURCE_EXHAUSTED" in result.responses[0].error
        # Exactly one engine call: the failed probe reopened the circuit
        # and the retry loop respected it.
        assert inj.seam_hits["generate"] == 1
        assert reg.breaker(model).state == OPEN

    def test_open_breaker_does_not_block_other_models(self, monkeypatch):
        monkeypatch.setattr(
            RoundConfig, "sleep", staticmethod(lambda s: None)
        )
        reg = BreakerRegistry(threshold=1, cooldown_s=1e9)
        reg.record("tpu://random-tiny", ok=False, kind=FaultKind.BUG)
        cfg = RoundConfig(sampling=PARAMS, breakers=reg)
        result = run_round(
            "# spec",
            ["tpu://random-tiny", "mock://agree"],
            1,
            cfg,
        )
        by_model = {r.model: r for r in result.responses}
        assert "circuit open" in by_model["tpu://random-tiny"].error
        assert by_model["mock://agree"].ok


@pytest.mark.chaos
class TestSchedulerFaultIsolation:
    """Acceptance: an injected transient fault on one scheduler slot
    mid-drain yields partial tokens for that request and unchanged,
    complete results for all co-resident requests."""

    @pytest.fixture(scope="class")
    def tiny_model(self):
        import jax
        import jax.numpy as jnp

        from adversarial_spec_tpu.models import transformer as T
        from adversarial_spec_tpu.models.config import get_config

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        return params, cfg

    def _reference(self, params, cfg, prompt, max_new):
        from adversarial_spec_tpu.engine.generate import generate

        out = generate(
            params, cfg, [prompt], max_new_tokens=max_new,
            eos_ids=[], greedy=True, speculative=False,
        )
        return np.asarray(out.tokens[0, : out.n_generated[0]])

    def _batcher(self, params, cfg, **kw):
        from adversarial_spec_tpu.engine.scheduler import ContinuousBatcher

        kw.setdefault("max_batch", 2)
        kw.setdefault("max_new_cap", 16)
        kw.setdefault("chunk", 4)
        return ContinuousBatcher(params, cfg, **kw)

    def test_persistent_fault_evicts_one_slot_with_partial_tokens(
        self, tiny_model
    ):
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        params, cfg = tiny_model
        # times=2: the first eviction requeues (OOM is transient, one
        # retry), the second fire on the retry finalizes the partial.
        injector_mod.install(
            FaultInjector(
                parse_chaos_spec("oom@scheduler_chunk:after=1:times=2:slot=1")
            )
        )
        b = self._batcher(params, cfg)
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5, 9],
                              max_new_tokens=12))
        b.submit(SchedRequest(req_id=1, prompt_ids=[2, 6],
                              max_new_tokens=12))
        free0 = b.allocator.free_pages
        results = b.run_all()
        assert [r.req_id for r in results] == [0, 1]
        healthy, faulted = results
        # Co-resident request: byte-identical to its solo reference.
        assert healthy.error is None
        np.testing.assert_array_equal(
            healthy.tokens, self._reference(params, cfg, [1, 5, 9], 12)
        )
        # Faulted request: partial tokens + taxonomy metadata.
        assert faulted.fault_kind == "oom"
        assert faulted.error and "RESOURCE_EXHAUSTED" in faulted.error
        assert 1 <= faulted.n_generated < 12
        assert len(faulted.tokens) == faulted.n_generated
        # Evicted slot's pages were freed (no leak).
        assert b.allocator.free_pages == free0
        # Both fires landed in the process-wide fault counters (the
        # store the CLI's resilience report snapshots).
        assert faults_mod.snapshot() == {"scheduler_chunk.oom": 2}

    def test_transient_fault_retries_once_to_full_completion(
        self, tiny_model
    ):
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        params, cfg = tiny_model
        injector_mod.install(
            FaultInjector(
                parse_chaos_spec(
                    "device_lost@scheduler_chunk:after=1:times=1:slot=0"
                )
            )
        )
        b = self._batcher(params, cfg)
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5, 9],
                              max_new_tokens=12))
        b.submit(SchedRequest(req_id=1, prompt_ids=[2, 6],
                              max_new_tokens=12))
        results = b.run_all()
        # Retry-once-on-transient: the evicted request re-admitted and
        # completed in full; both rows match their solo references.
        for r, prompt in zip(results, [[1, 5, 9], [2, 6]]):
            assert r.error is None, r.error
            np.testing.assert_array_equal(
                r.tokens, self._reference(params, cfg, prompt, 12)
            )
        assert faults_mod.snapshot() == {"scheduler_chunk.device_lost": 1}

    def test_permanent_admission_fault_isolated_to_one_request(
        self, tiny_model
    ):
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        params, cfg = tiny_model
        injector_mod.install(
            FaultInjector(parse_chaos_spec("bug@kv_alloc:times=1"))
        )
        b = self._batcher(params, cfg)
        total_pages = b.allocator.free_pages
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5, 9],
                              max_new_tokens=8))
        b.submit(SchedRequest(req_id=1, prompt_ids=[2, 6],
                              max_new_tokens=8))
        results = b.run_all()
        assert [r.req_id for r in results] == [0, 1]
        assert results[0].fault_kind == "bug"  # BUG: no retry
        assert results[0].n_generated == 0
        assert results[1].error is None
        np.testing.assert_array_equal(
            results[1].tokens, self._reference(params, cfg, [2, 6], 8)
        )
        assert b.allocator.free_pages == total_pages

    def test_fault_inside_finish_admission_is_isolated(
        self, tiny_model, monkeypatch
    ):
        """A real fault during the admission's pool scatter (inside
        _finish_admission, past the prefill) must abort ONLY that
        admission — pages freed, request retried-once — not crash the
        drain with the admission record already cleared."""
        import adversarial_spec_tpu.engine.scheduler as sched_mod
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        params, cfg = tiny_model
        real_write = sched_mod.write_tokens
        fired = {"n": 0}

        def oom_once(*a, **kw):
            if fired["n"] == 0:
                fired["n"] += 1
                raise RuntimeError("RESOURCE_EXHAUSTED: pool scatter")
            return real_write(*a, **kw)

        monkeypatch.setattr(sched_mod, "write_tokens", oom_once)
        b = self._batcher(params, cfg)
        total_pages = b.allocator.free_pages
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5, 9],
                              max_new_tokens=8))
        b.submit(SchedRequest(req_id=1, prompt_ids=[2, 6],
                              max_new_tokens=8))
        results = b.run_all()
        assert [r.req_id for r in results] == [0, 1]
        # Transient: the aborted admission got its one requeue and
        # completed; both rows match their solo references.
        for r, prompt in zip(results, [[1, 5, 9], [2, 6]]):
            assert r.error is None, r.error
            np.testing.assert_array_equal(
                r.tokens, self._reference(params, cfg, prompt, 8)
            )
        assert faults_mod.snapshot() == {"admission.oom": 1}
        assert b.allocator.free_pages == total_pages

    def test_kv_alloc_fault_autodumps_reconstructable_flight_record(
        self, tiny_model, tmp_path
    ):
        """Acceptance: an injected ``kv_alloc`` fault produces a JSONL
        dump — written the moment the fault resolves, not at drain end,
        to the fault sibling of the armed path so the end-of-round dump
        can never clobber it — whose final events reconstruct the
        eviction: the slot the admission targeted, the pages freed, and
        the fault kind."""
        import json

        from adversarial_spec_tpu import obs
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        params, cfg = tiny_model
        dump = tmp_path / "flight.jsonl"
        obs.configure(enabled=True, events_out=str(dump))
        obs.reset_stats()
        try:
            injector_mod.install(
                FaultInjector(parse_chaos_spec("bug@kv_alloc:times=1"))
            )
            b = self._batcher(params, cfg)
            b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5, 9],
                                  max_new_tokens=8))
            b.submit(SchedRequest(req_id=1, prompt_ids=[2, 6],
                                  max_new_tokens=8))
            results = b.run_all()
        finally:
            obs.configure(events_out="")
        assert results[0].fault_kind == "bug"
        fault_dump = tmp_path / "flight.fault.jsonl"
        assert (
            fault_dump.exists()
        ), "fault did not auto-dump the flight recorder"
        events = [
            json.loads(line) for line in fault_dump.read_text().splitlines()
        ]
        for e in events:
            assert obs.validate_event(e) == [], e
        # Reconstruction: the FaultEvent names the seam, kind, slot and
        # pages freed; the victim's lifecycle ends in "evicted".
        faults_evs = [e for e in events if e["type"] == "fault"]
        assert faults_evs, "no FaultEvent in the dump"
        fe = faults_evs[-1]
        assert fe["seam"] == "kv_alloc" and fe["kind"] == "bug"
        assert fe["req_id"] == 0 and fe["slot"] == 0
        # kv_alloc fires BEFORE any page reservation: nothing to free.
        assert fe["pages_freed"] == 0 and fe["requeued"] is False
        victim = [
            e
            for e in events
            if e["type"] == "request" and e["req_id"] == 0
        ]
        assert victim[-1]["state"] == "evicted"

    def test_decode_fault_dump_records_slot_and_pages_freed(
        self, tiny_model, tmp_path
    ):
        """A mid-decode eviction's dump carries NONZERO pages_freed and
        the evicted slot — the triage walkthrough docs/observability.md
        promises."""
        import json

        from adversarial_spec_tpu import obs
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        params, cfg = tiny_model
        dump = tmp_path / "flight.jsonl"
        obs.configure(enabled=True, events_out=str(dump))
        obs.reset_stats()
        try:
            injector_mod.install(
                FaultInjector(
                    parse_chaos_spec(
                        "oom@scheduler_chunk:after=1:times=2:slot=1"
                    )
                )
            )
            b = self._batcher(params, cfg)
            b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5, 9],
                                  max_new_tokens=12))
            b.submit(SchedRequest(req_id=1, prompt_ids=[2, 6],
                                  max_new_tokens=12))
            results = b.run_all()
        finally:
            obs.configure(events_out="")
        assert results[1].fault_kind == "oom"
        fault_dump = tmp_path / "flight.fault.jsonl"
        events = [
            json.loads(line) for line in fault_dump.read_text().splitlines()
        ]
        fe = [e for e in events if e["type"] == "fault"][-1]
        assert fe["kind"] == "oom" and fe["seam"] == "scheduler_chunk"
        assert fe["slot"] == 1
        assert fe["pages_freed"] > 0  # the eviction returned real pages
        # The dump is schema-valid end to end (obs_dump would exit 0).
        for e in events:
            assert obs.validate_event(e) == [], e

    def test_engine_surfaces_slot_fault_as_transient_completion(self):
        """Through the TpuEngine: a faulted slot becomes an errored,
        transient Completion (the debate core's retry applies) while the
        co-resident completion stays clean."""
        from adversarial_spec_tpu.engine.registry import (
            ModelSpec,
            save_registry_entry,
        )

        save_registry_entry(
            ModelSpec(alias="chaos-tiny", family="llama", size="tiny",
                      kv="paged", dtype="float32", mesh={"dp": 1})
        )
        injector_mod.install(
            FaultInjector(
                parse_chaos_spec("oom@scheduler_chunk:after=1:times=2:slot=1")
            )
        )
        # Budget > the batcher's 32-step chunk so the drain spans several
        # chunks and the after=1 rule has a second chunk to fire on.
        comps = TpuEngine().chat(
            [_req("tpu://chaos-tiny"), _req("tpu://chaos-tiny")],
            SamplingParams(max_new_tokens=80, greedy=True),
        )
        oks = [c for c in comps if c.ok]
        bad = [c for c in comps if not c.ok]
        assert len(oks) == 1 and len(bad) == 1
        assert bad[0].transient  # OOM → debate core backs off and retries
        assert "RESOURCE_EXHAUSTED" in bad[0].error
