"""Fleet layer tests: hash ring, replicas, router, failover, chaos.

Most coverage runs on in-process replicas (fresh mock engines — fully
deterministic, no subprocesses); the worker transport gets one focused
protocol test plus the tier-1 replica-kill chaos smoke (the full drill
from tools/chaos_run.py --replica-kill, marked ``chaos``), which pins
the lose-a-replica-lose-nothing contract with real SIGKILLs.
"""

from __future__ import annotations

import json

import pytest

from adversarial_spec_tpu import fleet as fleet_mod
from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams
from adversarial_spec_tpu.fleet.hashring import HashRing
from adversarial_spec_tpu.fleet.replica import (
    InProcessReplica,
    ReplicaDead,
    WorkerReplica,
)
from adversarial_spec_tpu.fleet.router import FleetEngine, FleetRouter
from adversarial_spec_tpu.resilience import breaker as breaker_mod
from adversarial_spec_tpu.resilience import injector as injector_mod
from adversarial_spec_tpu.resilience.injector import FaultInjector, parse_chaos_spec

PARAMS = SamplingParams()


def _req(model="mock://critic", key="debate-A", user=None, **kw):
    return ChatRequest(
        model=model,
        system="You are a reviewer.",
        user=(
            user
            if user is not None
            else "Debate round 1\n--- DOCUMENT ---\nA spec body.\n"
            "--- END DOCUMENT ---"
        ),
        affinity_key=key,
        **kw,
    )


class TestHashRing:
    def test_deterministic_and_sticky(self):
        a = HashRing(["r0", "r1", "r2"])
        b = HashRing(["r2", "r0", "r1"])  # insertion order irrelevant
        for key in (f"debate-{i}" for i in range(20)):
            assert a.primary(key) == b.primary(key)
            assert a.primary(key) == a.primary(key)

    def test_preference_is_distinct_and_complete(self):
        ring = HashRing(["r0", "r1", "r2"])
        pref = ring.preference("debate-x")
        assert sorted(pref) == ["r0", "r1", "r2"]
        assert pref[0] == ring.primary("debate-x")

    def test_membership_change_moves_only_the_affected_arc(self):
        """The consistent-hashing contract: removing one replica moves
        ONLY the keys it owned; everyone else's cache stays warm."""
        ring = HashRing(["r0", "r1", "r2"])
        keys = [f"debate-{i}" for i in range(64)]
        before = {k: ring.primary(k) for k in keys}
        ring.remove("r1")
        for k in keys:
            if before[k] != "r1":
                assert ring.primary(k) == before[k]
        ring.add("r1")
        assert {k: ring.primary(k) for k in keys} == before

    def test_add_moves_about_one_over_n_keys_to_the_new_node(self):
        """The scale-OUT half of the consistent-hashing contract: a
        node joining an N-1 ring takes ~1/N of the keyspace, every
        moved key moves TO the newcomer, and nothing else reshuffles."""
        ring = HashRing(["r0", "r1", "r2"])
        keys = [f"debate-{i}" for i in range(2000)]
        before = {k: ring.primary(k) for k in keys}
        ring.add("r3")
        moved = [k for k in keys if ring.primary(k) != before[k]]
        frac = len(moved) / len(keys)
        assert 0.5 / 4 <= frac <= 2.0 / 4, frac
        assert all(ring.primary(k) == "r3" for k in moved)

    def test_add_keeps_preference_order_of_existing_nodes(self):
        """Preference-order stability on add: the newcomer's vnode
        points interleave into the walk, but the RELATIVE failover
        order of the pre-existing replicas is untouched for every key
        (unmoved keys keep their failover order; moved keys keep their
        old chain right behind the new primary) — a scale-out must not
        scramble where a later failover would land."""
        ring = HashRing(["r0", "r1", "r2"])
        keys = [f"debate-{i}" for i in range(256)]
        pref_before = {k: ring.preference(k) for k in keys}
        ring.add("r3")
        for k in keys:
            after_without_new = [
                r for r in ring.preference(k) if r != "r3"
            ]
            assert after_without_new == pref_before[k], k

    def test_keys_spread_across_replicas(self):
        ring = HashRing(["r0", "r1", "r2"])
        owners = {ring.primary(f"debate-{i}") for i in range(64)}
        assert owners == {"r0", "r1", "r2"}

    def test_empty_ring(self):
        ring = HashRing()
        assert ring.primary("k") is None
        assert ring.preference("k") == []


class TestFleetConfig:
    def test_env_defaults(self, monkeypatch):
        monkeypatch.delenv("ADVSPEC_FLEET", raising=False)
        assert fleet_mod.env_enabled() is False  # fleet is opt-in
        monkeypatch.setenv("ADVSPEC_FLEET", "1")
        assert fleet_mod.env_enabled() is True
        monkeypatch.setenv("ADVSPEC_FLEET_REPLICAS", "5")
        assert fleet_mod.env_replicas() == 5
        monkeypatch.setenv("ADVSPEC_FLEET_TRANSPORT", "worker")
        assert fleet_mod.env_transport() == "worker"
        monkeypatch.setenv("ADVSPEC_FLEET_TRANSPORT", "bogus")
        assert fleet_mod.env_transport() == "inproc"

    def test_bad_transport_fails_at_the_knob(self):
        with pytest.raises(ValueError, match="unknown fleet transport"):
            fleet_mod.configure(transport="bogus")

    def test_armed_needs_two_replicas(self):
        fleet_mod.configure(enabled=True, replicas=1)
        assert not fleet_mod.armed()
        fleet_mod.configure(replicas=2)
        assert fleet_mod.armed()
        fleet_mod.configure(enabled=False)
        assert not fleet_mod.armed()

    def test_snapshot_payload(self):
        snap = fleet_mod.snapshot()
        for key in (
            "routed_requests",
            "affinity_hits",
            "failover_hops",
            "breaker_skips",
            "reissued_requests",
            "completed_requests",
            "duplicated_completions",
            "affinity_hit_rate",
            "enabled",
            "replicas",
            "transport",
        ):
            assert key in snap


class TestInProcessReplica:
    def test_serves_and_accounts(self):
        rep = InProcessReplica("r0")
        comps = rep.chat_batch([_req(), _req(model="mock://agree")], PARAMS)
        assert all(c.ok for c in comps)
        assert rep.served == {"mock://critic": 1, "mock://agree": 1}
        assert rep.busy_s > 0
        rep.check()  # invariants clean
        assert rep.stats()["replica"] == "r0"

    def test_consumer_keeps_original_batch_indexing(self):
        rep = InProcessReplica("r0")
        seen = []

        def consumer(row, text):
            seen.append(row)
            return True

        rep.chat_batch([_req(), _req()], PARAMS, consumer=consumer)
        # Each request is served as its own single-row engine call, but
        # the consumer must see the fleet batch's indexing.
        assert set(seen) == {0, 1}

    def test_replicas_do_not_share_prefix_caches(self):
        """The lifecycle seam: each replica owns a FRESH engine — the
        second replica serving the same prompt pays the full prefill
        (no cross-replica device-cache magic)."""
        r0, r1 = InProcessReplica("r0"), InProcessReplica("r1")
        c0 = r0.chat_batch([_req()], PARAMS)[0]
        c1 = r1.chat_batch([_req()], PARAMS)[0]
        assert c0.usage.cached_tokens == c1.usage.cached_tokens == 0

    def test_closed_replica_raises(self):
        rep = InProcessReplica("r0")
        rep.close()
        with pytest.raises(ReplicaDead):
            rep.chat_batch([_req()], PARAMS)


class _DyingReplica:
    """Serves ``die_after`` requests of a batch, then dies — the
    in-process stand-in for a SIGKILLed worker."""

    def __init__(self, replica_id: str, die_after: int):
        self.id = replica_id
        self.die_after = die_after
        self.closed = False

    def ping(self) -> bool:
        return not self.closed

    def chat_batch(self, requests, params, consumer=None, on_completion=None):
        partial = {}
        for j, req in enumerate(requests[: self.die_after]):
            comp = Completion(text=f"{self.id}:{req.model}")
            partial[j] = comp
            if on_completion is not None:
                on_completion(j, comp)
        raise ReplicaDead(self.id, "scripted death", partial)

    def validate(self, model):
        return None

    def check(self) -> None:
        pass

    def stats(self) -> dict:
        return {"replica": self.id, "served": {}, "busy_s": 0.0}

    def close(self) -> None:
        self.closed = True


class TestRouterRouting:
    def _engine(self, n=2, **kw):
        return FleetEngine(replicas=n, transport="inproc", **kw)

    def test_affinity_is_sticky_across_submits(self):
        eng = self._engine(3)
        for _ in range(3):
            eng.chat([_req(key="debate-sticky")] * 2, PARAMS)
        served = {
            s["replica"]: sum(s["served"].values())
            for s in eng.router.replica_stats()
            if s["served"]
        }
        assert len(served) == 1  # one replica owns the debate
        assert sum(served.values()) == 6
        eng.shutdown()

    def test_distinct_debates_spread(self):
        eng = self._engine(3)
        for d in range(12):
            eng.chat([_req(key=f"debate-{d}")], PARAMS)
        used = [s for s in eng.router.replica_stats() if s["served"]]
        assert len(used) >= 2
        eng.shutdown()

    def test_random_mode_round_robins(self):
        eng = self._engine(3, affinity=False)
        eng.chat([_req(key="debate-same")] * 3, PARAMS)
        used = [s for s in eng.router.replica_stats() if s["served"]]
        assert len(used) == 3  # same key, three replicas: no stickiness
        assert fleet_mod.stats.affinity_hits == 0
        eng.shutdown()

    def test_route_events_carry_trace_ids(self):
        obs_mod.reset_stats()
        eng = self._engine(2)
        eng.chat(
            [_req(trace_id="tr-001-01", span_id="tr-001-01/s00")], PARAMS
        )
        routes = [
            e
            for e in obs_mod.recorder.events()
            if e["type"] == "route"
        ]
        assert routes and routes[0]["trace_id"] == "tr-001-01"
        assert routes[0]["span_id"] == "tr-001-01/s00"
        assert routes[0]["reason"] == "affinity" and routes[0]["hop"] == 0
        eng.shutdown()

    def test_breaker_open_pair_skips_replica(self):
        reg = breaker_mod.BreakerRegistry(threshold=1, cooldown_s=1e9)
        eng = self._engine(2, breakers=reg)
        primary = eng.router._ring.preference("debate-A")[0]
        reg.record(
            breaker_mod.replica_key(primary, "mock://critic"), ok=False
        )
        comps = eng.chat([_req()], PARAMS)
        assert comps[0].ok
        assert fleet_mod.stats.breaker_skips >= 1
        # The pair breaker drained the primary for this model only:
        # the OTHER replica served it.
        assert not eng.router.replica(primary).served
        eng.shutdown()

    def test_injected_replica_fault_fails_over(self):
        injector_mod.install(
            FaultInjector(parse_chaos_spec("device_lost@replica:times=1"))
        )
        reg = breaker_mod.BreakerRegistry(threshold=3)
        eng = self._engine(2, breakers=reg)
        comps = eng.chat([_req(), _req()], PARAMS)
        assert all(c.ok for c in comps)
        assert fleet_mod.stats.failover_hops == 2
        # Both replicas still alive: the fault was replica-LEVEL, not
        # a transport death.
        assert len(eng.router.alive_ids()) == 2
        # The faulted pair fed its breaker.
        primary = eng.router._ring.preference("debate-A")[0]
        pair = breaker_mod.replica_key(primary, "mock://critic")
        assert reg.breaker(pair).failures == 2
        eng.shutdown()

    def test_no_routable_replica_resolves_with_error(self):
        injector_mod.install(
            FaultInjector(parse_chaos_spec("device_lost@replica:times=1"))
        )
        eng = self._engine(1)
        comps = eng.chat([_req()], PARAMS)
        assert not comps[0].ok
        assert "no routable replica" in comps[0].error
        eng.shutdown()

    def test_replica_death_keeps_partials_and_reroutes_rest(self):
        key = "debate-death"
        primary = HashRing(["r0", "r1"]).preference(key)[0]
        survivor = "r1" if primary == "r0" else "r0"
        dying = _DyingReplica(primary, die_after=2)
        healthy = InProcessReplica(survivor)
        router = FleetRouter([dying, healthy])
        reqs = [_req(model=f"mock://critic?v={k}", key=key) for k in range(4)]
        comps = router.submit(reqs, PARAMS)
        assert all(c.ok for c in comps)
        # The two that landed before death are the dying replica's.
        assert [c.text for c in comps[:2]] == [
            f"{primary}:mock://critic?v=0",
            f"{primary}:mock://critic?v=1",
        ]
        # The remainder re-routed; the survivor never saw the first two.
        assert healthy.served == {
            "mock://critic?v=2": 1,
            "mock://critic?v=3": 1,
        }
        assert fleet_mod.stats.reissued_requests == 2
        assert fleet_mod.stats.duplicated_completions == 0
        assert router.alive_ids() == [survivor]
        assert router._dead == {primary: "dead"}

    def test_heartbeat_miss_retires(self):
        obs_mod.reset_stats()
        eng = self._engine(2)
        victim = eng.router.alive_ids()[0]
        eng.router.replica(victim).closed = True  # ping now fails
        eng.router.health_check()
        assert victim not in eng.router.alive_ids()
        assert fleet_mod.stats.heartbeat_failures == 1
        ops = [
            (e["replica"], e["op"])
            for e in obs_mod.recorder.events()
            if e["type"] == "replica"
        ]
        assert (victim, "heartbeat_miss") in ops
        assert (victim, "retire") in ops
        eng.shutdown()

    def test_retire_is_idempotent_and_shutdown_funnels_through_it(self):
        eng = self._engine(2)
        eng.router._retire_replica("r0", "dead")
        eng.router._retire_replica("r0", "heartbeat")  # second is a no-op
        assert eng.router._dead["r0"] == "dead"
        eng.shutdown()
        assert eng.router.alive_ids() == []
        assert eng.router._dead["r1"] == "shutdown"


class TestDispatchIntegration:
    def test_get_engine_returns_fleet_when_armed(self):
        from adversarial_spec_tpu.engine import dispatch

        fleet_mod.configure(enabled=True, replicas=2, transport="inproc")
        eng = dispatch.get_engine("mock://critic")
        assert isinstance(eng, FleetEngine)
        # One fleet serves every provider (that is the point).
        assert dispatch.get_engine("mock://agree") is eng
        fleet_mod.configure(enabled=False)
        from adversarial_spec_tpu.engine.mock import MockEngine

        assert isinstance(dispatch.get_engine("mock://critic"), MockEngine)

    def test_one_replica_fleet_never_routes(self):
        from adversarial_spec_tpu.engine import dispatch
        from adversarial_spec_tpu.engine.mock import MockEngine

        fleet_mod.configure(enabled=True, replicas=1)
        assert isinstance(dispatch.get_engine("mock://critic"), MockEngine)

    def test_topology_change_rebuilds_the_fleet(self):
        fleet_mod.configure(enabled=True, replicas=2, transport="inproc")
        first = fleet_mod.fleet_engine()
        fleet_mod.configure(replicas=3)
        second = fleet_mod.fleet_engine()
        assert second is not first
        assert first.router.alive_ids() == []  # old fleet shut down
        assert len(second.router.alive_ids()) == 3

    def test_validate_routes_to_a_replica(self):
        fleet_mod.configure(enabled=True, replicas=2)
        eng = fleet_mod.fleet_engine()
        assert eng.validate("mock://critic") is None
        assert eng.validate("nonsense") is not None


class TestRunRoundFleet:
    def test_round_routes_and_resolves(self):
        from adversarial_spec_tpu.debate.core import RoundConfig, run_round

        fleet_mod.configure(enabled=True, replicas=2)
        cfg = RoundConfig(debate_id="fleet-round")
        r = run_round(
            "# spec", ["mock://critic?v=1", "mock://agree"], 1, cfg
        )
        assert all(resp.ok for resp in r.responses)
        assert fleet_mod.stats.routed_requests == 2
        assert fleet_mod.stats.completed_requests == 2

    def test_rounds_of_one_debate_share_a_replica(self):
        from adversarial_spec_tpu.debate.core import RoundConfig, run_round

        fleet_mod.configure(enabled=True, replicas=3)
        cfg = RoundConfig(debate_id="fleet-affinity")
        for round_num in (1, 2):
            run_round(
                "# spec", ["mock://critic?v=1", "mock://critic?v=2"],
                round_num, cfg,
            )
        eng = fleet_mod.fleet_engine()
        used = [s for s in eng.router.replica_stats() if s["served"]]
        assert len(used) == 1
        assert sum(used[0]["served"].values()) == 4

    def test_sessionless_round_keys_on_the_spec(self):
        from adversarial_spec_tpu.debate.core import RoundConfig, run_round

        obs_mod.reset_stats()
        fleet_mod.configure(enabled=True, replicas=2)
        run_round("# spec", ["mock://critic"], 1, RoundConfig())
        routes = [
            e for e in obs_mod.recorder.events() if e["type"] == "route"
        ]
        from adversarial_spec_tpu.debate.journal import spec_sha

        assert routes[0]["key"] == spec_sha("# spec")[:16]

    def test_streaming_early_cancel_survives_the_replica_hop(self):
        from adversarial_spec_tpu.debate.core import RoundConfig, run_round
        from adversarial_spec_tpu.engine import streaming

        fleet_mod.configure(enabled=True, replicas=2)
        r = run_round(
            "# spec", ["mock://agree?agree_tail=50"], 1,
            RoundConfig(debate_id="fleet-cancel"),
        )
        assert r.responses[0].ok and r.responses[0].agreed
        # The consumer crossed the router with its indexing intact and
        # cancelled mid-reply (the in-process transport streams).
        assert streaming.stats.cancels == 1


class TestFleetEvents:
    def test_replica_and_route_events_validate(self):
        from adversarial_spec_tpu.obs.events import (
            ReplicaEvent,
            RouteEvent,
            event_to_dict,
            validate_event,
        )

        good_rep = event_to_dict(
            1, ReplicaEvent(replica="r0", op="retire", reason="dead", alive=1)
        )
        assert validate_event(json.loads(json.dumps(good_rep))) == []
        good_route = event_to_dict(
            2,
            RouteEvent(
                replica="r1", req_id=0, key="k", model="m", hop=1,
                reason="failover",
            ),
        )
        assert validate_event(json.loads(json.dumps(good_route))) == []
        assert validate_event(
            event_to_dict(3, ReplicaEvent(op="vanish"))
        )
        assert validate_event(
            event_to_dict(4, RouteEvent(reason="luck"))
        )


class TestToolsRendering:
    def _events(self):
        from adversarial_spec_tpu.obs.events import (
            ReplicaEvent,
            RouteEvent,
            SpanEvent,
            StepEvent,
            event_to_dict,
        )

        return [
            event_to_dict(1, ReplicaEvent(replica="r0", op="spawn", alive=1)),
            event_to_dict(
                2,
                RouteEvent(
                    replica="r0", req_id=0, key="debate-A", model="m",
                    trace_id="tr-001-01", span_id="tr-001-01/s00",
                ),
            ),
            event_to_dict(3, StepEvent(kind="decode", n_live=1)),
            event_to_dict(
                4,
                RouteEvent(
                    replica="r1", req_id=0, key="debate-A", model="m",
                    hop=1, reason="failover",
                    trace_id="tr-001-01", span_id="tr-001-01/s00",
                ),
            ),
            event_to_dict(
                5,
                ReplicaEvent(
                    replica="r0", op="retire", reason="dead", alive=1
                ),
            ),
            event_to_dict(6, StepEvent(kind="decode", n_live=1)),
            event_to_dict(
                7,
                SpanEvent(
                    name="request", phase="begin", req_id=0,
                    trace_id="tr-001-01", span_id="tr-001-01/s00",
                ),
            ),
            event_to_dict(
                8,
                SpanEvent(
                    name="prefill", phase="end", req_id=0, wall_s=0.25,
                    trace_id="tr-001-01", span_id="tr-001-01/s00",
                ),
            ),
            event_to_dict(
                9,
                SpanEvent(
                    name="decode", phase="end", req_id=0, wall_s=0.75,
                    trace_id="tr-001-01", span_id="tr-001-01/s00",
                ),
            ),
            event_to_dict(
                10,
                SpanEvent(
                    name="request", phase="end", req_id=0, wall_s=1.0,
                    trace_id="tr-001-01", span_id="tr-001-01/s00",
                ),
            ),
        ]

    def _write(self, tmp_path, events):
        p = tmp_path / "ev.jsonl"
        p.write_text(
            "".join(json.dumps(e) + "\n" for e in events), encoding="utf-8"
        )
        return str(p)

    def test_obs_dump_renders_replica_column_and_validates(
        self, tmp_path, capsys
    ):
        from tools.obs_dump import main

        path = self._write(tmp_path, self._events())
        assert main([path, "--timeline"]) == 0
        out = capsys.readouterr().out
        assert "route>r0" in out and "route>r1" in out
        assert "replica:retire" in out
        assert "rep=r0" in out and "rep=r1" in out  # the replica column
        assert "failover hop(s)" in out
        assert "WARNING: replica r0 retire" in out

    def test_trace_view_shows_the_failover_hop(self, tmp_path, capsys):
        from tools.trace_view import main

        path = self._write(tmp_path, self._events())
        assert main([path]) == 0
        out = capsys.readouterr().out
        assert "via r0 -> r1 (failover)" in out

    def test_bench_trend_picks_up_the_fleet_bench(self):
        from pathlib import Path

        from tools.bench_trend import validate_bench_file

        bench = Path(__file__).resolve().parent.parent / "BENCH_fleet.json"
        assert bench.is_file(), "BENCH_fleet.json must be committed"
        row, problems = validate_bench_file(bench)
        assert problems == []
        assert row["mode"] == "fleet"
        assert row["metric"] == "fleet_aggregate_speedup"


class TestFleetLifecycleLint:
    def test_exit_skipping_the_retirement_surgery_fires(self):
        """GL-LIFECYCLE's fleet machine is LIVE on the real source: a
        hand-rolled shutdown that skips _retire_replica (writing the
        dead-ledger directly) is permanently caught."""
        from pathlib import Path

        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        src = Path("adversarial_spec_tpu/fleet/router.py").read_text(
            encoding="utf-8"
        )
        broken = src.replace(
            "    def shutdown(self) -> None:\n"
            "        for rid in self.alive_ids():\n"
            "            self._retire_replica(rid, \"shutdown\")\n",
            "    def shutdown(self) -> None:\n"
            "        for rid in self.alive_ids():\n"
            "            self._dead[rid] = \"shutdown\"\n",
        )
        assert broken != src, "shutdown surgery call not found to strip"
        cfg = GraftlintConfig(package="pkg")
        findings = lint_sources(
            {"pkg/router.py": broken}, rules=["GL-LIFECYCLE"], cfg=cfg
        )
        msgs = [f.message for f in findings]
        assert any(
            "FleetRouter.shutdown never reaches" in m for m in msgs
        ), msgs
        assert any("self._dead" in m and "shutdown" in m for m in msgs)
        # The committed source is clean under the same config.
        assert (
            lint_sources(
                {"pkg/router.py": src}, rules=["GL-LIFECYCLE"], cfg=cfg
            )
            == []
        )


class TestHandoffLifecycleLint:
    def test_exit_skipping_the_publication_surgery_fires(self):
        """GL-LIFECYCLE's handoff machine is LIVE on the real source: a
        hand-rolled degrade that skips _publish_blocks (writing the
        terminal-outcome ledger directly) is permanently caught."""
        from pathlib import Path

        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        src = Path("adversarial_spec_tpu/fleet/handoff.py").read_text(
            encoding="utf-8"
        )
        broken = src.replace(
            "        return self._publish_blocks(key, DEGRADED, reason)\n",
            "        self._outcomes[key] = DEGRADED\n"
            "        return None\n",
        )
        assert broken != src, "_degrade surgery call not found to strip"
        cfg = GraftlintConfig(package="pkg")
        findings = lint_sources(
            {"pkg/handoff.py": broken}, rules=["GL-LIFECYCLE"], cfg=cfg
        )
        msgs = [f.message for f in findings]
        assert any(
            "HandoffLedger._degrade never reaches" in m for m in msgs
        ), msgs
        assert any("self._outcomes" in m and "_degrade" in m for m in msgs)
        # The committed source is clean under the same config.
        assert (
            lint_sources(
                {"pkg/handoff.py": src}, rules=["GL-LIFECYCLE"], cfg=cfg
            )
            == []
        )


class TestCliFleet:
    def _run(self, argv, monkeypatch, capsys, stdin="# spec\nBody.\n"):
        import io

        from adversarial_spec_tpu import cli

        monkeypatch.setattr("sys.stdin", io.StringIO(stdin))
        code = cli.main(argv)
        out, err = capsys.readouterr()
        return code, out, err

    def test_fleet_flags_reach_perf_json(self, monkeypatch, capsys):
        code, out, err = self._run(
            [
                "critique", "--models", "mock://critic,mock://agree",
                "--fleet", "--fleet-replicas", "3", "--json",
            ],
            monkeypatch, capsys,
        )
        assert code == 0
        perf = json.loads(out)["perf"]["fleet"]
        assert perf["enabled"] is True
        assert perf["replicas"] == 3
        assert perf["routed_requests"] == 2
        assert perf["completed_requests"] == 2
        assert "fleet: 2 request(s) routed" in err

    def test_fleet_does_not_leak_across_invocations(self, monkeypatch, capsys):
        self._run(
            [
                "critique", "--models", "mock://critic",
                "--fleet", "--fleet-replicas", "2", "--json",
            ],
            monkeypatch, capsys,
        )
        code, out, _ = self._run(
            ["critique", "--models", "mock://critic", "--json"],
            monkeypatch, capsys,
        )
        assert code == 0
        perf = json.loads(out)["perf"]["fleet"]
        assert perf["enabled"] is False  # env default (off) re-resolved
        assert perf["routed_requests"] == 0


@pytest.mark.chaos
class TestWorkerTransport:
    """One worker subprocess, full protocol round-trip."""

    def test_worker_protocol(self, tmp_path):
        rep = WorkerReplica(
            "rw0", request_timeout_s=60.0, log_dir=str(tmp_path)
        )
        try:
            assert rep.ping()
            assert rep.validate("mock://critic") is None
            got = []
            comps = rep.chat_batch(
                [_req(), _req(model="mock://agree")],
                PARAMS,
                on_completion=lambda j, c: got.append(j),
            )
            assert [c.ok for c in comps] == [True, True]
            assert got == [0, 1]  # completions streamed incrementally
            stats = rep.stats()
            assert stats["served"] == {
                "mock://critic": 1, "mock://agree": 1,
            }
            rep.check()  # allocator/tier invariants inside the worker
        finally:
            rep.close()
        assert not rep.ping()


@pytest.mark.chaos
class TestReplicaKillChaos:
    """The tier-1 fleet chaos smoke: the FULL drill from
    tools/chaos_run.py --replica-kill — two worker replicas sharing one
    KV store, the serving replica SIGKILLed after its 2nd completion,
    round completed on the survivor with byte-identical transcripts,
    zero duplicated opponent attempts, store rehydration, and clean
    survivor invariants."""

    def test_replica_kill_recovery_contract(self):
        from tools.chaos_run import run_replica_kill

        failures, payload = run_replica_kill(verbose=False)
        assert failures == []
        assert payload["transcripts_byte_identical"] is True
        assert payload["duplicated_completions"] == 0
        assert payload["reissued_requests"] == 2
        assert payload["survivor_rehydrated_blocks"] > 0
        assert payload["recovered_fraction"] == 0.5


class TestHashRingRoles:
    """Role-tagged ring pins (fleet disaggregation, docs/fleet.md)."""

    def test_role_filter_skips_foreign_roles(self):
        ring = HashRing()
        ring.add("p0", role="prefill")
        ring.add("d0", role="decode")
        ring.add("d1", role="decode")
        for key in (f"debate-{i}" for i in range(32)):
            assert ring.preference(key, role="prefill") == ["p0"]
            dec = ring.preference(key, role="decode")
            assert sorted(dec) == ["d0", "d1"]
            assert ring.primary(key, role="decode") == dec[0]
        assert ring.role_of("p0") == "prefill"
        assert ring.role_nodes("decode") == {"d0", "d1"}

    def test_untagged_nodes_serve_every_role(self):
        ring = HashRing(["r0", "r1"])  # symmetric fleet: no tags
        assert ring.role_nodes("prefill") == {"r0", "r1"}
        assert ring.role_nodes("decode") == {"r0", "r1"}
        for key in ("a", "b", "c"):
            assert ring.primary(key, role="decode") == ring.primary(key)

    def test_empty_role_pool_routes_nowhere(self):
        ring = HashRing()
        ring.add("d0", role="decode")
        assert ring.preference("k", role="prefill") == []
        assert ring.primary("k", role="prefill") is None

    def test_role_pool_membership_change_scoped_to_the_pool(self):
        """The per-pool consistent-hashing contract: a node joining
        the decode pool moves ~1/N of DECODE keys (all to the
        newcomer) and zero prefill keys; the foreign pool never even
        observes the change."""
        ring = HashRing()
        ring.add("p0", role="prefill")
        ring.add("p1", role="prefill")
        for k in range(3):
            ring.add(f"d{k}", role="decode")
        keys = [f"debate-{i}" for i in range(2000)]
        dec_before = {k: ring.primary(k, role="decode") for k in keys}
        pre_before = {k: ring.primary(k, role="prefill") for k in keys}
        ring.add("d3", role="decode")
        moved = [
            k for k in keys if ring.primary(k, role="decode") != dec_before[k]
        ]
        frac = len(moved) / len(keys)
        assert 0.5 / 4 <= frac <= 2.0 / 4, frac
        assert all(
            ring.primary(k, role="decode") == "d3" for k in moved
        )
        assert all(
            ring.primary(k, role="prefill") == pre_before[k] for k in keys
        )


class TestHandoffLedger:
    """The handoff lifecycle machine in isolation (fleet/handoff.py)."""

    def _ledger(self):
        from adversarial_spec_tpu.fleet.handoff import HandoffLedger

        fleet_mod.reset_stats()
        return HandoffLedger(stats=fleet_mod.stats)

    def test_adopt_walks_the_full_lifecycle(self):
        from adversarial_spec_tpu.fleet import handoff as h

        led = self._ledger()
        rec = led.begin("debate-A", "r0", "r1")
        assert rec.state == h.PLANNED
        assert led.seen("debate-A") and not led.seen("debate-B")
        led.note_prefilling("debate-A")
        assert led.active("debate-A").state == h.PREFILLING
        led.note_published("debate-A", ["c1", "c2"], blocks=2)
        assert led.active("debate-A").state == h.PUBLISHED
        out = led._finish_adopt("debate-A")
        assert out is not None and out.state == h.ADOPTED
        assert led.active("debate-A") is None
        assert led.outcome("debate-A") == h.ADOPTED
        assert led.seen("debate-A")  # decided keys never re-handoff
        assert fleet_mod.stats.handoff_attempts == 1
        assert fleet_mod.stats.handoff_adopted == 1
        assert fleet_mod.stats.handoff_shipped_blocks == 2

    def test_surgery_is_idempotent_first_decision_stands(self):
        led = self._ledger()
        led.begin("k", "r0", "r1")
        assert led._degrade("k", "store_miss") is not None
        # A second exit for the same key is a no-op: no double count.
        assert led._finish_adopt("k") is None
        assert led._degrade("k", "again") is None
        assert led.outcome("k") == "degraded"
        assert fleet_mod.stats.handoff_degraded == 1
        assert fleet_mod.stats.handoff_adopted == 0

    def test_abandon_counts_separately(self):
        led = self._ledger()
        led.begin("k", "r0", "r1")
        led._abandon("k", "no_blocks")
        assert led.outcome("k") == "abandoned"
        assert fleet_mod.stats.handoff_abandoned == 1
        assert led.snapshot() == {
            "active": 0, "adopted": 0, "degraded": 0, "abandoned": 1,
        }


class TestDisaggRouting:
    """Prefill/decode disaggregation on in-process replicas: the
    adopted fast path, every degradation, and the byte-identity
    contract against a symmetric fleet."""

    DOC = (
        "## Goals\nShip the spec.\n## Constraints\n"
        + "The decode replica SHALL NOT re-prefill shipped blocks. " * 40
    )

    def _arm_tier(self, tmp_path):
        from adversarial_spec_tpu.engine import kvtier

        kvtier.configure(
            enabled=True, host_mb=64, store_dir=str(tmp_path / "store")
        )

    def _reqs(self, n=2, key="debate-dis", doc=None):
        doc = self.DOC if doc is None else doc
        return [
            _req(
                model=f"mock://critic?v={k}",
                key=key,
                user=doc + f"\nOpponent {k}.",
            )
            for k in range(n)
        ]

    def _texts(self, engine, reqs):
        params = SamplingParams(max_new_tokens=32, greedy=True)
        outs = engine.chat(reqs, params)
        assert all(o.ok for o in outs), [o.error for o in outs]
        return [o.text for o in outs]

    def test_adopted_handoff_is_byte_identical(self, tmp_path):
        self._arm_tier(tmp_path)
        fleet_mod.reset_stats()
        sym = FleetEngine(replicas=2, transport="inproc")
        ref = self._texts(sym, self._reqs())
        sym.shutdown()
        fleet_mod.reset_stats()
        eng = FleetEngine(replicas=2, transport="inproc", prefill_replicas=1)
        try:
            assert eng.disagg_armed()
            assert eng.router.alive_ids("prefill") == ["r0"]
            assert eng.router.alive_ids("decode") == ["r1"]
            got = self._texts(eng, self._reqs())
            assert got == ref  # byte-identical across topologies
            assert fleet_mod.stats.handoff_attempts == 1
            assert fleet_mod.stats.handoff_adopted == 1
            assert fleet_mod.stats.handoff_shipped_blocks > 0
            assert eng.handoff.outcome("debate-dis") == "adopted"
        finally:
            eng.shutdown()

    def test_small_admissions_never_handoff(self, tmp_path):
        self._arm_tier(tmp_path)
        fleet_mod.reset_stats()
        eng = FleetEngine(replicas=2, transport="inproc", prefill_replicas=1)
        try:
            self._texts(eng, self._reqs(doc="Tiny spec."))
            assert fleet_mod.stats.handoff_attempts == 0
        finally:
            eng.shutdown()

    def test_later_rounds_ride_the_first_handoff(self, tmp_path):
        self._arm_tier(tmp_path)
        fleet_mod.reset_stats()
        eng = FleetEngine(replicas=2, transport="inproc", prefill_replicas=1)
        try:
            self._texts(eng, self._reqs())
            self._texts(eng, self._reqs())  # round 2, same debate key
            assert fleet_mod.stats.handoff_attempts == 1  # no re-handoff
        finally:
            eng.shutdown()

    def test_prefill_error_degrades_byte_identical(
        self, tmp_path, monkeypatch
    ):
        self._arm_tier(tmp_path)
        fleet_mod.reset_stats()
        sym = FleetEngine(replicas=2, transport="inproc")
        ref = self._texts(sym, self._reqs())
        sym.shutdown()
        fleet_mod.reset_stats()
        eng = FleetEngine(replicas=2, transport="inproc", prefill_replicas=1)
        try:
            def boom(requests, params):
                raise RuntimeError("prefill replica exploded")

            monkeypatch.setattr(eng.router.replica("r0"), "prefill", boom)
            got = self._texts(eng, self._reqs())
            assert got == ref  # local prefill on the decode side
            assert fleet_mod.stats.handoff_degraded == 1
            assert fleet_mod.stats.handoff_adopted == 0
        finally:
            eng.shutdown()

    def test_no_store_abandons_but_still_serves(self, tmp_path):
        from adversarial_spec_tpu.engine import kvtier

        # Tier 2 unset: the prefill side has nowhere durable to ship.
        kvtier.configure(enabled=True, host_mb=64, store_dir="")
        fleet_mod.reset_stats()
        eng = FleetEngine(replicas=2, transport="inproc", prefill_replicas=1)
        try:
            self._texts(eng, self._reqs())
            assert fleet_mod.stats.handoff_attempts == 1
            assert fleet_mod.stats.handoff_abandoned == 1
        finally:
            eng.shutdown()

    def test_symmetric_fleet_never_plans_handoffs(self, tmp_path):
        self._arm_tier(tmp_path)
        fleet_mod.reset_stats()
        eng = FleetEngine(replicas=2, transport="inproc")
        try:
            assert not eng.disagg_armed()
            self._texts(eng, self._reqs())
            assert fleet_mod.stats.handoff_attempts == 0
        finally:
            eng.shutdown()


@pytest.mark.chaos
class TestHandoffKillChaos:
    """The tier-1 disagg chaos smoke: the FULL drill from
    tools/chaos_run.py --handoff-kill — a 1 prefill + 1 decode worker
    fleet, the prefill replica SIGKILLed (a) after its publications
    are durable (handoff must still adopt) and (b) mid-publication
    (handoff must degrade to local prefill), byte-identical
    transcripts and zero duplicated completions throughout."""

    def test_handoff_kill_contract(self):
        from tools.chaos_run import run_handoff_kill

        failures, payload = run_handoff_kill(verbose=False)
        assert failures == []
        assert payload["adopted_after_kill"] is True
        assert payload["degraded_on_partial"] is True
        assert payload["transcripts_byte_identical"] is True
        assert payload["duplicated_completions"] == 0
        assert payload["decode_rehydrated_blocks"] > 0
        assert payload["invariants_clean"] is True


@pytest.mark.chaos
class TestWorkerDisaggProtocol:
    """Worker-transport round-trip of the disagg ops: role rides the
    spawn, prefill publishes durable chains to the shared store, and a
    second worker's prefetch finds every one of them."""

    def test_prefill_publishes_and_peer_prefetch_finds(self, tmp_path):
        import os

        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            ADVSPEC_KV_TIER="1",
            ADVSPEC_KV_HOST_MB="64",
            ADVSPEC_KV_STORE_DIR=str(tmp_path / "store"),
        )
        doc = "The prefill worker SHALL publish durable blocks. " * 40
        pre = WorkerReplica(
            "wp0", request_timeout_s=60.0, env=env,
            log_dir=str(tmp_path), role="prefill",
        )
        dec = WorkerReplica(
            "wd0", request_timeout_s=60.0, env=env,
            log_dir=str(tmp_path), role="decode",
        )
        try:
            assert pre.role == "prefill" and dec.role == "decode"
            outs = pre.prefill(
                [_req(user=doc), _req(model="mock://agree", user=doc)],
                PARAMS,
            )
            assert len(outs) == 2
            chains = sorted(
                {c for o in outs for c in o.get("chains", ())}
            )
            assert chains, outs  # something page-aligned shipped
            assert all(o.get("blocks", 0) > 0 for o in outs)
            # The peer worker sees every published chain in the store.
            assert dec.prefetch("mock://critic", chains) == len(chains)
            assert dec.prefetch("mock://critic", ["bogus-chain"]) == 0
        finally:
            pre.close()
            dec.close()


class TestDisaggBenchPin:
    def test_bench_trend_picks_up_the_disagg_bench(self):
        from pathlib import Path

        from tools.bench_trend import validate_bench_file

        bench = Path(__file__).resolve().parent.parent / "BENCH_disagg.json"
        assert bench.is_file(), "BENCH_disagg.json must be committed"
        row, problems = validate_bench_file(bench)
        assert problems == []
        assert row["mode"] == "disagg"
        assert row["metric"] == "disagg_decode_ttft_p99_speedup"
        payload = json.loads(bench.read_text(encoding="utf-8"))
        assert payload["transcripts_byte_identical"]["disagg"] is True
        assert payload["duplicated_completions"] == 0
        assert payload["unexpected_recompiles"] == 0
        assert payload["handoff_hit_fraction"] > 0
