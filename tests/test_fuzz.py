"""Fuzz tests: the tag parsers and message splitter consume ADVERSARIAL
model output by definition — no input may crash them, and the splitter's
invariants must hold for arbitrary text. The scheduler gets the same
treatment via the chaos injector: random faults mid-drain must never lose
a request."""

import random
import string

import pytest

from adversarial_spec_tpu.debate.parsing import (
    detect_agreement,
    extract_spec,
    extract_tasks,
    get_critique_summary,
    has_malformed_spec,
)
from adversarial_spec_tpu.debate.telegram import split_message


@pytest.fixture(autouse=True)
def _spec_off_module(monkeypatch):
    """Speculation is default-on and only multiplies the jit programs
    every batcher/engine this module compiles; its subject is
    orthogonal. Spec-on coverage (incl. SpecEvents, spec chaos fuzz,
    and the obs families) lives in tests/test_spec_batcher.py."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)


_ALPHABET = (
    string.ascii_letters
    + string.digits
    + " \n\t:[]/\\{}()<>|#*-_.,;\"'"
)
_FRAGMENTS = [
    "[AGREE]",
    "[SPEC]",
    "[/SPEC]",
    "[TASK]",
    "[/TASK]",
    "title:",
    "priority:",
    "dependencies:",
    "estimate:",
    "\n\n",
    "✓✗…",
]


def _random_soup(rng: random.Random, n: int) -> str:
    parts = []
    for _ in range(n):
        if rng.random() < 0.3:
            parts.append(rng.choice(_FRAGMENTS))
        else:
            parts.append(
                "".join(rng.choice(_ALPHABET) for _ in range(rng.randrange(1, 30)))
            )
    return "".join(parts)


class TestParserFuzz:
    def test_parsers_never_crash(self):
        rng = random.Random(0)
        for i in range(300):
            soup = _random_soup(rng, rng.randrange(0, 40))
            detect_agreement(soup)
            spec = extract_spec(soup)
            assert spec is None or isinstance(spec, str)
            has_malformed_spec(soup)
            for task in extract_tasks(soup):
                d = task.to_dict()
                assert d["priority"] in {"critical", "high", "medium", "low"}
            summary = get_critique_summary(soup)
            assert len(summary) <= 200

    def test_extract_spec_inverse_property(self):
        """Any payload wrapped in clean tags round-trips (after strip)."""
        rng = random.Random(1)
        for _ in range(100):
            payload = _random_soup(rng, rng.randrange(0, 10))
            # Avoid payloads that smuggle a closing tag at the very end
            # changing the widest-span semantics deliberately kept.
            wrapped = f"prefix [SPEC]{payload}[/SPEC]"
            got = extract_spec(wrapped)
            if "[/SPEC]" not in payload:
                assert got == payload.strip()


class TestSplitterFuzz:
    def test_invariants_hold_for_arbitrary_text(self):
        rng = random.Random(2)
        for _ in range(100):
            text = _random_soup(rng, rng.randrange(0, 60))
            limit = rng.choice([50, 100, 4096])
            chunks = split_message(text, limit=limit)
            # Every chunk within the limit.
            assert all(len(c) <= limit for c in chunks)
            # No content invented: concatenation loses only the boundary
            # whitespace the splitter strips.
            joined = "".join(chunks)
            assert len(joined) <= len(text)
            assert joined.replace("\n", "").replace(" ", "") == text.replace(
                "\n", ""
            ).replace(" ", "")
            # Empty input → no chunks; non-empty → at least one.
            assert (chunks == []) == (text == "")


@pytest.mark.chaos
class TestSchedulerChaosFuzz:
    """Random faults injected mid-drain (resilience/injector.py): the
    scheduler's isolation invariant is that NO request is ever lost —
    every submitted req_id gets exactly one SchedResult (clean, partial
    + fault metadata, or retried to completion) and every evicted slot's
    pages return to the pool."""

    def test_no_request_lost_under_random_faults(self):
        import jax
        import jax.numpy as jnp

        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )
        from adversarial_spec_tpu.models import transformer as T
        from adversarial_spec_tpu.models.config import get_config
        from adversarial_spec_tpu.resilience import injector as injector_mod
        from adversarial_spec_tpu.resilience.faults import FaultKind
        from adversarial_spec_tpu.resilience.injector import (
            FaultInjector,
            FaultRule,
        )

        import os

        from adversarial_spec_tpu import obs

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        kinds = list(FaultKind)
        seams = ["scheduler_chunk", "kv_alloc"]
        # Fixed seeds keep tier-1 deterministic; tools/chaos_run.py
        # --sweep widens coverage by appending extra seeds via env.
        seeds = [0, 1, 2]
        extra = os.environ.get("ADVSPEC_CHAOS_FUZZ_SEED")
        if extra is not None:
            seeds = [int(extra)]
        # Tiny ring (way below the event volume of one drain): the fuzz
        # additionally pins that chaos can never grow the flight
        # recorder past its bound — only age events out of it.
        ring_size = 32
        obs.configure(enabled=True, recorder_size=ring_size)
        for seed in seeds:
            rng = random.Random(seed)
            rules = [
                FaultRule(
                    kind=rng.choice(kinds),
                    seam=rng.choice(seams),
                    p=0.3,
                    slot=rng.choice([None, 0, 1]),
                )
                for _ in range(rng.randrange(1, 3))
            ]
            injector_mod.install(FaultInjector(rules, seed=seed))
            b = ContinuousBatcher(
                params, cfg, max_batch=2, max_new_cap=16, chunk=4
            )
            total_pages = b.allocator.free_pages
            n_req = rng.randrange(3, 6)
            for i in range(n_req):
                b.submit(
                    SchedRequest(
                        req_id=i,
                        prompt_ids=[1 + (i * 7) % 64, 5, 9][: 1 + i % 3],
                        max_new_tokens=4 + (i * 3) % 12,
                    )
                )
            results = b.run_all()
            injector_mod.reset()
            # Ring-buffer invariant: bounds are NEVER exceeded; every
            # append past capacity aged one event out (dropped count),
            # and the buffered+dropped total is exactly what was ever
            # recorded.
            assert len(obs.recorder) <= ring_size, f"seed {seed}"
            assert (
                len(obs.recorder) + obs.recorder.dropped
                == obs.recorder.seq
            ), f"seed {seed}"
            # The invariant: every req_id resolved exactly once.
            assert sorted(r.req_id for r in results) == list(range(n_req)), (
                f"seed {seed}: lost/duplicated requests "
                f"{[r.req_id for r in results]} with rules {rules}"
            )
            for r in results:
                # error and fault_kind travel together; partial output
                # never exceeds the request budget.
                assert (r.error is None) == (r.fault_kind is None)
                assert 0 <= r.n_generated <= 16
                assert len(r.tokens) == r.n_generated
            # Eviction always returns pages (no leak, no double-free).
            assert b.allocator.free_pages == total_pages, f"seed {seed}"
