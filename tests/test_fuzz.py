"""Fuzz tests: the tag parsers and message splitter consume ADVERSARIAL
model output by definition — no input may crash them, and the splitter's
invariants must hold for arbitrary text."""

import random
import string

from adversarial_spec_tpu.debate.parsing import (
    detect_agreement,
    extract_spec,
    extract_tasks,
    get_critique_summary,
    has_malformed_spec,
)
from adversarial_spec_tpu.debate.telegram import split_message

_ALPHABET = (
    string.ascii_letters
    + string.digits
    + " \n\t:[]/\\{}()<>|#*-_.,;\"'"
)
_FRAGMENTS = [
    "[AGREE]",
    "[SPEC]",
    "[/SPEC]",
    "[TASK]",
    "[/TASK]",
    "title:",
    "priority:",
    "dependencies:",
    "estimate:",
    "\n\n",
    "✓✗…",
]


def _random_soup(rng: random.Random, n: int) -> str:
    parts = []
    for _ in range(n):
        if rng.random() < 0.3:
            parts.append(rng.choice(_FRAGMENTS))
        else:
            parts.append(
                "".join(rng.choice(_ALPHABET) for _ in range(rng.randrange(1, 30)))
            )
    return "".join(parts)


class TestParserFuzz:
    def test_parsers_never_crash(self):
        rng = random.Random(0)
        for i in range(300):
            soup = _random_soup(rng, rng.randrange(0, 40))
            detect_agreement(soup)
            spec = extract_spec(soup)
            assert spec is None or isinstance(spec, str)
            has_malformed_spec(soup)
            for task in extract_tasks(soup):
                d = task.to_dict()
                assert d["priority"] in {"critical", "high", "medium", "low"}
            summary = get_critique_summary(soup)
            assert len(summary) <= 200

    def test_extract_spec_inverse_property(self):
        """Any payload wrapped in clean tags round-trips (after strip)."""
        rng = random.Random(1)
        for _ in range(100):
            payload = _random_soup(rng, rng.randrange(0, 10))
            # Avoid payloads that smuggle a closing tag at the very end
            # changing the widest-span semantics deliberately kept.
            wrapped = f"prefix [SPEC]{payload}[/SPEC]"
            got = extract_spec(wrapped)
            if "[/SPEC]" not in payload:
                assert got == payload.strip()


class TestSplitterFuzz:
    def test_invariants_hold_for_arbitrary_text(self):
        rng = random.Random(2)
        for _ in range(100):
            text = _random_soup(rng, rng.randrange(0, 60))
            limit = rng.choice([50, 100, 4096])
            chunks = split_message(text, limit=limit)
            # Every chunk within the limit.
            assert all(len(c) <= limit for c in chunks)
            # No content invented: concatenation loses only the boundary
            # whitespace the splitter strips.
            joined = "".join(chunks)
            assert len(joined) <= len(text)
            assert joined.replace("\n", "").replace(" ", "") == text.replace(
                "\n", ""
            ).replace(" ", "")
            # Empty input → no chunks; non-empty → at least one.
            assert (chunks == []) == (text == "")
