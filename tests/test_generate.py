"""Generation-loop and sampling tests (CPU, tiny synthetic models)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine.generate import (
    GenerateResult,
    bucket_length,
    generate,
    pad_batch,
)
from adversarial_spec_tpu.engine.sampling import sample_tokens
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    return params, cfg


class TestBucketing:
    def test_bucket_length_powers_of_two(self):
        assert bucket_length(1) == 128
        assert bucket_length(128) == 128
        assert bucket_length(129) == 256
        assert bucket_length(1000) == 1024

    def test_pad_batch_left_pads(self):
        tokens, pad_lens = pad_batch([[1, 2, 3], [7]], pad_id=0)
        assert tokens.shape == (2, 128)
        assert list(tokens[0, -3:]) == [1, 2, 3]
        assert tokens[1, -1] == 7
        assert pad_lens[0] == 125 and pad_lens[1] == 127
        assert (tokens[0, :125] == 0).all()

    def test_pad_batch_explicit_bucket_too_small(self):
        with pytest.raises(ValueError, match="bucket"):
            pad_batch([[1] * 10], pad_id=0, bucket=8)


class TestSampling:
    def _logits(self):
        return jnp.array([[0.1, 3.0, -1.0, 0.5]], jnp.float32)

    def test_greedy_argmax(self):
        out = sample_tokens(
            self._logits(),
            jax.random.key(0),
            greedy=True,
            top_k=0,
            temperature=jnp.float32(1.0),
            top_p=jnp.float32(1.0),
        )
        assert out.tolist() == [1]

    def test_temperature_zero_is_argmax(self):
        out = sample_tokens(
            self._logits(),
            jax.random.key(0),
            greedy=False,
            top_k=0,
            temperature=jnp.float32(0.0),
            top_p=jnp.float32(1.0),
        )
        assert out.tolist() == [1]

    def test_top_k_one_is_argmax(self):
        out = sample_tokens(
            self._logits(),
            jax.random.key(3),
            greedy=False,
            top_k=1,
            temperature=jnp.float32(5.0),
            top_p=jnp.float32(1.0),
        )
        assert out.tolist() == [1]

    def test_tiny_top_p_is_argmax(self):
        for seed in range(5):
            out = sample_tokens(
                self._logits(),
                jax.random.key(seed),
                greedy=False,
                top_k=0,
                temperature=jnp.float32(2.0),
                top_p=jnp.float32(1e-6),
            )
            assert out.tolist() == [1]

    def test_sampling_respects_top_k_support(self):
        logits = jnp.array([[0.0, 1.0, 2.0, 3.0]], jnp.float32)
        for seed in range(10):
            out = sample_tokens(
                logits,
                jax.random.key(seed),
                greedy=False,
                top_k=2,
                temperature=jnp.float32(3.0),
                top_p=jnp.float32(1.0),
            )
            assert out.tolist()[0] in (2, 3)


class TestGenerate:
    def test_greedy_deterministic(self, tiny_model):
        params, cfg = tiny_model
        prompts = [[1, 5, 9], [2, 6]]
        a = generate(
            params, cfg, prompts, max_new_tokens=8, eos_ids=[2], greedy=True
        )
        b = generate(
            params, cfg, prompts, max_new_tokens=8, eos_ids=[2], greedy=True
        )
        assert isinstance(a, GenerateResult)
        np.testing.assert_array_equal(a.tokens, b.tokens)
        assert a.decode_tokens == b.decode_tokens

    def test_max_new_tokens_respected(self, tiny_model):
        params, cfg = tiny_model
        out = generate(
            params,
            cfg,
            [[1, 2, 3]],
            max_new_tokens=5,
            eos_ids=[],  # random model may never emit a chosen eos
            greedy=True,
        )
        assert out.tokens.shape[1] == 5
        assert out.n_generated[0] <= 5
        assert out.decode_tokens == out.n_generated.sum()

    def test_seeded_sampling_reproducible(self, tiny_model):
        params, cfg = tiny_model
        kw = dict(
            max_new_tokens=6,
            eos_ids=[],
            temperature=1.0,
            seed=42,
        )
        a = generate(params, cfg, [[3, 1, 4]], **kw)
        b = generate(params, cfg, [[3, 1, 4]], **kw)
        np.testing.assert_array_equal(a.tokens, b.tokens)

    def test_different_seeds_differ(self, tiny_model):
        params, cfg = tiny_model
        kw = dict(max_new_tokens=16, eos_ids=[], temperature=5.0)
        a = generate(params, cfg, [[3, 1, 4]], seed=1, **kw)
        b = generate(params, cfg, [[3, 1, 4]], seed=2, **kw)
        assert not np.array_equal(a.tokens, b.tokens)

    def test_eos_stops_row(self, tiny_model):
        """Greedy decode of a random model is periodic-ish; use its own
        first token as EOS so the second emission of it stops the row."""
        params, cfg = tiny_model
        probe = generate(
            params, cfg, [[1, 2]], max_new_tokens=4, eos_ids=[], greedy=True
        )
        eos = int(probe.tokens[0, 0])
        out = generate(
            params,
            cfg,
            [[1, 2]],
            max_new_tokens=32,
            eos_ids=[eos],
            greedy=True,
        )
        n = int(out.n_generated[0])
        assert n <= 32
        assert int(out.tokens[0, n - 1]) == eos
        # Nothing generated past the EOS slot.
        assert (out.tokens[0, n:] == 0).all()

    def test_cached_decode_matches_full_recompute(self, tiny_model):
        """Greedy tokens from the KV-cached decode loop must equal tokens
        from re-running the full forward at every step (regression: decode
        KV writes were off by one slot, shifting RoPE positions and
        attending over a zero key at slot S)."""
        params, cfg = tiny_model
        prompt = [1, 5, 9, 3, 7]
        n_new = 6
        out = generate(
            params, cfg, [prompt], max_new_tokens=n_new, eos_ids=[], greedy=True
        )

        seq = list(prompt)
        for _ in range(n_new):
            ids = jnp.asarray([seq], jnp.int32)
            S = len(seq)
            cache = T.init_cache(cfg, 1, S, dtype=jnp.float32)
            positions = jnp.arange(S, dtype=jnp.int32)[None, :]
            kv_valid = jnp.ones((1, S), bool)
            logits, _ = T.forward(
                params, cfg, ids, positions, cache, jnp.int32(0), kv_valid
            )
            seq.append(int(jnp.argmax(logits[0, -1])))
        expected = seq[len(prompt):]
        assert out.tokens[0, :n_new].tolist() == expected

    def test_chunked_prefill_matches_single_chunk(self, tiny_model, monkeypatch):
        """A prompt spanning multiple prefill chunks must produce the same
        greedy tokens as one-shot prefill (chunk boundary correctness)."""
        from adversarial_spec_tpu.engine import generate as gen_mod

        params, cfg = tiny_model
        prompt = [((i * 7) % 500) + 3 for i in range(300)]  # bucket 512
        kw = dict(max_new_tokens=6, eos_ids=[], greedy=True)

        monkeypatch.setattr(gen_mod, "PREFILL_CHUNK", 128)  # 4 chunks
        chunked = generate(params, cfg, [prompt], **kw)
        monkeypatch.setattr(gen_mod, "PREFILL_CHUNK", 4096)  # 1 chunk
        oneshot = generate(params, cfg, [prompt], **kw)
        np.testing.assert_array_equal(chunked.tokens, oneshot.tokens)

    def test_shared_prefix_matches_unshared_greedy(self, tiny_model):
        """Identical opponent prompts: prefill-once-and-tile must produce
        the same greedy tokens as independent per-row prefill."""
        params, cfg = tiny_model
        prompts = [[1, 5, 9, 3, 7]] * 3
        kw = dict(max_new_tokens=6, eos_ids=[], greedy=True)
        shared = generate(params, cfg, prompts, share_prefix=True, **kw)
        unshared = generate(params, cfg, prompts, share_prefix=False, **kw)
        np.testing.assert_array_equal(shared.tokens, unshared.tokens)
        # All rows identical under greedy (same prompt, same argmax).
        assert (shared.tokens[0] == shared.tokens[1]).all()

    def test_shared_prefix_fires_on_single_device_mesh(self, tiny_model):
        """The production path (TpuEngine always passes a mesh; one real
        chip → mesh.size == 1) must still take the shared-prefix route
        and produce correct greedy tokens."""
        import jax
        from adversarial_spec_tpu.parallel.mesh import make_mesh

        params, cfg = tiny_model
        mesh = make_mesh({}, devices=jax.devices()[:1])
        assert mesh.size == 1
        prompts = [[1, 5, 9, 3, 7]] * 3
        kw = dict(max_new_tokens=6, eos_ids=[], greedy=True)
        ref = generate(params, cfg, prompts, share_prefix=False, **kw)
        with mesh:
            out = generate(params, cfg, prompts, mesh=mesh, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)

    def test_shared_prefix_rows_diverge_when_sampling(self, tiny_model):
        """With temperature, tiled rows must sample independently."""
        params, cfg = tiny_model
        prompts = [[1, 5, 9, 3, 7]] * 4
        out = generate(
            params,
            cfg,
            prompts,
            max_new_tokens=16,
            eos_ids=[],
            temperature=5.0,
            seed=7,
        )
        rows = {tuple(r) for r in out.tokens.tolist()}
        assert len(rows) > 1

    @pytest.mark.parametrize("family", ["llama", "gemma2"])
    def test_paged_decode_matches_dense(self, family):
        """Paged-pool decode (gather reference path) must reproduce the
        dense cache's greedy tokens exactly — including alternating
        sliding-window layers (gemma2) whose per-layer bounds tighten."""
        from dataclasses import replace

        cfg = get_config(family, "tiny")
        if cfg.sliding_window > 0:
            cfg = replace(cfg, sliding_window=8)
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3] * 3, [2, 6, 4]]
        kw = dict(max_new_tokens=10, eos_ids=[], greedy=True)
        dense = generate(params, cfg, prompts, paged=False, **kw)
        paged = generate(params, cfg, prompts, paged=True, page_size=16, **kw)
        np.testing.assert_array_equal(dense.tokens, paged.tokens)

    def test_paged_kernel_in_loop_matches_gather(self, tiny_model):
        """Force the paged Pallas kernel (interpret mode on CPU) inside
        the decode loop — must match the gather reference path."""
        params, cfg = tiny_model
        prompts = [[1, 5, 9, 3], [2, 6]]
        kw = dict(
            max_new_tokens=4, eos_ids=[], greedy=True, paged=True,
            page_size=16,
        )
        gather = generate(params, cfg, prompts, use_pallas_decode=False, **kw)
        kernel = generate(params, cfg, prompts, use_pallas_decode=True, **kw)
        np.testing.assert_array_equal(gather.tokens, kernel.tokens)

    def test_paged_early_eos_row_does_not_corrupt_others(self, tiny_model):
        """Regression: inactive rows' KV writes redirect to the reserved
        trash page. Before the +1 table shift, physical page 0 belonged to
        row 0's prompt and an early-EOS row would scribble over it — the
        surviving row's tokens must match dense decode exactly."""
        params, cfg = tiny_model
        # Row 0's prompt fills the whole 128 bucket (pad_len = 0), so its
        # REAL slot 0 lives in physical page 0 under the unshifted layout;
        # row 1 dies after its first token (its greedy first token is the
        # EOS) and its trash-page writes land exactly there. Row 0 keeps
        # decoding and must stay uncorrupted.
        probe = generate(
            params, cfg, [[1, 2]], max_new_tokens=2, eos_ids=[], greedy=True
        )
        eos = int(probe.tokens[0, 0])
        long_prompt = [((i * 11) % 500) + 3 for i in range(128)]
        prompts = [long_prompt, [1, 2]]
        kw = dict(max_new_tokens=24, eos_ids=[eos], greedy=True)
        dense = generate(params, cfg, prompts, paged=False, **kw)
        paged = generate(params, cfg, prompts, paged=True, page_size=16, **kw)
        np.testing.assert_array_equal(dense.tokens, paged.tokens)
        np.testing.assert_array_equal(dense.n_generated, paged.n_generated)

    def test_paged_shared_prompt_pages(self, tiny_model, monkeypatch):
        """Identical opponent prompts share ONE physical copy of the
        prompt pages (pool sized prompt+B*decode, not B*total), and the
        outputs still match the dense unshared reference."""
        from adversarial_spec_tpu.engine import kvcache as kv_mod

        pool_sizes = []
        real_init = kv_mod.init_page_pool

        def spy(layout, **kw):
            pool_sizes.append(layout.n_pages)
            return real_init(layout, **kw)

        # generate() imports init_page_pool inside the function, so patch
        # the source module.
        monkeypatch.setattr(kv_mod, "init_page_pool", spy)

        params, cfg = tiny_model
        B, page = 3, 16
        prompt = [1, 5, 9, 3, 7, 2]  # buckets to 128 → 8 prompt pages
        kw = dict(max_new_tokens=16, eos_ids=[], greedy=True)
        ref = generate(
            params, cfg, [prompt] * B, paged=False, share_prefix=False, **kw
        )
        out = generate(
            params, cfg, [prompt] * B, paged=True, page_size=page, **kw
        )
        np.testing.assert_array_equal(ref.tokens, out.tokens)
        # 128/16=8 shared prompt pages + per-row decode pages for the
        # DECODE_CHUNK-bucketed output budget + 1 trash page — versus
        # 3 full per-row tables + trash unshared.
        from adversarial_spec_tpu.engine.generate import (
            DECODE_CHUNK,
            bucket_length,
        )

        decode_pages = bucket_length(16, minimum=DECODE_CHUNK) // page
        assert pool_sizes == [8 + B * decode_pages + 1]

    def test_paged_decode_with_eos(self, tiny_model):
        params, cfg = tiny_model
        probe = generate(
            params, cfg, [[1, 2]], max_new_tokens=4, eos_ids=[], greedy=True
        )
        eos = int(probe.tokens[0, 0])
        dense = generate(
            params, cfg, [[1, 2]], max_new_tokens=24, eos_ids=[eos], greedy=True
        )
        paged = generate(
            params,
            cfg,
            [[1, 2]],
            max_new_tokens=24,
            eos_ids=[eos],
            greedy=True,
            paged=True,
            page_size=16,
        )
        np.testing.assert_array_equal(dense.tokens, paged.tokens)
        np.testing.assert_array_equal(dense.n_generated, paged.n_generated)

    def test_timing_fields_populated(self, tiny_model):
        params, cfg = tiny_model
        out = generate(
            params, cfg, [[1, 2, 3]], max_new_tokens=4, eos_ids=[], greedy=True
        )
        assert out.prefill_time_s > 0
        assert out.decode_time_s >= 0


class TestDecodeStateMachineFuzz:
    """Seeded mini-fuzz over the decode loop's state machine — mixed
    prompt lengths, random EOS vocab, speculation on/off — pinning the
    invariants that survive every path (sync, desync, catch-up, early
    EOS): per-row counts within budget, zero-fill after each row's end,
    and greedy speculation bit-identical to greedy plain decode."""

    def test_invariants_over_random_shapes(self, tiny_model):
        import random

        import numpy as np

        params, cfg = tiny_model
        rng = random.Random(7)
        for trial in range(8):
            b = rng.choice([1, 2, 3, 5])
            prompts = []
            for _ in range(b):
                n = rng.randrange(2, 24)
                base = [rng.randrange(3, cfg.vocab_size) for _ in range(n)]
                if rng.random() < 0.5:  # repetition helps drafts accept
                    base = (base * 4)[:n * 2]
                prompts.append(base)
            max_new = rng.choice([4, 12, 24])
            eos = (
                [rng.randrange(3, cfg.vocab_size)]
                if rng.random() < 0.5
                else []
            )
            kw = dict(max_new_tokens=max_new, eos_ids=eos, greedy=True)
            plain = generate(params, cfg, prompts, speculative=False, **kw)
            spec = generate(params, cfg, prompts, speculative=True, **kw)

            for r in (plain, spec):
                assert r.tokens.shape == (b, max_new)
                assert (r.n_generated >= 0).all()
                assert (r.n_generated <= max_new).all()
                for row in range(b):
                    n = int(r.n_generated[row])
                    # Zero-fill after each row's end (EOS contract).
                    assert (r.tokens[row, n:] == 0).all(), (trial, row)
                    if eos and n < max_new:
                        # A short row must have stopped AT its EOS.
                        assert r.tokens[row, n - 1] == eos[0], (trial, row)
            np.testing.assert_array_equal(
                plain.tokens, spec.tokens, err_msg=f"trial {trial}"
            )
            np.testing.assert_array_equal(
                plain.n_generated, spec.n_generated, err_msg=f"trial {trial}"
            )
