"""Fused-step / pipelined-drive-loop telemetry tests (engine/interleave.py).

The device-side behavior (fused dispatches, token parity, legacy escape
hatch) is pinned in tests/test_scheduler.py; this file covers the
process-wide accounting contract:

- ``stalled_prefill_s + overlapped_prefill_s == prefill_time_s`` holds
  EXACTLY (the mock engine's synthetic seconds are tokens/1024 — exact
  binary fractions — so the pin is ``==``, not approx);
- the mock engine attributes request 0 of a chat batch as stalled and
  later requests as overlapped, deterministically on CPU;
- the CLI's ``--json`` carries the ``perf.interleave`` block and the
  ``--no-interleave`` escape hatch zeroes the overlapped bucket.
"""

import io
import json

import pytest

from adversarial_spec_tpu.engine import interleave as interleave_mod


@pytest.fixture(autouse=True)
def _spec_off_module(monkeypatch):
    """Speculation is default-on and only multiplies the jit programs
    every batcher/engine this module compiles; its subject is
    orthogonal. Spec-on coverage (incl. SpecEvents, spec chaos fuzz,
    and the obs families) lives in tests/test_spec_batcher.py."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)



@pytest.fixture(autouse=True)
def _fresh_interleave_state():
    interleave_mod.configure(enabled=True, pipeline_depth=2)
    interleave_mod.reset_stats()
    yield
    interleave_mod.configure(enabled=True, pipeline_depth=2)
    interleave_mod.reset_stats()


class TestInterleaveModule:
    def test_snapshot_sum_invariant(self):
        s = interleave_mod.stats
        s.record_prefill_time(0.25, overlapped=False)
        s.record_prefill_time(0.5, overlapped=True)
        s.record_prefill_time(0.125, overlapped=True)
        snap = interleave_mod.snapshot()
        assert snap["stalled_prefill_s"] == 0.25
        assert snap["overlapped_prefill_s"] == 0.625
        assert snap["prefill_time_s"] == (
            snap["stalled_prefill_s"] + snap["overlapped_prefill_s"]
        )

    def test_configure_clamps_depth(self):
        assert interleave_mod.configure(pipeline_depth=9).pipeline_depth == 2
        assert interleave_mod.configure(pipeline_depth=0).pipeline_depth == 1
        assert interleave_mod.configure(pipeline_depth=2).pipeline_depth == 2

    def test_reset_zeroes_in_place(self):
        s = interleave_mod.stats
        s.record_step(fused=True)
        s.record_prefill_time(1.0, overlapped=True)
        ref = interleave_mod.stats  # engines hold the object itself
        interleave_mod.reset_stats()
        assert ref.fused_steps == 0 and ref.overlapped_prefill_s == 0.0


class TestMockEngineOverlapAccounting:
    def _chat(self, n_requests):
        from adversarial_spec_tpu.engine.mock import MockEngine
        from adversarial_spec_tpu.engine.types import (
            ChatRequest,
            SamplingParams,
        )

        reqs = [
            ChatRequest(
                model="mock://critic",
                system="sys " * 40,
                user=f"opponent {i} " * 50,
            )
            for i in range(n_requests)
        ]
        return MockEngine().chat(reqs, SamplingParams())

    def test_first_request_stalled_rest_overlapped(self):
        self._chat(3)
        snap = interleave_mod.snapshot()
        # Request 0 prefilled into an empty batch; 1 and 2 rode it.
        assert snap["prefill_steps"] == 1
        assert snap["fused_steps"] == 2
        assert snap["stalled_prefill_s"] > 0
        assert snap["overlapped_prefill_s"] > 0
        # Exact, not approximate: synthetic seconds are tokens/1024.
        assert snap["prefill_time_s"] == (
            snap["stalled_prefill_s"] + snap["overlapped_prefill_s"]
        )

    def test_disabled_loop_accounts_everything_stalled(self):
        interleave_mod.configure(enabled=False)
        self._chat(3)
        snap = interleave_mod.snapshot()
        assert snap["enabled"] is False
        assert snap["overlapped_prefill_s"] == 0.0
        assert snap["fused_steps"] == 0
        assert snap["prefill_steps"] == 3
        assert snap["stalled_prefill_s"] == snap["prefill_time_s"] > 0

    def test_single_request_has_nothing_to_overlap(self):
        self._chat(1)
        snap = interleave_mod.snapshot()
        assert snap["overlapped_prefill_s"] == 0.0
        assert snap["stalled_prefill_s"] > 0


class TestCliInterleaveFlags:
    SPEC = "# S\n" + "body line\n" * 50

    def _run(self, argv, monkeypatch, capsys):
        from adversarial_spec_tpu import cli

        monkeypatch.setattr("sys.stdin", io.StringIO(self.SPEC))
        code = cli.main(argv)
        out, err = capsys.readouterr()
        return code, json.loads(out), err

    def test_json_carries_interleave_section(self, monkeypatch, capsys):
        """A mock round with TWO opponents in one chat batch: one
        stalled + one overlapped prefill, and the sum invariant holds in
        the reported JSON — deterministically on CPU."""
        code, data, _ = self._run(
            [
                "critique", "--models", "mock://critic,mock://agree",
                "--json",
            ],
            monkeypatch, capsys,
        )
        assert code == 0
        snap = data["perf"]["interleave"]
        assert snap["enabled"] is True
        assert snap["pipeline_depth"] == 2
        assert snap["prefill_steps"] == 1
        assert snap["fused_steps"] == 1
        assert snap["overlapped_prefill_s"] > 0
        assert snap["stalled_prefill_s"] + snap["overlapped_prefill_s"] == (
            snap["prefill_time_s"]
        )

    def test_no_interleave_escape_hatch(self, monkeypatch, capsys):
        code, data, _ = self._run(
            [
                "critique", "--models", "mock://critic,mock://agree",
                "--json", "--no-interleave",
            ],
            monkeypatch, capsys,
        )
        assert code == 0
        snap = data["perf"]["interleave"]
        assert snap["enabled"] is False
        assert snap["fused_steps"] == 0
        assert snap["overlapped_prefill_s"] == 0.0
        assert snap["stalled_prefill_s"] == snap["prefill_time_s"] > 0

    def test_pipeline_depth_flag_reported(self, monkeypatch, capsys):
        code, data, _ = self._run(
            [
                "critique", "--models", "mock://agree", "--json",
                "--pipeline-depth", "1",
            ],
            monkeypatch, capsys,
        )
        assert code == 0
        assert data["perf"]["interleave"]["pipeline_depth"] == 1
