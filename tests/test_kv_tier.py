"""Tiered KV cache tests (engine/kvtier.py + the serving-path wiring).

Covers the tier state machine bottom-up:
- the procconfig hoist (the shared config/stats mechanics the four
  process-wide modules now ride on);
- chain hashing (cross-process content identity of radix blocks);
- HostTier LRU + the demote conservation invariant;
- DiskStore format hardening: atomic writes, fingerprint/token/sha
  verification, corrupt-entry quarantine, and a write/rehydrate/corrupt
  fuzz against an oracle;
- PageAllocator swap pins (a promotion's in-flight write target can
  never free under it);
- the mock engine's deterministic tier accounting (pressure promotion,
  restart rehydration through a shared store dir);
- the real batcher: demote/promote under a page cap and restart
  rehydration through the store, both byte-identical to tier-off, with
  allocator + tier invariants clean and zero unexpected recompiles;
- chaos: ``kv_swap`` injected mid-promotion evicts only the waiting
  request, leaves both tiers invariant-clean, and the auto-dumped JSONL
  reconstructs the swap + fault;
- CLI plumbing: flags/env reach the process config and ``perf.kv_tier``.
"""

import json
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine import kvtier
from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
from adversarial_spec_tpu.engine.kvcache import PageAllocator
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config


@pytest.fixture(autouse=True)
def _spec_off(monkeypatch):
    """This module pins tier demote/promote semantics; speculation only
    multiplies the jit programs each batcher compiles (the spec × tier
    interaction rides the same extend_evicting path test_spec_batcher
    covers)."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    return params, cfg


class TestProcConfig:
    def test_unknown_knob_fails_loudly(self):
        from adversarial_spec_tpu.engine import procconfig

        from dataclasses import dataclass

        @dataclass
        class C:
            enabled: bool = True

        @dataclass
        class S(procconfig.StatsBase):
            n: int = 0

        state = procconfig.ProcState(C(), S())
        with pytest.raises(AttributeError, match="no knob"):
            state.configure(typo=1)

    def test_ported_modules_keep_their_payload_keys(self):
        """The hoist is internal: every perf payload keeps its exact
        key set (CLI consumers and docs depend on them)."""
        from adversarial_spec_tpu.engine import interleave, spec

        il = interleave.snapshot()
        assert {"fused_steps", "prefill_time_s", "enabled",
                "pipeline_depth"} <= set(il)
        assert il["prefill_time_s"] == (
            il["stalled_prefill_s"] + il["overlapped_prefill_s"]
        )
        sp = spec.snapshot()
        assert {"acceptance_rate", "tokens_per_step", "enabled",
                "gamma"} <= set(sp)
        pc = prefix_mod.snapshot()
        assert "hit_rate" in pc and "enabled" in pc
        assert "max_pages" not in pc  # config-only knob stays out
        kt = kvtier.snapshot()
        assert {"host_hit_rate", "disk_hit_rate", "enabled", "host_mb",
                "store_dir"} <= set(kt)

    def test_gamma_validation_survives_the_port(self):
        from adversarial_spec_tpu.engine import spec

        with pytest.raises(ValueError, match="ADVSPEC_GAMMA"):
            spec.configure(gamma=0)

    def test_stats_reset_in_place(self):
        kvtier.stats.demoted_blocks = 7
        ref = kvtier.stats
        kvtier.reset_stats()
        assert ref.demoted_blocks == 0 and kvtier.stats is ref


class TestChainHash:
    def test_deterministic_and_parent_sensitive(self):
        a = kvtier.chain_hash("", (1, 2, 3))
        assert a == kvtier.chain_hash("", (1, 2, 3))
        assert a != kvtier.chain_hash("", (1, 2, 4))
        assert kvtier.chain_hash(a, (9,)) != kvtier.chain_hash("", (9,))

    def test_string_tokens_hash(self):
        # The mock's 4-char-chunk tokens must address the same way.
        assert kvtier.chain_hash("", ("abcd", "efgh")) == kvtier.chain_hash(
            "", ("abcd", "efgh")
        )


class TestHostTier:
    def test_lru_eviction_and_conservation(self):
        h = kvtier.HostTier(capacity_bytes=300, block_bytes=100)
        assert h.put("a", (1,), None) == []
        assert h.put("b", (2,), None) == []
        h.get("a")  # refresh: b becomes LRU
        assert h.put("c", (3,), None) == []
        evicted = h.put("d", (4,), None)
        assert [b.chain for b in evicted] == ["b"]
        h.note_freed(len(evicted))
        h.check_invariants()

    def test_take_promoted_is_terminal(self):
        h = kvtier.HostTier(capacity_bytes=1000, block_bytes=100)
        h.put("a", (1, 2), None)
        assert h.take_promoted("a").chain == "a"
        assert h.get("a") is None
        assert h.take_promoted("a") is None  # idempotent miss
        h.check_invariants()

    def test_conservation_violation_detected(self):
        h = kvtier.HostTier(capacity_bytes=1000, block_bytes=100)
        h.put("a", (1,), None)
        del h._blocks["a"]  # corrupt: vanished without a terminal state
        with pytest.raises(RuntimeError, match="conservation"):
            h.check_invariants()

    def test_single_block_over_budget_demotes_without_crash(self):
        """A block bigger than the whole host budget is evicted by
        put() itself (clear branch) — demote must treat it as an LRU
        victim (spill/free), not index the vanished entry."""
        kvtier.reset_stats()
        tiers = kvtier.TieredStore(
            kvtier.HostTier(capacity_bytes=10, block_bytes=100), None
        )
        calls = []

        def lazy():
            calls.append(1)
            return {"k": np.zeros(2)}

        tiers.demote("a", (1, 2), lazy)  # must not raise
        assert tiers.host_resident == 0
        assert kvtier.stats.host_freed_blocks == 1
        tiers.check_invariants()

    def test_lazy_payload_materializes_once(self):
        calls = []

        def lazy():
            calls.append(1)
            return {"k": np.zeros(2)}

        h = kvtier.HostTier(capacity_bytes=1000, block_bytes=100)
        h.put("a", (1,), lazy)
        b = h.get("a")
        p1 = kvtier.HostTier.materialize(b)
        p2 = kvtier.HostTier.materialize(b)
        assert p1 is p2 and calls == [1]


class TestDiskStore:
    def _store(self, tmp_path, fp="fp-a"):
        return kvtier.DiskStore(str(tmp_path / "store"), fp)

    def test_roundtrip_preserves_dtype_and_shape(self, tmp_path):
        s = self._store(tmp_path)
        payload = {
            "k": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
            "v": np.ones((2, 2), np.int8),
        }
        chain = kvtier.chain_hash("", (5, 6))
        assert s.put(chain, (5, 6), payload)
        assert not s.put(chain, (5, 6), payload)  # idempotent
        toks, got = s.get(chain, (5, 6))
        assert toks == (5, 6)
        assert got["k"].dtype == np.float32 and got["k"].shape == (2, 3, 4)
        assert np.array_equal(got["k"], payload["k"])
        assert got["v"].dtype == np.int8

    def test_no_tmp_orphan_after_put(self, tmp_path):
        s = self._store(tmp_path)
        s.put(kvtier.chain_hash("", (1,)), (1,), None)
        leftovers = [
            p for p in (tmp_path / "store").rglob("*") if ".tmp" in p.name
        ]
        assert leftovers == []

    def test_fingerprint_namespaces(self, tmp_path):
        a = self._store(tmp_path, "fp-a")
        chain = kvtier.chain_hash("", (1,))
        a.put(chain, (1,), None)
        b = kvtier.DiskStore(str(tmp_path / "store"), "fp-b")
        assert not b.has(chain)  # different namespace directory

    def test_token_mismatch_quarantines(self, tmp_path):
        s = self._store(tmp_path)
        chain = kvtier.chain_hash("", (1, 2))
        s.put(chain, (1, 2), None)
        kvtier.reset_stats()
        assert s.get(chain, (9, 9)) is None
        assert kvtier.stats.store_corrupt == 1
        assert s.resident_entries == 0
        assert not s.has(chain)
        # The evidence moved aside rather than vanishing.
        assert list((tmp_path / "store").rglob("quarantine/*.kvb"))

    def test_corrupt_payload_quarantines_and_store_survives(self, tmp_path):
        s = self._store(tmp_path)
        c1 = kvtier.chain_hash("", (1,))
        c2 = kvtier.chain_hash("", (2,))
        s.put(c1, (1,), {"k": np.arange(8, dtype=np.float32)})
        s.put(c2, (2,), {"k": np.arange(8, dtype=np.float32)})
        path = s._path(c1)
        raw = bytearray(open(path, "rb").read())
        raw[-3] ^= 0xFF  # flip a payload byte: sha must catch it
        open(path, "wb").write(bytes(raw))
        kvtier.reset_stats()
        assert s.get(c1, (1,)) is None
        assert kvtier.stats.store_corrupt == 1
        # The sibling entry still serves.
        assert s.get(c2, (2,)) is not None
        assert s.resident_entries == 1

    def test_restart_rescan_counts_entries(self, tmp_path):
        s = self._store(tmp_path)
        for i in range(3):
            s.put(kvtier.chain_hash("", (i,)), (i,), None)
        reopened = kvtier.DiskStore(str(tmp_path / "store"), "fp-a")
        assert reopened.resident_entries == 3


class TestDiskStoreConcurrentWriters:
    """The property the SHARED fleet store depends on (docs/fleet.md):
    N writers racing the same content-addressed chain — two threads of
    one engine, or two replica processes writing through one store dir
    — must end with EXACTLY ONE valid entry, no quarantine, and
    consistent resident accounting. The tmp+rename discipline makes
    the race harmless: every writer lands a complete identical entry
    under a unique temp name and the replaces are atomic."""

    def _race(self, tmp_path, stores, n_threads, payload):
        """Hammer one chain from n_threads across the given store
        instances, all released together by a barrier."""
        import threading

        chain = kvtier.chain_hash("", (7, 8, 9))
        barrier = threading.Barrier(n_threads)
        errors: list[BaseException] = []

        def write(store):
            try:
                barrier.wait(timeout=10)
                for _ in range(5):
                    store.put(chain, (7, 8, 9), payload)
            except BaseException as e:  # pragma: no cover - fail loudly
                errors.append(e)

        threads = [
            threading.Thread(target=write, args=(stores[i % len(stores)],))
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert errors == []
        return chain

    def _check_one_valid_entry(self, tmp_path, stores, chain, payload):
        kvtier.reset_stats()
        root = tmp_path / "store"
        entries = [
            p
            for p in root.rglob("*.kvb")
            if "quarantine" not in p.parts
        ]
        assert len(entries) == 1  # exactly one on-disk entry
        assert not list(root.rglob("quarantine/*")), "nothing quarantined"
        assert not [p for p in root.rglob("*") if ".tmp" in p.name]
        for s in stores:
            toks, got = s.get(chain, (7, 8, 9))  # fully verifies
            assert toks == (7, 8, 9)
            if payload is not None:
                assert np.array_equal(got["k"], payload["k"])
            # No writer double-counted: each instance tracks at most
            # the single entry that exists (check_invariants' one-sided
            # shared-store rule).
            assert s.resident_entries <= s._scan() == 1
        assert kvtier.stats.store_corrupt == 0

    def test_threads_sharing_one_instance(self, tmp_path):
        payload = {"k": np.arange(64, dtype=np.float32)}
        store = kvtier.DiskStore(str(tmp_path / "store"), "fp-a")
        chain = self._race(tmp_path, [store], n_threads=8, payload=payload)
        self._check_one_valid_entry(tmp_path, [store], chain, payload)
        assert store.resident_entries == 1  # counted exactly once

    def test_two_instances_same_dir_like_two_processes(self, tmp_path):
        """Two DiskStore instances over one dir — each fleet replica
        process holds its own instance; same-pid here makes the temp
        name collision HARDER than the cross-process case."""
        payload = {"k": np.arange(64, dtype=np.float32)}
        stores = [
            kvtier.DiskStore(str(tmp_path / "store"), "fp-a")
            for _ in range(2)
        ]
        chain = self._race(tmp_path, stores, n_threads=8, payload=payload)
        self._check_one_valid_entry(tmp_path, stores, chain, payload)

    def test_two_real_processes(self, tmp_path):
        """The literal fleet shape: two PROCESSES write-through the
        same chain simultaneously (rendezvous via a spin on a marker
        file), then the parent verifies the single valid entry."""
        import subprocess
        import sys

        script = r"""
import sys, os, time
sys.path.insert(0, sys.argv[1])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from adversarial_spec_tpu.engine import kvtier

root, ready, go = sys.argv[2], sys.argv[3], sys.argv[4]
store = kvtier.DiskStore(root, "fp-a")
chain = kvtier.chain_hash("", (7, 8, 9))
open(ready, "w").close()
deadline = time.time() + 20
while not os.path.exists(go):
    if time.time() > deadline:
        sys.exit(3)
    time.sleep(0.001)
for _ in range(5):
    store.put(chain, (7, 8, 9), {"k": np.arange(64, dtype=np.float32)})
print(store.resident_entries)
"""
        import os

        repo = os.path.dirname(
            os.path.dirname(
                os.path.dirname(os.path.abspath(kvtier.__file__))
            )
        )
        root = str(tmp_path / "store")
        go = tmp_path / "go"
        procs = []
        readies = []
        for i in range(2):
            ready = tmp_path / f"ready-{i}"
            readies.append(ready)
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-c", script, repo, root,
                        str(ready), str(go),
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                )
            )
        import time

        deadline = time.time() + 20
        while not all(r.exists() for r in readies):
            assert time.time() < deadline, "children never reached rendezvous"
            time.sleep(0.005)
        go.touch()  # both children race from here
        outs = [p.communicate(timeout=30) for p in procs]
        assert all(p.returncode == 0 for p in procs), outs
        chain = kvtier.chain_hash("", (7, 8, 9))
        verifier = kvtier.DiskStore(root, "fp-a")
        payload = {"k": np.arange(64, dtype=np.float32)}
        self._check_one_valid_entry(
            tmp_path, [verifier], chain, payload
        )


class TestDiskFuzz:
    def test_write_rehydrate_corrupt_against_oracle(self, tmp_path):
        """Random block sets through write/rehydrate/quarantine must
        agree with an oracle dict at every step: a corrupted entry
        reads as a miss exactly once (then quarantined), never as wrong
        data."""
        rng = random.Random(0)
        s = kvtier.DiskStore(str(tmp_path / "store"), "fuzz")
        oracle: dict[str, tuple] = {}
        kvtier.reset_stats()
        for step in range(200):
            op = rng.random()
            if op < 0.5 or not oracle:
                toks = tuple(rng.randrange(100) for _ in range(4))
                chain = kvtier.chain_hash("", toks + (step,))
                payload = {
                    "k": np.full((2, 2), step, np.float32)
                } if rng.random() < 0.5 else None
                s.put(chain, toks, payload)
                oracle[chain] = (
                    toks,
                    None if payload is None else payload["k"].copy(),
                )
            elif op < 0.85:
                chain = rng.choice(list(oracle))
                toks, want = oracle[chain]
                got = s.get(chain, toks)
                assert got is not None, "oracle says resident"
                assert got[0] == toks
                if want is None:
                    assert got[1] is None
                else:
                    assert np.array_equal(got[1]["k"], want)
            else:
                chain = rng.choice(list(oracle))
                path = s._path(chain)
                raw = bytearray(open(path, "rb").read())
                raw[rng.randrange(len(raw))] ^= 0xFF
                open(path, "wb").write(bytes(raw))
                del oracle[chain]
                # Corruption reads as a miss (quarantine), never data.
                assert s.get(chain, None) is None
            assert s.resident_entries == len(oracle)
        assert kvtier.stats.store_corrupt > 0


class TestAllocatorSwapPins:
    def test_pin_requires_allocated_page(self):
        a = PageAllocator(4, 4)
        with pytest.raises(ValueError, match="unallocated"):
            a.swap_pin(0)

    def test_free_under_pin_is_corruption(self):
        a = PageAllocator(4, 4)
        a.new_sequence(0)
        [p] = a.extend(0, 4)
        a.swap_pin(p)
        with pytest.raises(RuntimeError, match="swap in flight"):
            a.free_sequence(0)
        a.swap_unpin(p)
        a.check_invariants()

    def test_unpin_without_pin_raises(self):
        a = PageAllocator(4, 4)
        a.new_sequence(0)
        [p] = a.extend(0, 4)
        with pytest.raises(RuntimeError, match="without pin"):
            a.swap_unpin(p)

    def test_invariants_catch_pin_on_freed_page(self):
        a = PageAllocator(4, 4)
        a.new_sequence(0)
        [p] = a.extend(0, 4)
        a._swap_pins[p] = 1
        # Corrupt: the page freed (refs + table dropped) while a swap
        # pin is outstanding — an in-flight write against a freed page.
        a._tables[0] = []
        a._lengths[0] = 0
        a._refs.pop(p)
        a._free.append(p)
        with pytest.raises(RuntimeError, match="swap-pinned"):
            a.check_invariants()


def _mock_round(eng, doc, rnd, n_opp=2):
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

    reqs = [
        ChatRequest(
            model="mock://critic",
            system="You are an adversarial spec critic.",
            # Prefix-stable ordering: document first, round header last.
            user=(
                f"--- DOCUMENT ---\n{doc}\n--- END DOCUMENT ---\n"
                f"Debate round {rnd}"
            ),
        )
        for _ in range(n_opp)
    ]
    return eng.chat(reqs, SamplingParams())


class TestMockTier:
    DOC = (
        "The allocator SHALL bound page reuse by refcount. "
        "Demoted blocks MUST reach exactly one terminal state. "
    ) * 40  # ~4 KB -> well past a small radix cap

    def test_pressure_promotes_from_host(self):
        from adversarial_spec_tpu.engine.mock import MockEngine

        kvtier.configure(enabled=True, host_mb=16, store_dir="")
        prefix_mod.configure(enabled=True, max_pages=16)
        prefix_mod.reset_stats()
        kvtier.reset_stats()
        eng = MockEngine()
        _mock_round(eng, self.DOC, 1)
        snap = kvtier.snapshot()
        assert snap["demoted_blocks"] > 0  # cap eviction demoted the tail
        assert snap["promoted_tokens"] > 0  # opponent 2 promoted it back
        assert snap["host_hit_rate"] > 0
        eng._prefix.tiers.check_invariants()
        eng._allocator.check_invariants()

    def test_restart_rehydrates_from_store(self, tmp_path):
        from adversarial_spec_tpu.engine.mock import MockEngine

        kvtier.configure(
            enabled=True, host_mb=16, store_dir=str(tmp_path / "kv")
        )
        prefix_mod.configure(enabled=True, max_pages=0)
        prefix_mod.reset_stats()
        kvtier.reset_stats()
        eng_a = MockEngine()
        _mock_round(eng_a, self.DOC, 1)
        assert kvtier.stats.store_writes > 0
        # The restart: a FRESH engine (empty radix, empty host tier)
        # sharing only the store directory.
        before = prefix_mod.stats.prefilled_tokens
        eng_b = MockEngine()
        out = _mock_round(eng_b, self.DOC, 1)
        rehydration_prefill = prefix_mod.stats.prefilled_tokens - before
        snap = kvtier.snapshot()
        assert snap["rehydrated_tokens"] > 0
        assert out[0].usage.cached_tokens >= snap["rehydrated_tokens"] // 2
        # The restarted engine prefilled only the unaligned tail.
        assert rehydration_prefill < len(self.DOC) // 4 // 4
        eng_b._prefix.tiers.check_invariants()

    def test_transcripts_identical_tier_on_off(self, tmp_path):
        from adversarial_spec_tpu.engine.mock import MockEngine

        texts = {}
        for on in (True, False):
            kvtier.configure(
                enabled=on,
                host_mb=16,
                store_dir=str(tmp_path / "kv") if on else "",
            )
            prefix_mod.configure(enabled=True, max_pages=16)
            prefix_mod.reset_stats()
            kvtier.reset_stats()
            eng = MockEngine()
            texts[on] = [
                [c.text for c in _mock_round(eng, self.DOC, rnd)]
                for rnd in (1, 2)
            ]
        assert texts[True] == texts[False]

    def test_deterministic_stats_across_runs(self, tmp_path):
        from adversarial_spec_tpu.engine.mock import MockEngine

        snaps = []
        for rep in range(2):
            kvtier.configure(
                enabled=True,
                host_mb=16,
                store_dir=str(tmp_path / f"kv{rep}"),
            )
            prefix_mod.configure(enabled=True, max_pages=16)
            prefix_mod.reset_stats()
            kvtier.reset_stats()
            eng = MockEngine()
            for rnd in (1, 2):
                _mock_round(eng, self.DOC, rnd)
            snap = kvtier.stats.snapshot()
            snap.pop("swap_in_s")
            snap.pop("swap_out_s")
            snaps.append(snap)
        assert snaps[0] == snaps[1]


def _drain_rounds(params, cfg, *, rounds, prompt, cap_pages, max_new=8):
    """Drive a growing-prompt workload through a fresh batcher; returns
    (per-round token lists, per-round prefilled, batcher)."""
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )

    prefix_mod.configure(enabled=True, max_pages=cap_pages)
    b = ContinuousBatcher(
        params, cfg, max_batch=2, max_new_cap=max_new, page_size=16,
        prefix_cache=True,
    )
    doc = list(prompt)
    toks, prefilled = [], []
    for r in range(rounds):
        before = prefix_mod.stats.prefilled_tokens
        for i in range(2):
            b.submit(
                SchedRequest(
                    req_id=i, prompt_ids=list(doc), max_new_tokens=max_new
                )
            )
        results = b.run_all()
        toks.append([x.tokens.tolist() for x in results])
        prefilled.append(prefix_mod.stats.prefilled_tokens - before)
        doc = doc + [((r * 13 + k) % 400) + 3 for k in range(16)]
        b.allocator.check_invariants()
        if b.tiers is not None:
            b.tiers.check_invariants()
    return toks, prefilled, b


class TestBatcherTier:
    PROMPT = [((i * 7) % 400) + 3 for i in range(96)]

    def test_pressure_parity_and_promotion(self, tiny_model):
        """Page-cap pressure: tier-off re-prefills the evicted tail,
        tier-on promotes it from host RAM — byte-identical greedy
        tokens, clean invariants, zero unexpected recompiles."""
        from adversarial_spec_tpu import obs

        params, cfg = tiny_model
        kvtier.configure(enabled=True, host_mb=16, store_dir="")
        prefix_mod.reset_stats()
        kvtier.reset_stats()
        obs.reset_stats()
        on_toks, on_pre, b = _drain_rounds(
            params, cfg, rounds=2, prompt=self.PROMPT, cap_pages=3
        )
        snap = kvtier.snapshot()
        assert snap["demoted_blocks"] > 0
        assert snap["promoted_tokens"] > 0
        assert obs.snapshot()["retrace"]["unexpected_recompiles"] == 0
        kvtier.configure(enabled=False)
        off_toks, off_pre, _ = _drain_rounds(
            params, cfg, rounds=2, prompt=self.PROMPT, cap_pages=3
        )
        assert on_toks == off_toks
        # The host tier strictly reduces re-prefill under pressure.
        assert sum(on_pre) < sum(off_pre)

    def test_restart_rehydrates_byte_identical(self, tiny_model, tmp_path):
        params, cfg = tiny_model
        store = str(tmp_path / "kv")
        kvtier.configure(enabled=True, host_mb=16, store_dir=store)
        prefix_mod.reset_stats()
        kvtier.reset_stats()
        _drain_rounds(params, cfg, rounds=1, prompt=self.PROMPT, cap_pages=0)
        # Restart: a fresh batcher (new pool + radix) over the same store.
        kvtier.reset_stats()
        warm_toks, warm_pre, b = _drain_rounds(
            params, cfg, rounds=1, prompt=self.PROMPT, cap_pages=0
        )
        snap = kvtier.snapshot()
        assert snap["rehydrated_tokens"] > 0
        kvtier.configure(enabled=False)
        cold_toks, cold_pre, _ = _drain_rounds(
            params, cfg, rounds=1, prompt=self.PROMPT, cap_pages=0
        )
        assert warm_toks == cold_toks  # rehydrated KV == recomputed KV
        assert sum(warm_pre) < sum(cold_pre)

    def test_lost_race_degrades_to_prefill(self, tiny_model):
        """A host entry evicted between lookup and promotion must fall
        back to prefill (recomputed_blocks counts it) with identical
        output — the correctness escape hatch."""
        params, cfg = tiny_model
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        kvtier.configure(enabled=True, host_mb=16, store_dir="")
        prefix_mod.configure(enabled=True, max_pages=3)
        kvtier.reset_stats()
        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=8, page_size=16,
            prefix_cache=True,
        )
        b.submit(
            SchedRequest(
                req_id=0, prompt_ids=list(self.PROMPT), max_new_tokens=8
            )
        )
        ref = b.run_all()
        assert b.tiers.host_resident > 0
        # Sabotage the race: empty the host tier after lookups would
        # have seen it. materialize() must report the loss.
        b.tiers.host.clear()
        b.submit(
            SchedRequest(
                req_id=0, prompt_ids=list(self.PROMPT), max_new_tokens=8
            )
        )
        out = b.run_all()
        assert out[0].tokens.tolist() == ref[0].tokens.tolist()
        b.allocator.check_invariants()
        b.tiers.check_invariants()

    def test_chaos_kv_swap_evicts_only_waiting_slot(
        self, tiny_model, tmp_path
    ):
        """``kv_swap`` injected mid-promotion: the co-resident request
        finishes untouched, the faulted request reports the injected
        kind at the kv_swap seam, both tiers stay invariant-clean, and
        the auto-dumped JSONL reconstructs the swap + fault."""
        from adversarial_spec_tpu import obs
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )
        from adversarial_spec_tpu.resilience import injector

        params, cfg = tiny_model
        events_out = tmp_path / "ev.jsonl"
        obs.configure(events_out=str(events_out))
        kvtier.configure(enabled=True, host_mb=16, store_dir="")
        prefix_mod.configure(enabled=True, max_pages=3)
        kvtier.reset_stats()
        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=8, page_size=16,
            prefix_cache=True,
        )
        # Round 1 populates the host tier (cap eviction demotes).
        b.submit(
            SchedRequest(
                req_id=0, prompt_ids=list(self.PROMPT), max_new_tokens=8
            )
        )
        b.run_all()
        assert b.tiers.host_resident > 0
        # Round 2: the second promotion attempt faults (after=1 lets
        # block 1 promote first, so an in-flight swap is genuinely
        # abandoned mid-run).
        injector.install(
            injector.FaultInjector(
                injector.parse_chaos_spec("bug@kv_swap:after=1:times=1")
            )
        )
        try:
            for i in range(2):
                b.submit(
                    SchedRequest(
                        req_id=i,
                        prompt_ids=list(self.PROMPT),
                        max_new_tokens=8,
                    )
                )
            results = b.run_all()
        finally:
            injector.install(None)
        by_id = {r.req_id: r for r in results}
        # Exactly one request faulted (bug = permanent, no requeue) and
        # the co-resident finished with real tokens.
        faulted = [r for r in results if r.error]
        clean = [r for r in results if not r.error]
        assert len(faulted) == 1 and len(clean) == 1
        assert faulted[0].fault_kind == "bug"
        assert clean[0].n_generated > 0
        assert len(by_id) == 2
        b.allocator.check_invariants()
        b.tiers.check_invariants()
        b.prefix_cache.allocator.check_invariants()
        # The fault auto-dump reconstructs the story: SwapEvents for the
        # demotions/promotions and a FaultEvent at the kv_swap seam.
        dump = tmp_path / "ev.fault.jsonl"
        assert dump.exists()
        events = [json.loads(l) for l in dump.read_text().splitlines()]
        from adversarial_spec_tpu.obs.events import validate_event

        assert all(validate_event(e) == [] for e in events)
        assert any(e["type"] == "swap" for e in events)
        faults = [e for e in events if e["type"] == "fault"]
        assert any(e["seam"] == "kv_swap" for e in faults)


class TestCliPlumbing:
    def _run(self, argv, monkeypatch, capsys, stdin="# Spec\nbody\n"):
        import io
        import sys as _sys

        from adversarial_spec_tpu import cli

        monkeypatch.setattr(_sys, "stdin", io.StringIO(stdin))
        rc = cli.main(argv)
        out = capsys.readouterr().out
        return rc, out

    def test_flags_reach_config_and_perf_block(
        self, monkeypatch, capsys, tmp_path
    ):
        # Restore the production env default (conftest pins the suite
        # to ADVSPEC_KV_TIER=0 for wall budget; this test IS the
        # default's coverage).
        monkeypatch.delenv("ADVSPEC_KV_TIER", raising=False)
        store = str(tmp_path / "kv")
        rc, out = self._run(
            [
                "critique",
                "--models",
                "mock://critic",
                "--json",
                "--kv-host-mb",
                "7",
                "--kv-store-dir",
                store,
            ],
            monkeypatch,
            capsys,
        )
        assert rc == 0
        payload = json.loads(out)
        tier = payload["perf"]["kv_tier"]
        assert tier["enabled"] is True
        assert tier["host_mb"] == 7
        assert tier["store_dir"] == store
        assert tier["store_writes"] > 0  # write-through persisted blocks

    def test_no_kv_tier_disables_and_does_not_leak(
        self, monkeypatch, capsys
    ):
        monkeypatch.delenv("ADVSPEC_KV_TIER", raising=False)
        rc, out = self._run(
            ["critique", "--models", "mock://critic", "--json",
             "--no-kv-tier"],
            monkeypatch,
            capsys,
        )
        assert rc == 0
        assert json.loads(out)["perf"]["kv_tier"]["enabled"] is False
        # The next invocation re-resolves to env defaults: no leak.
        rc, out = self._run(
            ["critique", "--models", "mock://critic", "--json"],
            monkeypatch,
            capsys,
        )
        assert rc == 0
        tier = json.loads(out)["perf"]["kv_tier"]
        assert tier["enabled"] is True
        assert tier["host_mb"] == kvtier.DEFAULT_HOST_MB

    def test_env_defaults_respected(self, monkeypatch, capsys, tmp_path):
        monkeypatch.setenv("ADVSPEC_KV_TIER", "0")
        rc, out = self._run(
            ["critique", "--models", "mock://critic", "--json"],
            monkeypatch,
            capsys,
        )
        assert rc == 0
        assert json.loads(out)["perf"]["kv_tier"]["enabled"] is False


class TestObsDumpTimeline:
    def test_swap_events_validate_and_annotate_timeline(self, tmp_path):
        """SwapEvent rides the EVENT_FIELDS schema and the occupancy
        timeline annotates per-tier residency."""
        from adversarial_spec_tpu import obs
        from adversarial_spec_tpu.engine.mock import MockEngine

        from tools.obs_dump import load_events, occupancy_timeline

        kvtier.configure(enabled=True, host_mb=16, store_dir="")
        prefix_mod.configure(enabled=True, max_pages=16)
        obs.reset_stats()
        eng = MockEngine()
        _mock_round(eng, TestMockTier.DOC, 1)
        path = tmp_path / "ev.jsonl"
        obs.dump_events(str(path))
        events, errors = load_events(str(path))
        assert errors == []
        assert any(e["type"] == "swap" for e in events)
        timeline = occupancy_timeline(events)
        assert "host=" in timeline and "disk=" in timeline
        assert "demote" in timeline


class TestFlushThreshold:
    """``--kv-flush-blocks``: write-through flush every N enqueued
    blocks instead of only at settle — the disagg publication window
    bound (docs/kv_tiering.md)."""

    def _tiers(self, tmp_path):
        kvtier.reset_stats()
        return kvtier.TieredStore(
            None, kvtier.DiskStore(str(tmp_path / "store"), "fp-a")
        )

    def _payload(self):
        return {"k": np.zeros(2, dtype=np.float32)}

    def test_env_default(self, monkeypatch):
        monkeypatch.delenv("ADVSPEC_KV_FLUSH_BLOCKS", raising=False)
        assert kvtier.env_flush_blocks() == 0  # settle-only
        monkeypatch.setenv("ADVSPEC_KV_FLUSH_BLOCKS", "8")
        assert kvtier.env_flush_blocks() == 8
        monkeypatch.setenv("ADVSPEC_KV_FLUSH_BLOCKS", "junk")
        assert kvtier.env_flush_blocks() == 0

    def test_settle_only_by_default(self, tmp_path):
        tiers = self._tiers(tmp_path)
        for i in range(6):
            tiers.enqueue_store(
                kvtier.chain_hash("", (i,)), (i,), self._payload()
            )
        assert kvtier.stats.store_writes == 0  # nothing mid-drain
        assert tiers.settle() == 6
        assert kvtier.stats.store_writes == 6

    def test_threshold_flushes_mid_drain(self, tmp_path):
        kvtier.configure(flush_blocks=3)
        tiers = self._tiers(tmp_path)
        for i in range(5):
            tiers.enqueue_store(
                kvtier.chain_hash("", (i,)), (i,), self._payload()
            )
        # The 3rd enqueue crossed the threshold: one flush of 3.
        assert kvtier.stats.store_writes == 3
        assert tiers.settle() == 2  # the tail still settles
        assert kvtier.stats.store_writes == 5

    def test_threshold_flush_never_resolves_lazies(self, tmp_path):
        """A threshold flush must not sync the device mid-drain: lazy
        payloads stay queued for settle (the sanctioned point)."""
        kvtier.configure(flush_blocks=2)
        tiers = self._tiers(tmp_path)
        calls = []

        def lazy():
            calls.append(1)
            return self._payload()

        tiers.enqueue_store(kvtier.chain_hash("", (1,)), (1,), lazy)
        tiers.enqueue_store(
            kvtier.chain_hash("", (2,)), (2,), self._payload()
        )
        # Threshold crossed: the plain payload flushed, the lazy held.
        assert kvtier.stats.store_writes == 1
        assert calls == []
        assert tiers.settle() == 1  # lazy resolves only at settle
        assert calls == [1]
        assert kvtier.stats.store_writes == 2
