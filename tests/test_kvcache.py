"""Paged KV-cache manager tests: allocator bookkeeping and pool scatter."""

import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine.kvcache import (
    OutOfPages,
    PageAllocator,
    PagedCacheLayout,
    init_page_pool,
    token_positions_to_pages,
    write_tokens,
)


class TestPageAllocator:
    def test_extend_allocates_minimal_pages(self):
        a = PageAllocator(n_pages=8, page_size=4)
        a.new_sequence(0)
        new = a.extend(0, 3)  # 3 tokens → 1 page
        assert len(new) == 1
        assert a.length(0) == 3
        assert a.extend(0, 1) == []  # 4th token fits the same page
        new2 = a.extend(0, 1)  # 5th token → second page
        assert len(new2) == 1
        assert a.free_pages == 6

    def test_tables_are_ordered(self):
        a = PageAllocator(n_pages=8, page_size=2)
        a.new_sequence(1)
        a.extend(1, 6)
        assert len(a.table(1)) == 3

    def test_out_of_pages_rolls_back(self):
        a = PageAllocator(n_pages=2, page_size=2)
        a.new_sequence(0)
        a.extend(0, 4)  # both pages used
        a.new_sequence(1)
        with pytest.raises(OutOfPages):
            a.extend(1, 2)
        assert a.length(1) == 0
        assert a.free_pages == 0
        a.free_sequence(0)
        assert a.free_pages == 2
        a.extend(1, 2)  # now fits

    def test_free_sequence_recycles(self):
        a = PageAllocator(n_pages=4, page_size=2)
        a.new_sequence(0)
        a.extend(0, 8)
        assert a.free_pages == 0
        a.free_sequence(0)
        assert a.free_pages == 4

    def test_duplicate_sequence_rejected(self):
        a = PageAllocator(n_pages=4, page_size=2)
        a.new_sequence(0)
        with pytest.raises(ValueError, match="already allocated"):
            a.new_sequence(0)

    def test_table_array_padding(self):
        a = PageAllocator(n_pages=8, page_size=2)
        a.new_sequence(0)
        a.new_sequence(1)
        a.extend(0, 4)
        a.extend(1, 2)
        arr = a.table_array([0, 1], max_pages=4)
        assert arr.shape == (2, 4)
        assert (arr[0, :2] >= 0).all() and (arr[0, 2:] == -1).all()
        assert arr[1, 0] >= 0 and (arr[1, 1:] == -1).all()

    def test_table_array_overflow_raises(self):
        a = PageAllocator(n_pages=8, page_size=1)
        a.new_sequence(0)
        a.extend(0, 5)
        with pytest.raises(ValueError, match="spans"):
            a.table_array([0], max_pages=4)


class TestPagePool:
    def test_write_and_readback(self):
        layout = PagedCacheLayout(
            n_pages=4, page_size=2, n_layers=2, n_kv_heads=2, head_dim=4
        )
        pool = init_page_pool(layout, dtype=jnp.float32)
        a = PageAllocator(layout.n_pages, layout.page_size)
        a.new_sequence(0)
        a.extend(0, 3)

        L, B, S = 2, 1, 3
        # Heads-major cache layout [L, B, Hkv, S, D].
        k_new = jnp.arange(L * B * S * 2 * 4, dtype=jnp.float32).reshape(
            L, B, 2, S, 4
        )
        v_new = -k_new
        positions = np.array([[0, 1, 2]])
        page_ids, offsets = token_positions_to_pages(a, [0], positions)
        pool = write_tokens(pool, k_new, v_new, page_ids, offsets)

        table = a.table(0)
        # Token 0 → page table[0] slot 0; token 2 → page table[1] slot 0.
        np.testing.assert_array_equal(
            np.asarray(pool["k"][:, table[0], :, 0]),
            np.asarray(k_new[:, 0, :, 0]),
        )
        np.testing.assert_array_equal(
            np.asarray(pool["k"][:, table[0], :, 1]),
            np.asarray(k_new[:, 0, :, 1]),
        )
        np.testing.assert_array_equal(
            np.asarray(pool["k"][:, table[1], :, 0]),
            np.asarray(k_new[:, 0, :, 2]),
        )
        np.testing.assert_array_equal(
            np.asarray(pool["v"][:, table[0], :, 0]),
            np.asarray(v_new[:, 0, :, 0]),
        )

    def test_capacity(self):
        layout = PagedCacheLayout(
            n_pages=16, page_size=128, n_layers=1, n_kv_heads=1, head_dim=8
        )
        assert layout.tokens_capacity == 2048
