"""The TPU ladder's measurement code must be proven BEFORE a tunnel
window: ADVSPEC_LADDER_SMOKE=1 runs the full phase-A path (and one
phase-B env child) on CPU with tiny shapes, and the harvest must parse
into recommendations. A bug here would otherwise meet its first
execution during the scarce hardware session it exists to harvest."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.crossover_report import load, recommended_min_t  # noqa: E402


def _run_child(args, out_path, extra_env=None, timeout=600):
    env = dict(os.environ)
    env.update(
        ADVSPEC_LADDER_SMOKE="1",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=str(REPO_ROOT),
    )
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, str(REPO_ROOT / "tpu_ladder.py")] + args,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO_ROOT,
    )


@pytest.mark.slow
def test_phase_a_smoke_records_every_step(tmp_path):
    out = tmp_path / "smoke.jsonl"
    proc = _run_child(["--child-main", str(out)], out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    steps = load(str(out), include_smoke=True)
    for required in (
        "north_star",
        "crossover_T256_kernel",
        "crossover_T256_xla",
        "spec_on",
        "spec_off",
        "int8_kv",
        "int8_weights",
        "int8_weights_kv",
        "paged",
        "greedy",
        "long_context_16k",
        "profile_trace",
        "config2_8b_int8_greedy",
        "phase_a_complete",
    ):
        assert required in steps, (required, sorted(steps))
    assert steps["north_star"]["decode_tok_s"] > 0
    # The harvest parses into a MIN_T recommendation (0 or the sentinel
    # — either is fine on CPU; the point is the pipeline runs).
    assert recommended_min_t(steps) is not None
    # Real-harvest consumers must NOT see smoke rows.
    assert load(str(out)) == {}
    # The profiler trace directory materialized.
    assert os.path.isdir(steps["profile_trace"]["trace_dir"])


@pytest.mark.slow
def test_phase_a_smoke_resumes_without_remeasuring(tmp_path):
    """Steps already in the results file are skipped on re-run (the
    resumability a flaky tunnel depends on)."""
    out = tmp_path / "smoke.jsonl"
    done = {
        "step": "north_star",
        "decode_tok_s": 123.0,
        "sentinel": "preexisting",
        "smoke": True,  # matches the smoke run's resumability domain
    }
    out.write_text(json.dumps(done) + "\n")
    proc = _run_child(["--child-main", str(out)], out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    north = [
        json.loads(line)
        for line in out.read_text().splitlines()
        if '"north_star"' in line
    ]
    assert len(north) == 1 and north[0]["sentinel"] == "preexisting"


@pytest.mark.slow
def test_phase_b_env_child_smoke(tmp_path):
    out = tmp_path / "smoke.jsonl"
    proc = _run_child(
        ["--child-env", str(out), "gamma4"],
        out,
        extra_env={"ADVSPEC_GAMMA": "4"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    steps = load(str(out), include_smoke=True)
    assert steps["gamma4"]["decode_tok_s"] > 0
    assert steps["gamma4"]["env"] == {"ADVSPEC_GAMMA": "4"}


@pytest.mark.slow
def test_tier_child_smoke(tmp_path):
    """Phase C (tiered KV): the child must record the restart-
    rehydration step and every pool-sweep row with the tier telemetry
    the crossover report renders."""
    import tpu_ladder

    out = tmp_path / "smoke.jsonl"
    proc = _run_child(["--child-tier", str(out)], out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    steps = load(str(out), include_smoke=True)
    for required in tpu_ladder.TIER_STEPS:
        assert required in steps, (required, sorted(steps))
    tr = steps["tier_restart"]
    assert tr["rehydrated_fraction"] > 0
    assert tr["rehydrated_tokens"] > 0
    for p in tpu_ladder.TIER_POOL_TOKENS:
        row = steps[f"tier_pool{p}"]
        assert row["decode_tok_s"] > 0
        assert row["pool_tokens"] > 0


def test_residency_child_smoke(tmp_path):
    """Phase D (weight residency): the child must record every
    (pool, budget) sweep point with the paging-vs-thrash walls and the
    swap-overlap fraction the residency story is judged by."""
    import tpu_ladder

    out = tmp_path / "smoke.jsonl"
    proc = _run_child(["--child-residency", str(out)], out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    steps = load(str(out), include_smoke=True)
    for required in tpu_ladder.RES_STEPS:
        assert required in steps, (required, sorted(steps))
    # The 4-pool/2-budget acceptance point must actually swap, and
    # paging must beat naive evict-reload on weight-load seconds.
    row = steps["res_pool4b2"]
    assert row["promotions"] > 0
    assert row["load_wall_thrash_s"] > row["load_wall_resident_s"]
    # The no-pressure control must not swap at all.
    assert steps["res_pool2b2"]["demotions"] == 0


def test_kernels_child_smoke(tmp_path):
    """Phase E (fused serving kernels): the child must record the
    dequant-matmul A/B for both quantized formats and the span-verify
    A/B, each with byte-identical transcripts across arms — the parity
    half of the harvest a hardware window banks tok/s against."""
    import tpu_ladder

    out = tmp_path / "smoke.jsonl"
    proc = _run_child(["--child-kernels", str(out)], out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    steps = load(str(out), include_smoke=True)
    for required in tpu_ladder.KERNEL_STEPS:
        assert required in steps, (required, sorted(steps))
        row = steps[required]
        assert row["tokens_identical"] is True, row
        assert row["speedup"] > 0
    assert steps["kernels_int8_matmul"]["decode_tok_s_fused"] > 0
    sv = steps["kernels_span_verify"]
    assert sv["decode_tok_s_kernel"] > 0
    assert sv["tokens_per_step"] >= 1.0


def test_batcher_spec_child_smoke(tmp_path):
    """Phase B' (batcher γ sweep): the child must drain the bench-shaped
    pool through the ContinuousBatcher under the env γ and record the
    speculation telemetry the crossover is judged by."""
    out = tmp_path / "smoke.jsonl"
    proc = _run_child(
        ["--child-batcher-spec", str(out), "batcher_gamma4"],
        out,
        extra_env={"ADVSPEC_GAMMA": "4"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    steps = load(str(out), include_smoke=True)
    row = steps["batcher_gamma4"]
    assert row["decode_tok_s"] > 0
    assert row["spec_steps"] > 0
    assert row["tokens_per_step"] >= 1.0
    assert row["env"] == {"ADVSPEC_GAMMA": "4"}


class TestOrchestrator:
    """The orchestrator's unattended branching: probe gating, skip of a
    completed phase A, phase-B completeness, and the final marker."""

    def _steps_file(self, tmp_path, steps):
        out = tmp_path / "r.jsonl"
        out.write_text(
            "\n".join(json.dumps({"step": s, "decode_tok_s": 1.0})
                      for s in steps) + "\n"
        )
        return out

    def test_probe_failure_runs_nothing(self, tmp_path, monkeypatch):
        import bench
        import tpu_ladder

        monkeypatch.delenv("ADVSPEC_LADDER_SMOKE", raising=False)
        monkeypatch.setattr(bench, "_probe_tpu", lambda **kw: False)
        monkeypatch.setattr(
            tpu_ladder.subprocess, "Popen",
            lambda *a, **k: pytest.fail("no child may launch"),
        )
        out = tmp_path / "r.jsonl"
        assert tpu_ladder.orchestrate(str(out)) == 3
        assert not out.exists() or "ladder_complete" not in out.read_text()

    def test_fully_harvested_file_completes_without_children(
        self, tmp_path, monkeypatch
    ):
        import bench
        import tpu_ladder

        monkeypatch.delenv("ADVSPEC_LADDER_SMOKE", raising=False)
        out = self._steps_file(
            tmp_path,
            [
                "phase_a_complete",
                *tpu_ladder.ENV_STEPS,
                *tpu_ladder.BATCHER_SPEC_STEPS,
                *tpu_ladder.TIER_STEPS,
                *tpu_ladder.RES_STEPS,
                *tpu_ladder.KERNEL_STEPS,
            ],
        )
        monkeypatch.setattr(bench, "_probe_tpu", lambda **kw: True)
        monkeypatch.setattr(
            tpu_ladder.subprocess, "Popen",
            lambda *a, **k: pytest.fail("no child may launch"),
        )
        assert tpu_ladder.orchestrate(str(out)) == 0
        assert "ladder_complete" in out.read_text()

    def test_missing_env_step_launches_only_it(self, tmp_path, monkeypatch):
        import bench
        import tpu_ladder

        monkeypatch.delenv("ADVSPEC_LADDER_SMOKE", raising=False)
        done = [
            s
            for s in (
                list(tpu_ladder.ENV_STEPS)
                + list(tpu_ladder.BATCHER_SPEC_STEPS)
                + list(tpu_ladder.TIER_STEPS)
                + list(tpu_ladder.RES_STEPS)
                + list(tpu_ladder.KERNEL_STEPS)
            )
            if s != "gamma16"
        ]
        out = self._steps_file(tmp_path, ["phase_a_complete", *done])
        monkeypatch.setattr(bench, "_probe_tpu", lambda **kw: True)
        launched = []

        class FakeChild:
            def __init__(self, cmd, **kw):
                flag = (
                    "--child-env"
                    if "--child-env" in cmd
                    else "--child-batcher-spec"
                    if "--child-batcher-spec" in cmd
                    else "--child-tier"
                    if "--child-tier" in cmd
                    else "--child-residency"
                    if "--child-residency" in cmd
                    else "--child-kernels"
                )
                i = cmd.index(flag)
                if flag in (
                    "--child-tier", "--child-residency", "--child-kernels"
                ):
                    # These children record every remaining phase step.
                    phase_steps = (
                        tpu_ladder.TIER_STEPS
                        if flag == "--child-tier"
                        else tpu_ladder.RES_STEPS
                        if flag == "--child-residency"
                        else tpu_ladder.KERNEL_STEPS
                    )
                    launched.append(flag.removeprefix("--child-"))
                    with open(cmd[i + 1], "a") as f:
                        for s in phase_steps:
                            f.write(json.dumps({"step": s}) + "\n")
                    return
                step = cmd[i + 2]
                launched.append(step)
                with open(cmd[i + 1], "a") as f:
                    f.write(
                        json.dumps({"step": step, "decode_tok_s": 1.0})
                        + "\n"
                    )

            def poll(self):
                return 0

        monkeypatch.setattr(tpu_ladder.subprocess, "Popen", FakeChild)
        assert tpu_ladder.orchestrate(str(out)) == 0
        assert launched == ["gamma16"]
        assert "ladder_complete" in out.read_text()

    def test_env_child_without_record_is_incomplete(
        self, tmp_path, monkeypatch
    ):
        """A phase-B child that exits without recording its step must
        leave the ladder INCOMPLETE (rc=2, no ladder_complete) so the
        session loop retries."""
        import bench
        import tpu_ladder

        monkeypatch.delenv("ADVSPEC_LADDER_SMOKE", raising=False)
        done = [s for s in tpu_ladder.ENV_STEPS if s != "gamma16"]
        out = self._steps_file(tmp_path, ["phase_a_complete", *done])
        monkeypatch.setattr(bench, "_probe_tpu", lambda **kw: True)

        class SilentChild:
            def __init__(self, *a, **k):
                pass

            def poll(self):
                return 1  # died without writing its row

        monkeypatch.setattr(tpu_ladder.subprocess, "Popen", SilentChild)
        assert tpu_ladder.orchestrate(str(out)) == 2
        assert "ladder_complete" not in out.read_text()
