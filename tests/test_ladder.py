"""The TPU ladder's measurement code must be proven BEFORE a tunnel
window: ADVSPEC_LADDER_SMOKE=1 runs the full phase-A path (and one
phase-B env child) on CPU with tiny shapes, and the harvest must parse
into recommendations. A bug here would otherwise meet its first
execution during the scarce hardware session it exists to harvest."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.crossover_report import load, recommended_min_t  # noqa: E402


def _run_child(args, out_path, extra_env=None, timeout=600):
    env = dict(os.environ)
    env.update(
        ADVSPEC_LADDER_SMOKE="1",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=str(REPO_ROOT),
    )
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, str(REPO_ROOT / "tpu_ladder.py")] + args,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO_ROOT,
    )


@pytest.mark.slow
def test_phase_a_smoke_records_every_step(tmp_path):
    out = tmp_path / "smoke.jsonl"
    proc = _run_child(["--child-main", str(out)], out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    steps = load(str(out), include_smoke=True)
    for required in (
        "north_star",
        "crossover_T256_kernel",
        "crossover_T256_xla",
        "spec_on",
        "spec_off",
        "int8_kv",
        "int8_weights",
        "int8_weights_kv",
        "paged",
        "greedy",
        "long_context_16k",
        "profile_trace",
        "config2_8b_int8_greedy",
        "phase_a_complete",
    ):
        assert required in steps, (required, sorted(steps))
    assert steps["north_star"]["decode_tok_s"] > 0
    # The harvest parses into a MIN_T recommendation (0 or the sentinel
    # — either is fine on CPU; the point is the pipeline runs).
    assert recommended_min_t(steps) is not None
    # Real-harvest consumers must NOT see smoke rows.
    assert load(str(out)) == {}
    # The profiler trace directory materialized.
    assert os.path.isdir(steps["profile_trace"]["trace_dir"])


@pytest.mark.slow
def test_phase_a_smoke_resumes_without_remeasuring(tmp_path):
    """Steps already in the results file are skipped on re-run (the
    resumability a flaky tunnel depends on)."""
    out = tmp_path / "smoke.jsonl"
    done = {
        "step": "north_star",
        "decode_tok_s": 123.0,
        "sentinel": "preexisting",
        "smoke": True,  # matches the smoke run's resumability domain
    }
    out.write_text(json.dumps(done) + "\n")
    proc = _run_child(["--child-main", str(out)], out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    north = [
        json.loads(line)
        for line in out.read_text().splitlines()
        if '"north_star"' in line
    ]
    assert len(north) == 1 and north[0]["sentinel"] == "preexisting"


@pytest.mark.slow
def test_phase_b_env_child_smoke(tmp_path):
    out = tmp_path / "smoke.jsonl"
    proc = _run_child(
        ["--child-env", str(out), "gamma4"],
        out,
        extra_env={"ADVSPEC_GAMMA": "4"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    steps = load(str(out), include_smoke=True)
    assert steps["gamma4"]["decode_tok_s"] > 0
    assert steps["gamma4"]["env"] == {"ADVSPEC_GAMMA": "4"}
