"""Trace-replay load harness tests (tools/load_replay.py).

Covers the four contracts the harness stands on: the canonical shape
encoding is invertible (record → reconstruct → identical requests),
the synthetic generator is seed-deterministic, the recording reader is
torn/foreign-line tolerant (journal discipline), and the arrival
process is OPEN-LOOP — a slow server must never slow the schedule.
The chaos-marked smoke drives the real daemon end to end: a small
sweep with zero accepted-request loss, plus the 1×-rate round-trip
that replays a recorded flight-recorder dump to byte-identical
transcripts.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from adversarial_spec_tpu.serve import driver  # noqa: E402
from tools import load_replay  # noqa: E402
from tools.load_replay import (  # noqa: E402
    ReplayRequest,
    ServeKnobs,
    SLOSpec,
    SynthSpec,
    canonical_spec,
    est_tokens_for,
    read_recording,
    replay_once,
    slo_breaches,
    spec_chars_from_est,
    synthesize,
    tenant_rates,
)


class TestCanonicalShapeEncoding:
    def test_spec_is_exact_length_and_deterministic(self):
        for n in (128, 256, 513, 2048, 4096):
            s = canonical_spec(n)
            assert len(s) == n - (n % 4)
            assert s == canonical_spec(n)

    def test_est_inverts_for_every_canonical_shape(self):
        """The round-trip pin at the unit level: estimate → shape →
        estimate is the identity for every canonical (chars, tier)."""
        for tier in ("interactive", "batch"):
            for chars in (128, 400, 512, 1000, 4096):
                chars = len(canonical_spec(chars))
                est = est_tokens_for(chars, tier)
                assert spec_chars_from_est(est, tier) == chars
                # And the daemon-side estimator agrees byte for byte.
                assert est == driver.estimate_debate_tokens(
                    {
                        "spec": canonical_spec(chars),
                        "models": list(load_replay.MODELS),
                        "max_new_tokens": load_replay.TIER_MAX_NEW[tier],
                    }
                )

    def test_foreign_estimates_rejected(self):
        assert spec_chars_from_est(3, "interactive") is None  # odd
        assert spec_chars_from_est(10, "interactive") is None  # tiny
        assert spec_chars_from_est(10**6, "batch") is None  # huge
        assert spec_chars_from_est(900, "premium") is None  # bad tier


class TestSynthesis:
    def test_seed_determinism(self):
        a = synthesize(SynthSpec(seed=7, requests=40))
        b = synthesize(SynthSpec(seed=7, requests=40))
        assert a == b
        c = synthesize(SynthSpec(seed=8, requests=40))
        assert a != c

    def test_trace_shape(self):
        reqs = synthesize(SynthSpec(seed=0, requests=80, tenants=3))
        assert len(reqs) == 80
        # Arrivals are monotone non-decreasing offsets from 0.
        offsets = [r.arrival_s for r in reqs]
        assert offsets == sorted(offsets) and offsets[0] > 0
        # Zipf skew: the hot tenant dominates.
        rates = tenant_rates(reqs)
        assert set(rates) <= {"t0", "t1", "t2"}
        assert rates["t0"] == max(rates.values())
        # Mixed tiers, canonical shapes throughout.
        assert {r.tier for r in reqs} == {"interactive", "batch"}
        for r in reqs:
            assert r.spec_chars == len(canonical_spec(r.spec_chars))


class TestTolerantReader:
    def _line(self, seq, op="accepted", arrival=1.0, tokens=None,
              tier="interactive", tenant="t0"):
        if tokens is None:
            tokens = est_tokens_for(512, tier)
        return json.dumps(
            {
                "seq": seq,
                "type": "serve",
                "op": op,
                "tenant": tenant,
                "tier": tier,
                "debate": f"d{seq:05d}",
                "index": -1,
                "reason": "",
                "tokens": tokens,
                "backlog_tokens": 0,
                "arrival_s": arrival,
                "trace_id": "",
                "span_id": "",
            }
        )

    def test_reconstructs_arrivals_rebased(self, tmp_path):
        p = tmp_path / "events.jsonl"
        p.write_text(
            self._line(1, arrival=5.0)
            + "\n"
            + self._line(2, arrival=5.25, tier="batch", tenant="t1")
            + "\n"
        )
        reqs, report = read_recording(p)
        assert report == {"requests": 2, "skipped": 0, "torn_tail": 0}
        assert [r.arrival_s for r in reqs] == [0.0, 0.25]  # re-based
        assert [r.tenant for r in reqs] == ["t0", "t1"]
        assert [r.tier for r in reqs] == ["interactive", "batch"]
        assert all(r.spec_chars == 512 for r in reqs)

    def test_torn_tail_discarded_foreign_lines_skipped_alone(
        self, tmp_path
    ):
        """Journal tolerant-reader discipline: one bad byte never
        poisons the recording — garbage, foreign event types, foreign
        versions (unknown shape / wrong field types), and a torn final
        line each drop ALONE."""
        p = tmp_path / "events.jsonl"
        lines = [
            self._line(1, arrival=1.0),
            "{not json at all",
            json.dumps({"seq": 2, "type": "futuristic", "op": "warp"}),
            json.dumps({"seq": 3, "type": "step", "kind": "decode"}),
            # serve event from a FOREIGN workload: non-canonical est.
            self._line(4, arrival=1.5, tokens=7),
            # serve event with a wrong-typed tokens field.
            self._line(5, arrival=1.6).replace(
                f'"tokens": {est_tokens_for(512, "interactive")}',
                '"tokens": "many"',
            ),
            # unarmed event (arrival 0): recorded pre-arming, not ours.
            self._line(6, arrival=0.0),
            self._line(7, arrival=2.0),
        ]
        # Torn tail: the final line has no newline terminator.
        p.write_text("\n".join(lines) + "\n" + self._line(8)[:20])
        reqs, report = read_recording(p)
        assert len(reqs) == 2  # seq 1 and 7 only
        assert report["torn_tail"] == 1
        assert report["skipped"] == 3  # garbage + bad est + bad type
        assert [r.arrival_s for r in reqs] == [0.0, 1.0]

    def test_empty_and_unarmed_recordings(self, tmp_path):
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        reqs, report = read_recording(p)
        assert reqs == [] and report["requests"] == 0


@pytest.mark.chaos
class TestReplayAgainstDaemon:
    """End-to-end against the real socket daemon on the mock engine."""

    def test_open_loop_schedule_fidelity_on_slow_server(
        self, monkeypatch
    ):
        """A server that takes ~100ms per debate must NOT slow the
        arrival schedule (open loop): with 8 arrivals 25ms apart, a
        closed-loop harness would stretch the schedule 4x+; the open-
        loop generator's p99 submit lateness stays under 50ms."""
        real_run = driver.run_debate

        def slow_run(payload, sched, **kw):
            time.sleep(0.1)
            return real_run(payload, sched, **kw)

        monkeypatch.setattr(driver, "run_debate", slow_run)
        reqs = [
            ReplayRequest(
                arrival_s=0.025 * i,
                tenant="t0",
                tier="interactive",
                spec_chars=256,
            )
            for i in range(8)
        ]
        res = replay_once(
            reqs,
            1.0,
            knobs=ServeKnobs(max_backlog_tokens=10**6, max_queue_depth=64),
            poll_pressure=False,
        )
        m = res.metrics
        assert m["lost"] == 0 and m["shed"] == 0
        assert m["completed"] == 8
        # The schedule span is 0.175s; the run itself takes longer
        # (slow server), but the GENERATOR stayed on time.
        assert m["schedule_lateness_p99_s"] < 0.05

    def test_smoke_sweep_zero_accepted_loss(self):
        """The tier-1 replay smoke: a small two-arm sweep completes
        with zero accepted-request loss and a bench_trend-valid
        payload (the lint_all replay-smoke stage's contract)."""
        from tools.bench_trend import validate_bench_file

        reqs = synthesize(SynthSpec(seed=0, requests=12))
        slo = SLOSpec()
        frontier = load_replay.frontier_sweep(
            reqs,
            [ServeKnobs(replicas=1), ServeKnobs(replicas=3)],
            slo,
            max_doublings=1,
            bisect_iters=0,
        )
        assert set(frontier) == {"replicas=1", "replicas=3"}
        for arm in frontier.values():
            assert arm["at_frontier"]["lost"] == 0
            assert arm["debates_per_s"] >= 0
        payload = load_replay.bench_payload(
            frontier, slo, "test", platform="cpu"
        )
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            out = Path(td) / "BENCH_capacity.json"
            out.write_text(json.dumps(payload), encoding="utf-8")
            row, problems = validate_bench_file(out)
        assert problems == [] and row is not None

    def test_recorded_roundtrip_byte_identical_at_1x(self, tmp_path):
        """The acceptance pin: replay a synthetic trace with arrivals
        armed, dump the flight recorder, RECONSTRUCT the trace from
        the dump, replay at 1× — byte-identical transcripts, because
        the canonical shape encoding makes the recorded admission
        estimates invertible."""
        events = str(tmp_path / "events.jsonl")
        reqs = synthesize(SynthSpec(seed=3, requests=10))
        knobs = ServeKnobs(max_backlog_tokens=10**6, max_queue_depth=64)
        first = replay_once(
            reqs,
            1.0,
            knobs=knobs,
            collect_transcripts=True,
            events_out=events,
            poll_pressure=False,
        )
        assert first.metrics["shed"] == 0 and first.metrics["lost"] == 0
        rebuilt, report = read_recording(events)
        assert report["requests"] == len(reqs)
        assert report["skipped"] == 0
        # The reconstruction IS the original workload: same shapes,
        # tenants, tiers (arrivals re-based to the first admission).
        assert [
            (r.tenant, r.tier, r.spec_chars) for r in rebuilt
        ] == [(r.tenant, r.tier, r.spec_chars) for r in reqs]
        second = replay_once(
            rebuilt,
            1.0,
            knobs=knobs,
            collect_transcripts=True,
            poll_pressure=False,
        )
        assert second.metrics["shed"] == 0 and second.metrics["lost"] == 0
        assert first.transcripts == second.transcripts
        assert all(t is not None for t in first.transcripts)

    def test_slo_breach_detection(self):
        m = {"lost": 0, "ttft_p95_s": 0.1, "shed_fraction": 0.0}
        assert slo_breaches(m, SLOSpec()) == []
        assert slo_breaches({**m, "lost": 1}, SLOSpec())
        assert slo_breaches({**m, "ttft_p95_s": 9.0}, SLOSpec())
        assert slo_breaches({**m, "shed_fraction": 0.5}, SLOSpec())


class TestArrivalRendering:
    """The obs_dump/trace_view satellite: armed recordings render the
    arrival offsets (@t) and the per-tenant rate summary."""

    def _serve_event(self, seq, arrival, tenant="t0"):
        return {
            "seq": seq,
            "type": "serve",
            "op": "accepted",
            "tenant": tenant,
            "tier": "interactive",
            "debate": f"d{seq:05d}",
            "index": -1,
            "reason": "",
            "tokens": 1000,
            "backlog_tokens": 1000,
            "arrival_s": arrival,
            "trace_id": "",
            "span_id": "",
        }

    def _request_event(self, seq, req_id, state, arrival=0.0):
        return {
            "seq": seq,
            "type": "request",
            "req_id": req_id,
            "state": state,
            "slot": req_id,
            "tokens": 10,
            "cached_tokens": 0,
            "arrival_s": arrival,
            "trace_id": "",
            "span_id": "",
        }

    def test_obs_dump_summary_has_tenant_rate_line(self):
        from tools.obs_dump import summarize

        events = [
            self._serve_event(1, 1.0, "t0"),
            self._serve_event(2, 1.5, "t0"),
            self._serve_event(3, 3.0, "t1"),
        ]
        text = summarize(events)
        assert "arrivals: 3 over 2.000s" in text
        assert "t0=1.0/s" in text and "t1=0.5/s" in text
        # Unarmed dumps (arrival 0) keep the old summary byte for byte.
        unarmed = [
            {**e, "arrival_s": 0.0} for e in events
        ]
        assert "arrivals:" not in summarize(unarmed)

    def test_obs_dump_request_log_leads_with_arrival_column(self):
        from tools.obs_dump import request_log

        events = [
            self._request_event(1, 0, "queued", arrival=1.25),
            self._request_event(2, 0, "finished"),
        ]
        text = request_log(events)
        assert "@   1.250s " in text.splitlines()[0]
        # Non-edge rows keep alignment without inventing an offset.
        assert text.splitlines()[1].startswith(" " * 11 + "seq")
        unarmed = request_log(
            [self._request_event(1, 0, "queued", arrival=0.0)]
        )
        assert "@" not in unarmed

    def test_obs_dump_timeline_serve_rows_show_offset(self):
        from tools.obs_dump import occupancy_timeline

        events = [
            {
                "seq": 1,
                "type": "step",
                "kind": "decode",
                "n_live": 1,
                "admission_slot": -1,
                "prefill_tokens": 0,
                "pipeline_depth": 0,
                "sync_reason": "",
                "trace_id": "",
                "span_id": "",
            },
            self._serve_event(2, 0.125),
        ]
        text = occupancy_timeline(events)
        assert "@0.125s" in text

    def test_trace_view_waterfall_head_shows_arrival(self):
        from tools.trace_view import collect_requests, render_waterfall

        def span(seq, name, phase, wall):
            return {
                "seq": seq,
                "type": "span",
                "name": name,
                "phase": phase,
                "req_id": 0,
                "slot": 0,
                "wall_s": wall,
                "trace_id": "tr0",
                "span_id": "sp0",
            }

        events = [
            self._request_event(1, 0, "queued", arrival=2.5),
            span(2, "request", "begin", 0.0),
            span(3, "prefill", "end", 0.01),
            span(4, "decode", "end", 0.02),
            span(5, "request", "end", 0.03),
        ]
        recs = collect_requests(events)
        assert recs["sp0"]["arrival_s"] == 2.5
        assert "@2.500s" in render_waterfall(recs)
