"""Checkpoint loader tests: sharded safetensors with an index file, error
messages, and the bounded-host-RAM stacking path."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine.loader import (
    CheckpointConfigError,
    _open_safetensors,
    load_hf_checkpoint,
    materialize_params,
    preflight_config,
)
from adversarial_spec_tpu.models.config import get_config


def _hf_config_json(cfg, family="llama", **overrides):
    """The config.json an HF export of ``cfg`` would carry."""
    d = {
        "model_type": family,
        "hidden_size": cfg.dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.ffn_dim,
        "vocab_size": cfg.vocab_size,
        "rope_theta": cfg.rope_theta,
        "tie_word_embeddings": cfg.tied_embeddings,
    }
    d.update(overrides)
    return d


def _write_sharded_checkpoint(tmp_path, cfg):
    """Write a tiny llama checkpoint SPLIT across two safetensors shards
    with a model.safetensors.index.json — the multi-file layout real 8B/70B
    checkpoints use."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(0)
    D, F = cfg.dim, cfg.ffn_dim
    QD = cfg.n_heads * cfg.head_dim
    KD = cfg.n_kv_heads * cfg.head_dim

    tensors = {}
    tensors["model.embed_tokens.weight"] = rng.standard_normal(
        (cfg.vocab_size, D), dtype=np.float32
    )
    tensors["model.norm.weight"] = np.ones((D,), np.float32)
    tensors["lm_head.weight"] = rng.standard_normal(
        (cfg.vocab_size, D), dtype=np.float32
    )
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        tensors[p + "input_layernorm.weight"] = np.ones((D,), np.float32)
        tensors[p + "post_attention_layernorm.weight"] = np.ones(
            (D,), np.float32
        )
        tensors[p + "self_attn.q_proj.weight"] = rng.standard_normal(
            (QD, D), dtype=np.float32
        )
        tensors[p + "self_attn.k_proj.weight"] = rng.standard_normal(
            (KD, D), dtype=np.float32
        )
        tensors[p + "self_attn.v_proj.weight"] = rng.standard_normal(
            (KD, D), dtype=np.float32
        )
        tensors[p + "self_attn.o_proj.weight"] = rng.standard_normal(
            (D, QD), dtype=np.float32
        )
        tensors[p + "mlp.gate_proj.weight"] = rng.standard_normal(
            (F, D), dtype=np.float32
        )
        tensors[p + "mlp.up_proj.weight"] = rng.standard_normal(
            (F, D), dtype=np.float32
        )
        tensors[p + "mlp.down_proj.weight"] = rng.standard_normal(
            (D, F), dtype=np.float32
        )

    names = sorted(tensors)
    half = len(names) // 2
    shards = {
        "model-00001-of-00002.safetensors": {n: tensors[n] for n in names[:half]},
        "model-00002-of-00002.safetensors": {n: tensors[n] for n in names[half:]},
    }
    weight_map = {}
    for fname, shard in shards.items():
        save_file(shard, str(tmp_path / fname))
        for n in shard:
            weight_map[n] = fname
    (tmp_path / "model.safetensors.index.json").write_text(
        json.dumps({"weight_map": weight_map})
    )
    return tensors


class TestShardedCheckpoint:
    def test_index_json_resolves_all_shards(self, tmp_path):
        cfg = get_config("llama", "tiny")
        tensors = _write_sharded_checkpoint(tmp_path, cfg)
        files = _open_safetensors(tmp_path)
        assert set(files) == set(tensors)
        assert len({f.name for f in files.values()}) == 2

    def test_load_across_shards_matches_source(self, tmp_path):
        cfg = get_config("llama", "tiny")
        tensors = _write_sharded_checkpoint(tmp_path, cfg)
        params = load_hf_checkpoint(tmp_path, cfg, "llama", dtype=jnp.float32)
        # Layer-stacked wq[0] equals the transposed per-layer source.
        np.testing.assert_allclose(
            np.asarray(params["layers"]["wq"][0]),
            tensors["model.layers.0.self_attn.q_proj.weight"].T,
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(params["lm_head"]),
            tensors["lm_head.weight"].T,
            rtol=1e-6,
        )

    def test_missing_tensor_actionable_error(self, tmp_path):
        """An index that omits tensors names the missing tensor."""
        cfg = get_config("llama", "tiny")
        _write_sharded_checkpoint(tmp_path, cfg)
        (tmp_path / "model.safetensors.index.json").write_text(
            json.dumps({"weight_map": {}})
        )
        with pytest.raises(KeyError, match="missing from checkpoint"):
            load_hf_checkpoint(tmp_path, cfg, "llama")

    def test_empty_dir_actionable_error(self, tmp_path):
        cfg = get_config("llama", "tiny")
        with pytest.raises(FileNotFoundError, match="no \\*.safetensors"):
            load_hf_checkpoint(tmp_path, cfg, "llama")


class TestPreflightConfig:
    """The loader cross-checks the checkpoint's own config.json before
    reading any tensor: a mis-registered alias must fail loudly with the
    mismatched fields named, never load into garbage logits."""

    def test_matching_config_json_loads(self, tmp_path):
        cfg = get_config("llama", "tiny")
        _write_sharded_checkpoint(tmp_path, cfg)
        (tmp_path / "config.json").write_text(
            json.dumps(_hf_config_json(cfg))
        )
        params = load_hf_checkpoint(tmp_path, cfg, "llama", dtype=jnp.float32)
        assert "embed" in params

    def test_absent_config_json_skips_check(self, tmp_path):
        cfg = get_config("llama", "tiny")
        preflight_config(tmp_path, cfg, "llama")  # no error

    def test_misregistered_family_fails_loudly(self, tmp_path):
        """Checkpoint dir holds a llama-1b-shaped config.json but the
        alias was registered as llama-tiny: every differing field is
        named and no tensor read is attempted (dir has none)."""
        tiny = get_config("llama", "tiny")
        big = get_config("llama", "1b")
        (tmp_path / "config.json").write_text(
            json.dumps(_hf_config_json(big))
        )
        with pytest.raises(CheckpointConfigError) as ei:
            load_hf_checkpoint(tmp_path, tiny, "llama")
        msg = str(ei.value)
        assert "hidden_size" in msg
        assert "num_hidden_layers" in msg
        assert "re-register" in msg

    def test_wrong_model_type_fails(self, tmp_path):
        cfg = get_config("llama", "tiny")
        (tmp_path / "config.json").write_text(
            json.dumps(_hf_config_json(cfg, family="mistral"))
        )
        with pytest.raises(CheckpointConfigError, match="model_type"):
            preflight_config(tmp_path, cfg, "llama")

    def test_rope_theta_mismatch_fails(self, tmp_path):
        """Same shapes, different rope base — the silent-garbage case the
        preflight exists for (logits plausible, positions wrong)."""
        cfg = get_config("llama", "tiny")
        (tmp_path / "config.json").write_text(
            json.dumps(_hf_config_json(cfg, rope_theta=10000.0))
        )
        with pytest.raises(CheckpointConfigError, match="rope_theta"):
            preflight_config(tmp_path, cfg, "llama")

    def test_unregistered_rope_scaling_fails(self, tmp_path):
        """Checkpoint is llama3-rope-scaled but the registered config is
        unscaled: long-context positions would silently be wrong."""
        cfg = get_config("llama", "tiny")
        (tmp_path / "config.json").write_text(
            json.dumps(
                _hf_config_json(
                    cfg,
                    rope_scaling={
                        "rope_type": "llama3",
                        "factor": 8.0,
                        "low_freq_factor": 1.0,
                        "high_freq_factor": 4.0,
                        "original_max_position_embeddings": 8192,
                    },
                )
            )
        )
        with pytest.raises(CheckpointConfigError, match="rope_scaling"):
            preflight_config(tmp_path, cfg, "llama")

    def test_tied_embeddings_mismatch_fails(self, tmp_path):
        cfg = get_config("llama", "tiny")
        (tmp_path / "config.json").write_text(
            json.dumps(_hf_config_json(cfg, tie_word_embeddings=True))
        )
        with pytest.raises(CheckpointConfigError, match="tie_word_embeddings"):
            preflight_config(tmp_path, cfg, "llama")

    def test_inert_sliding_window_accepted(self, tmp_path):
        """Qwen2 checkpoints declare sliding_window=131072 but
        use_sliding_window=false — the inert window must not trip the
        preflight against our (windowless) registered qwen2 config."""
        cfg = get_config("qwen2", "tiny")
        (tmp_path / "config.json").write_text(
            json.dumps(
                _hf_config_json(
                    cfg,
                    family="qwen2",
                    sliding_window=131072,
                    use_sliding_window=False,
                )
            )
        )
        preflight_config(tmp_path, cfg, "qwen2")  # no error

    def test_active_sliding_window_mismatch_fails(self, tmp_path):
        cfg = get_config("llama", "tiny")
        (tmp_path / "config.json").write_text(
            json.dumps(_hf_config_json(cfg, sliding_window=4096))
        )
        with pytest.raises(CheckpointConfigError, match="sliding_window"):
            preflight_config(tmp_path, cfg, "llama")

    def test_corrupt_config_json_actionable(self, tmp_path):
        cfg = get_config("llama", "tiny")
        (tmp_path / "config.json").write_text("{not json")
        with pytest.raises(CheckpointConfigError, match="unreadable"):
            preflight_config(tmp_path, cfg, "llama")

    def test_weird_typed_values_never_crash(self, tmp_path):
        """Arbitrary JSON values (strings where numbers belong, objects,
        lists) report as mismatches, never raise TypeError/ValueError."""
        import random

        cfg = get_config("llama", "tiny")
        rng = random.Random(0)
        weird = ["x", None, [], [1], {"a": 1}, "12abc", True, -3.5, 1e99]
        keys = [
            "hidden_size", "num_hidden_layers", "num_attention_heads",
            "num_key_value_heads", "intermediate_size", "vocab_size",
            "head_dim", "sliding_window", "tie_word_embeddings",
            "rope_theta", "rope_scaling", "model_type",
        ]
        for trial in range(50):
            conf = {
                k: rng.choice(weird) for k in rng.sample(keys, 5)
            }
            (tmp_path / "config.json").write_text(json.dumps(conf))
            try:
                preflight_config(tmp_path, cfg, "llama")
            except CheckpointConfigError:
                pass  # mismatch report is the correct outcome

    def test_materialize_random_is_deterministic(self):
        a, cfg_a = materialize_params("random", "llama", "tiny", seed=3)
        b, _ = materialize_params("random", "llama", "tiny", seed=3)
        np.testing.assert_array_equal(
            np.asarray(a["embed"]), np.asarray(b["embed"])
        )
        c, _ = materialize_params("random", "llama", "tiny", seed=4)
        assert not np.array_equal(np.asarray(a["embed"]), np.asarray(c["embed"]))


class TestHostRamBound:
    def test_peak_staging_is_one_stacked_param(self, tmp_path):
        """Pins the loader docstring's claim (engine/loader.py module
        doc): host-RAM staging during load is bounded by ONE stacked
        param buffer (+ one layer tensor), not the checkpoint size —
        the property that makes 70B loadable within host RAM. Measured
        with tracemalloc (numpy allocations are tracked; jax device
        buffers are not staging).

        Runs in a SUBPROCESS: inside the warm test-suite interpreter,
        jax's CPU backend may adopt numpy buffers zero-copy, keeping
        every staged buffer alive inside the returned params and
        inflating tracemalloc's peak to the checkpoint size — a fresh
        interpreter measures the loader itself, deterministically."""
        import subprocess
        import sys
        from dataclasses import replace
        from pathlib import Path

        # Large embeddings (vocab 8192) make the whole checkpoint much
        # bigger than any single staged buffer — the regime where the
        # bound matters.
        cfg = replace(
            get_config("llama", "tiny"), n_layers=8, vocab_size=8192
        )
        _write_sharded_checkpoint(tmp_path, cfg)

        # Largest single staged buffer in f32: the embed/lm_head tensors
        # ([vocab, dim]) or the stacked w_gate/w_up ([L, dim, ffn]).
        max_staged = max(
            cfg.vocab_size * cfg.dim * 4,
            cfg.n_layers * cfg.dim * cfg.ffn_dim * 4,
        )
        per_layer = (
            2 * cfg.dim * cfg.ffn_dim  # gate, up
            + cfg.ffn_dim * cfg.dim  # down
            + 2 * cfg.dim * cfg.n_heads * cfg.head_dim  # wq, wo
            + 2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim  # wk, wv
        ) * 4
        total = cfg.n_layers * per_layer + 2 * cfg.vocab_size * cfg.dim * 4

        probe = f"""
import tracemalloc
from dataclasses import replace
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from adversarial_spec_tpu.engine.loader import load_hf_checkpoint
from adversarial_spec_tpu.models.config import get_config

cfg = replace(get_config("llama", "tiny"), n_layers=8, vocab_size=8192)
tracemalloc.start()
tracemalloc.reset_peak()
params = load_hf_checkpoint({str(tmp_path)!r}, cfg, "llama", dtype=jnp.float32)
_, peak = tracemalloc.get_traced_memory()
assert params["layers"]["w_gate"].shape == (8, cfg.dim, cfg.ffn_dim)
print("PEAK", peak)
"""
        import os

        env = dict(os.environ)
        env.update(
            PYTHONPATH=str(Path(__file__).resolve().parent.parent),
            JAX_PLATFORMS="cpu",
        )
        out = subprocess.run(
            [sys.executable, "-c", probe],
            env=env,
            capture_output=True,
            text=True,
            timeout=300,  # CPU-only: safe to kill
        )
        assert out.returncode == 0, out.stdout + out.stderr
        peak = int(out.stdout.split("PEAK")[1].strip())

        # Peak numpy staging is a small constant times the largest
        # single staged buffer (buffer + one in-flight copy + slack) —
        # NOT the checkpoint size, which a read-everything loader would
        # hit (peak ≈ total ≈ 4.25x max_staged at this config). Measured
        # steady-state is ~2.4-2.9x max_staged; the 3.5x/0.75x margins
        # absorb allocator noise while still rejecting read-everything —
        # the property 70B-within-host-RAM rests on.
        assert peak < 3.5 * max_staged, (peak, max_staged)
        assert peak < 0.75 * total, (peak, total)
