"""The lockdep sanitizer (resilience/lockdep.py) and its static twin
(GL-LOCK, tools/graftlint/rules/locking.py).

The runtime side is pinned end to end: inversion detection naming both
stacks, RLock re-entry staying edge-free, ``threading.Condition`` over a
tracked lock, the hold/wait histograms landing in obs snapshots, and the
disabled path handing back raw primitives with zero bookkeeping. The
static side gets a LIVE-FIRE pin: the real ``serve/sched.py`` source is
linted as a fixture tree, once untouched (clean) and once with a real
lock acquire stripped (GL-LOCK-GUARD must fire on the now-unguarded
reads) — proving the rule catches a regression in the real code it
guards, not just in synthetic fixtures."""

from __future__ import annotations

import threading
from pathlib import Path

import pytest

from adversarial_spec_tpu import obs
from adversarial_spec_tpu.resilience import lockdep

REPO = Path(__file__).resolve().parents[1]
SCHED_PATH = REPO / "adversarial_spec_tpu" / "serve" / "sched.py"


@pytest.fixture(autouse=True)
def _armed():
    """Every test here runs with the sanitizer armed and a clean graph
    (conftest already resets; this pins enabled regardless of env)."""
    lockdep.configure(enabled=True, raise_on_violation=False)
    lockdep.reset()
    yield
    lockdep.reset()
    lockdep.configure(
        enabled=lockdep.env_enabled(), raise_on_violation=False
    )


class TestInversionDetection:
    def test_two_thread_inversion_names_both_stacks(self):
        """A->B then B->A across two (sequential) threads is THE
        violation; the message must carry the acquiring stack and the
        first-recorded opposite-direction stack."""
        a = lockdep.TrackedLock("t.A", metrics=False)
        b = lockdep.TrackedLock("t.B", metrics=False)

        def forward():
            with a:
                with b:
                    pass

        def backward():
            with b:
                with a:
                    pass

        for fn in (forward, backward):
            t = threading.Thread(target=fn)
            t.start()
            t.join(timeout=10.0)
        got = lockdep.violations()
        assert len(got) == 1
        v = got[0]
        assert v.edge == ("t.B", "t.A")
        msg = str(v)
        assert "this acquisition" in msg
        assert "opposite edge" in msg
        assert "t.A" in msg and "t.B" in msg

    def test_raise_mode_raises_and_releases_inner_lock(self):
        """--lockdep-raise semantics: the violating acquire raises AND
        leaves the just-acquired inner lock released so the process is
        not wedged by its own sanitizer."""
        lockdep.configure(raise_on_violation=True)
        a = lockdep.TrackedLock("r.A", metrics=False)
        b = lockdep.TrackedLock("r.B", metrics=False)
        with a:
            with b:
                pass
        with b:
            with pytest.raises(lockdep.LockOrderViolation):
                a.acquire()
        assert not a.locked()
        assert not b.locked()

    def test_same_name_locks_share_a_graph_node(self):
        """Two instances named identically (every ``ServeScheduler``
        instance's ``_lock``) are one node: nesting instance 1 under
        instance 2 records no self-edge and no violation."""
        a1 = lockdep.TrackedLock("s.L", metrics=False)
        a2 = lockdep.TrackedLock("s.L", metrics=False)
        with a1:
            with a2:
                pass
        assert lockdep.violations() == []
        assert "s.L" not in lockdep.order_edges().get("s.L", ())


class TestReentrancy:
    def test_rlock_reentry_records_no_edge_and_no_violation(self):
        r = lockdep.TrackedRLock("re.R", metrics=False)
        with r:
            with r:
                with r:
                    pass
        assert lockdep.violations() == []
        assert lockdep.order_edges() == {}
        assert lockdep.held_names() == ()

    def test_rlock_release_order_unwinds_cleanly(self):
        r = lockdep.TrackedRLock("re.R2", metrics=False)
        r.acquire()
        r.acquire()
        assert lockdep.held_names() == ("re.R2",)
        r.release()
        assert lockdep.held_names() == ("re.R2",)
        r.release()
        assert lockdep.held_names() == ()


class TestConditionIntegration:
    def test_condition_over_tracked_lock_wait_notify(self):
        """``threading.Condition(tracked)`` is the ServeScheduler's
        exact shape: wait releases and reacquires through the wrapper
        without corrupting the held stack or recording junk edges."""
        lk = lockdep.make_lock("cond.L", metrics=False)
        assert isinstance(lk, lockdep.TrackedLock)
        cond = threading.Condition(lk)
        fired = []

        def waiter():
            with cond:
                while not fired:
                    if not cond.wait(timeout=5.0):
                        break

        t = threading.Thread(target=waiter)
        t.start()
        with cond:
            fired.append(1)
            cond.notify_all()
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert lockdep.violations() == []
        assert lockdep.held_names() == ()


class TestMetrics:
    def test_hold_and_wait_histograms_land_in_obs_snapshot(self):
        obs.configure(enabled=True)
        lk = lockdep.make_lock("MetricsDemo._lock")
        with lk:
            pass
        snap = obs.metrics.snapshot()
        hold = snap['advspec_lock_hold_seconds{lock="MetricsDemo._lock"}']
        wait = snap['advspec_lock_wait_seconds{lock="MetricsDemo._lock"}']
        assert hold["count"] == 1
        assert wait["count"] == 1

    def test_disabled_obs_records_no_lock_metrics(self):
        """The observe gate is per-observe, not per-handle: flipping
        obs off must stop NEW observations even on a warm lock."""
        obs.configure(enabled=True)
        lk = lockdep.make_lock("GateDemo._lock")
        with lk:
            pass
        obs.reset_stats()
        obs.configure(enabled=False)
        with lk:
            pass
        snap = obs.metrics.snapshot()
        key = 'advspec_lock_hold_seconds{lock="GateDemo._lock"}'
        assert snap.get(key, {"count": 0})["count"] == 0
        obs.configure(enabled=True)


class TestDisabledPassthrough:
    def test_make_lock_disabled_returns_raw_primitives(self):
        lockdep.configure(enabled=False)
        lk = lockdep.make_lock("off.L")
        rl = lockdep.make_rlock("off.R")
        assert type(lk) is type(threading.Lock())
        assert type(rl) is type(threading.RLock())

    def test_disabled_locks_do_no_bookkeeping(self):
        lockdep.configure(enabled=False)
        a = lockdep.make_lock("off.A")
        b = lockdep.make_lock("off.B")
        with a:
            with b:
                pass
        with b:
            with a:  # a real inversion — invisible when disabled
                pass
        assert lockdep.order_edges() == {}
        assert lockdep.violations() == []


class TestSelfTest:
    def test_self_test_passes_and_leaves_no_state(self):
        before_edges = lockdep.order_edges()
        assert lockdep.self_test() == []
        assert lockdep.order_edges() == before_edges
        assert lockdep.violations() == []


class TestLiveFireGuardRule:
    """GL-LOCK-GUARD against the REAL scheduler source."""

    def _lint(self, source: str):
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources
        import tools.graftlint.rules  # noqa: F401 - registers rules

        cfg = GraftlintConfig(
            lock_thread_entries=[
                "adversarial_spec_tpu.serve.sched:"
                "ServeScheduler.pressure_snapshot",
                "adversarial_spec_tpu.serve.sched:"
                "ServeScheduler.try_admit",
            ],
        )
        return lint_sources(
            {"adversarial_spec_tpu/serve/sched.py": source},
            rules=["GL-LOCK-GUARD"],
            cfg=cfg,
        )

    def test_unmodified_sched_source_is_clean(self):
        src = SCHED_PATH.read_text(encoding="utf-8")
        assert self._lint(src) == []

    def test_stripping_a_real_acquire_is_a_finding(self):
        """Replace pressure_snapshot's ``with self._lock:`` with
        ``if True:`` (same indentation, no acquire): the guarded reads
        inside become findings on a thread-reachable path."""
        src = SCHED_PATH.read_text(encoding="utf-8")
        needle = "        with self._lock:\n            mix:"
        assert needle in src, "pressure_snapshot shape changed"
        broken = src.replace(
            needle, "        if True:\n            mix:", 1
        )
        findings = self._lint(broken)
        assert findings, "stripped acquire produced no GL-LOCK-GUARD"
        assert all(f.rule == "GL-LOCK-GUARD" for f in findings)
        assert any("pressure_snapshot" in f.message for f in findings)


@pytest.mark.chaos
class TestDeadlockHammer:
    def test_deadlock_hammer_drill_is_green(self):
        from tools import chaos_run

        failures, payload = chaos_run.run_deadlock_hammer(verbose=False)
        assert failures == []
        assert payload["edges"], "storm recorded no cross-lock edges"
        assert payload["seeded_violations"] == 1
