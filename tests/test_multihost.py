"""Two-process jax.distributed smoke test (multi-host dry story).

VERDICT r1 item 10: ``maybe_initialize_distributed`` must be a *path*,
not just a guard — the v5p-16 multi-host config should not be first
exercised on scarce hardware. This launches two real OS processes that
each call maybe_initialize_distributed() via the documented env-var
contract, build the framework's {dp,tp,sp} mesh over the GLOBAL device
set, and run a cross-process psum. Runs on CPU (2 virtual devices per
process → 4 global), so it exercises process bring-up, the coordinator
handshake, and a DCN-analog collective with zero TPUs.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent

_PROBE = """
import jax
jax.config.update("jax_platforms", "cpu")
from adversarial_spec_tpu.parallel.mesh import (
    DP,
    make_mesh,
    maybe_initialize_distributed,
)
maybe_initialize_distributed()
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

n = jax.device_count()
assert n == 4, f"expected 4 global devices, got {n}"
assert jax.process_count() == 2
mesh = make_mesh({})  # all devices on dp, spanning both processes
x = jnp.arange(n, dtype=jnp.float32)
out = shard_map(
    lambda v: jax.lax.psum(v, DP), mesh=mesh, in_specs=P(DP), out_specs=P()
)(x)
assert float(out[0]) == sum(range(n)), float(out[0])
print(f"OK proc={jax.process_index()} psum={float(out[0])}")
"""


_SPEC_PARITY = """
import jax
jax.config.update("jax_platforms", "cpu")
from adversarial_spec_tpu.parallel.mesh import (
    make_mesh,
    maybe_initialize_distributed,
)
maybe_initialize_distributed()
import jax.numpy as jnp
import numpy as np
from adversarial_spec_tpu.engine.generate import generate
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config
from adversarial_spec_tpu.parallel.sharding import shard_params

assert jax.process_count() == 2 and jax.device_count() == 4
from adversarial_spec_tpu.engine.speculative import GAMMA

cfg = get_config("llama", "tiny")
params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
prompts = [[5 + i, 7, 11 + i, 13] for i in range(4)]
# Derived from GAMMA so an ADVSPEC_GAMMA override can't gate spec off.
kw = dict(max_new_tokens=2 * GAMMA + 8, eos_ids=[], greedy=True)

# Single-device reference (plain chunked decode, no mesh, no spec).
ref = generate(params, cfg, prompts, speculative=False, **kw)

# Cross-process dp=4 mesh with speculation ON: the host-side control
# flow must only fetch replicated scalars — any np.asarray of a
# dp-sharded array raises on non-addressable shards here.
mesh = make_mesh({})
sharded = shard_params(mesh, params)
out = generate(sharded, cfg, prompts, mesh=mesh, speculative=True, **kw)

np.testing.assert_array_equal(ref.tokens, out.tokens)
assert (ref.n_generated == out.n_generated).all()
print(f"OK proc={jax.process_index()} spec-parity")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_process(probe_text, tmp_path, ok_marker, timeout=240):
    probe = tmp_path / "probe.py"
    probe.write_text(probe_text)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        # Fresh interpreters WITHOUT the parent's jax state; PYTHONPATH
        # points at the repo only (drops any site customization that
        # would redirect jax at a hardware backend).
        env.update(
            PYTHONPATH=str(REPO_ROOT),
            JAX_PLATFORMS="cpu",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(probe)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)  # CPU-only: safe to kill
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed smoke test timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"OK proc={pid} {ok_marker}" in out, out


class TestLaunchContractErrors:
    """maybe_initialize_distributed fails fast, with the missing piece
    named, on a half-set launch contract — every branch raises BEFORE
    touching jax.distributed.initialize, so these run in-process."""

    def _call(self, monkeypatch, **env):
        from adversarial_spec_tpu.parallel.mesh import (
            maybe_initialize_distributed,
        )

        for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                  "JAX_PROCESS_ID"):
            monkeypatch.delenv(k, raising=False)
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        maybe_initialize_distributed()

    def test_no_contract_is_noop(self, monkeypatch):
        self._call(monkeypatch)  # no env: plain single-process, no error

    def test_pieces_without_coordinator_fail(self, monkeypatch):
        with pytest.raises(RuntimeError, match="JAX_COORDINATOR_ADDRESS"):
            self._call(monkeypatch, JAX_NUM_PROCESSES="2")

    def test_coordinator_without_pid_fails(self, monkeypatch):
        with pytest.raises(RuntimeError, match="JAX_PROCESS_ID is not"):
            self._call(
                monkeypatch,
                JAX_COORDINATOR_ADDRESS="127.0.0.1:1",
                JAX_NUM_PROCESSES="2",
            )

    def test_coordinator_without_num_fails(self, monkeypatch):
        with pytest.raises(RuntimeError, match="JAX_NUM_PROCESSES is not"):
            self._call(
                monkeypatch,
                JAX_COORDINATOR_ADDRESS="127.0.0.1:1",
                JAX_PROCESS_ID="0",
            )

    def test_non_integer_contract_fails(self, monkeypatch):
        with pytest.raises(RuntimeError, match="must be integers"):
            self._call(
                monkeypatch,
                JAX_COORDINATOR_ADDRESS="127.0.0.1:1",
                JAX_NUM_PROCESSES="two",
                JAX_PROCESS_ID="0",
            )


@pytest.mark.slow
def test_two_process_distributed_psum(tmp_path):
    _run_two_process(_PROBE, tmp_path, "psum=6.0")


@pytest.mark.slow
def test_two_process_speculative_parity(tmp_path):
    """Speculative decode on a cross-process dp mesh matches the
    single-device greedy reference token-for-token (VERDICT r3 item 5:
    the host control flow must never fetch a non-addressable shard)."""
    _run_two_process(_SPEC_PARITY, tmp_path, "spec-parity", timeout=480)
