"""Observability subsystem: metrics registry, flight recorder, retrace
watch, scheduler/mock instrumentation, and the CLI's --metrics-out /
--events-out / perf.obs surfaces.

The load-bearing pins: (1) a mock round's Prometheus text and events
JSONL are BYTE-identical across two runs (the schema the acceptance
criteria fix), (2) the recorder ring never grows past its bound, (3)
the real scheduler emits the same event vocabulary the mock does.
"""

import io
import json

import pytest

from adversarial_spec_tpu import cli, obs
from adversarial_spec_tpu.obs import (
    BreakerEvent,
    CacheEvent,
    CompileEvent,
    FaultEvent,
    FlightRecorder,
    MetricsRegistry,
    RequestEvent,
    StepEvent,
    validate_event,
)
from adversarial_spec_tpu.obs.retrace import RetraceWatch


@pytest.fixture(autouse=True)
def _spec_off_module(monkeypatch):
    """Speculation is default-on and only multiplies the jit programs
    every batcher/engine this module compiles; its subject is
    orthogonal. Spec-on coverage (incl. SpecEvents, spec chaos fuzz,
    and the obs families) lives in tests/test_spec_batcher.py."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)



@pytest.fixture(autouse=True)
def _reset_obs():
    obs.configure(
        enabled=True,
        recorder_size=obs.DEFAULT_RECORDER_SIZE,
        events_out="",
        dump_on_fault=True,
    )
    obs.reset_stats()
    yield
    obs.configure(
        enabled=True,
        recorder_size=obs.DEFAULT_RECORDER_SIZE,
        events_out="",
        dump_on_fault=True,
    )
    obs.reset_stats()


class TestMetricsRegistry:
    def test_counter_gauge_histogram_roundtrip(self):
        reg = MetricsRegistry()
        reg.counter("advspec_x_total", seam="a").inc()
        reg.counter("advspec_x_total", seam="a").inc(2)
        reg.counter("advspec_x_total", seam="b").inc()
        reg.gauge("advspec_util").set(0.5)
        h = reg.histogram("advspec_lat_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(99.0)
        snap = reg.snapshot()
        assert snap['advspec_x_total{seam="a"}'] == 3
        assert snap['advspec_x_total{seam="b"}'] == 1
        assert snap["advspec_util"] == 0.5
        assert snap["advspec_lat_seconds"] == {
            "count": 3,
            "sum": 99.55,
            # Bucket-estimated quantiles: p50 interpolates inside the
            # (0.1, 1.0] bucket; the tail quantiles clamp to the last
            # bound (the overflow observation is past what fixed
            # buckets can resolve).
            "p50": 0.55,
            "p95": 1.0,
            "p99": 1.0,
        }

    def test_handles_are_stable_and_reset_in_place(self):
        """The resilience/interleave reset contract: an engine holding a
        metric handle keeps recording into the same object."""
        reg = MetricsRegistry()
        c = reg.counter("advspec_n_total")
        c.inc(5)
        reg.reset()
        assert reg.counter("advspec_n_total") is c
        assert c.value == 0
        c.inc()
        assert reg.snapshot()["advspec_n_total"] == 1

    def test_hot_handles_alias_registry_series(self):
        """obs.hot caches handles ONCE at import; they must be the very
        objects the registry returns for the same name+labels, and must
        survive reset() live (reset-in-place contract) — otherwise the
        hot emit sites would record into orphaned series."""
        assert obs.hot.ttft is obs.metrics.histogram("advspec_ttft_seconds")
        assert obs.hot.req_finished is obs.metrics.counter(
            "advspec_requests_total", outcome="finished"
        )
        obs.metrics.reset()
        obs.hot.req_finished.inc()
        assert (
            obs.metrics.snapshot()['advspec_requests_total{outcome="finished"}']
            == 1
        )
        # Label-dynamic families cache per label, same aliasing rule.
        assert obs.hot.sync("fault") is obs.metrics.counter(
            "advspec_host_syncs_total", reason="fault"
        )
        assert obs.hot.sync("fault") is obs.hot.sync("fault")

    def test_kind_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("advspec_n_total")
        with pytest.raises(ValueError):
            reg.gauge("advspec_n_total")

    def test_prometheus_exposition_schema(self):
        """Schema pin: TYPE lines, labeled series, cumulative histogram
        buckets ending at +Inf, _sum/_count — and integral floats render
        as integers (byte-stable formatting)."""
        reg = MetricsRegistry()
        reg.counter("advspec_x_total", help="things", seam="a").inc(3)
        reg.histogram("advspec_lat_seconds", buckets=(0.5, 1.0)).observe(0.7)
        text = reg.render_prometheus()
        assert "# HELP advspec_x_total things\n" in text
        assert "# TYPE advspec_x_total counter\n" in text
        assert 'advspec_x_total{seam="a"} 3\n' in text
        assert "# TYPE advspec_lat_seconds histogram\n" in text
        assert 'advspec_lat_seconds_bucket{le="0.5"} 0\n' in text
        assert 'advspec_lat_seconds_bucket{le="1"} 1\n' in text
        assert 'advspec_lat_seconds_bucket{le="+Inf"} 1\n' in text
        assert "advspec_lat_seconds_sum 0.7\n" in text
        assert "advspec_lat_seconds_count 1\n" in text
        # Quantile estimate lines ride along after _count — ONE
        # implementation (Histogram.quantile) feeds snapshot(),
        # render_prometheus(), and every harness percentile.
        assert "advspec_lat_seconds_p50 0.75\n" in text
        assert "advspec_lat_seconds_p95 0.975\n" in text
        assert "advspec_lat_seconds_p99 0.995\n" in text
        # Deterministic: same registry renders the same bytes.
        assert text == reg.render_prometheus()

    def test_percentile_exact_nearest_rank(self):
        """The shared sample-percentile (obs.metrics.percentile): exact
        nearest-rank pins on a known sample — the SLO gate, bench.py,
        and load_replay all report through this one implementation."""
        from adversarial_spec_tpu.obs.metrics import percentile

        xs = list(range(1, 101))  # 1..100
        assert percentile(xs, 0.50) == 50
        assert percentile(xs, 0.95) == 95
        assert percentile(xs, 0.99) == 99
        assert percentile(xs, 1.0) == 100
        assert percentile(xs, 0.0) == 1
        assert percentile([7.5], 0.99) == 7.5
        assert percentile([], 0.99) == 0.0
        # Unsorted input: percentile sorts a copy, never mutates.
        ys = [3.0, 1.0, 2.0]
        assert percentile(ys, 0.5) == 2.0
        assert ys == [3.0, 1.0, 2.0]

    def test_histogram_quantile_vs_exact_percentiles(self):
        """Unit pin: bucket-estimated quantiles track exact percentiles
        on a known sample to within one bucket width (the resolution a
        fixed-bucket histogram can promise) and clamp to the last bound
        beyond it."""
        from adversarial_spec_tpu.obs.metrics import (
            Histogram,
            percentile,
        )

        buckets = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0)
        h = Histogram(buckets=buckets)
        samples = [0.001 * i for i in range(1, 200)]  # 1ms..199ms
        for v in samples:
            h.observe(v)
        for q in (0.5, 0.95, 0.99):
            exact = percentile(samples, q)
            est = h.quantile(q)
            # The estimate lands in the same bucket as the exact value.
            width = max(
                b - a for a, b in zip((0.0,) + buckets, buckets)
            )
            assert abs(est - exact) <= width
        assert Histogram(buckets=buckets).quantile(0.99) == 0.0
        h2 = Histogram(buckets=(1.0, 2.0))
        h2.observe(50.0)  # beyond the last bound: clamps, never lies up
        assert h2.quantile(0.99) == 2.0


class TestFlightRecorder:
    def test_ring_is_bounded_and_counts_drops(self):
        r = FlightRecorder(size=4)
        for i in range(10):
            r.append(RequestEvent(req_id=i, state="queued"))
        assert len(r) == 4
        assert r.seq == 10
        assert r.dropped == 6
        # The LAST 4 events survive, in order.
        assert [e["req_id"] for e in r.events()] == [6, 7, 8, 9]
        assert [e["seq"] for e in r.events()] == [7, 8, 9, 10]

    def test_every_event_type_validates(self):
        r = FlightRecorder(size=16)
        for ev in (
            StepEvent(kind="fused", n_live=2, sync_reason="depth_fetch"),
            RequestEvent(req_id=1, state="finished", tokens=3),
            FaultEvent(seam="kv_alloc", kind="oom", slot=1),
            BreakerEvent(model="m", frm="closed", to="open"),
            CacheEvent(op="lookup", matched_tokens=64, hit=True),
            CompileEvent(program="decode", key="(4,)", n_compiles=1),
        ):
            r.append(ev)
        for line in r.to_jsonl().splitlines():
            assert validate_event(json.loads(line)) == []

    def test_validate_rejects_bad_lines(self):
        assert validate_event({"type": "nope"})  # unknown type
        assert validate_event(
            {"seq": 1, "type": "request", "req_id": "x"}
        )  # wrong type + missing fields
        good = {
            "seq": 1,
            "type": "request",
            "req_id": 0,
            "state": "queued",
            "slot": -1,
            "tokens": 0,
            "cached_tokens": 0,
            "arrival_s": 0.0,
            "trace_id": "",
            "span_id": "",
        }
        assert validate_event(good) == []
        assert validate_event({**good, "state": "exploded"})  # bad state
        assert validate_event({**good, "extra": 1})  # unknown field
        # arrival_s is a schema field like any other: int is an
        # acceptable float, a string is not.
        assert validate_event({**good, "arrival_s": 2}) == []
        assert validate_event({**good, "arrival_s": "soon"})
        # Trace ids are schema fields like any other: wrong type and
        # missing both reject.
        assert validate_event({**good, "trace_id": 7})
        missing = dict(good)
        del missing["span_id"]
        assert validate_event(missing)

    def test_dump_jsonl_atomic_write(self, tmp_path):
        r = FlightRecorder(size=4)
        r.append(StepEvent())
        out = tmp_path / "ev.jsonl"
        assert r.dump_jsonl(str(out)) == 1
        assert out.read_text().count("\n") == 1
        assert not (tmp_path / "ev.jsonl.tmp").exists()

    def test_shrink_resize_counts_aged_out_events_as_dropped(self):
        """buffered + dropped == recorded must survive a shrink: the
        events a smaller ring ages out are drops like any other."""
        r = FlightRecorder(size=8)
        for i in range(6):
            r.append(RequestEvent(req_id=i, state="queued"))
        r.resize(2)
        assert len(r) == 2
        assert r.dropped == 4
        assert len(r) + r.dropped == r.seq
        assert [e["req_id"] for e in r.events()] == [4, 5]

    def test_disabled_recorder_is_inert(self):
        r = FlightRecorder(size=4, enabled=False)
        r.append(StepEvent())
        assert len(r) == 0 and r.seq == 0


class TestRetraceWatch:
    def test_new_key_is_an_expected_compile(self):
        events = []
        w = RetraceWatch(emit=events.append)
        assert w.observe("decode", (4, True)) is True
        assert w.observe("decode", (4, True)) is False  # seen: no compile
        assert w.observe("decode", (8, True)) is True  # new shape
        snap = w.snapshot()
        assert snap["programs"]["decode"]["compiles"] == 2
        assert snap["programs"]["decode"]["distinct_keys"] == 2
        assert snap["programs"]["decode"]["dispatches"] == 3
        assert snap["unexpected_recompiles"] == 0
        assert all(not e.unexpected for e in events)

    def test_cache_size_growth_on_seen_key_is_unexpected(self):
        """The silent-100x-slowdown case: the host key says 'compiled
        already' but the trace cache grew — flagged, not swallowed."""

        class FakeJitted:
            sizes = iter([1, 2])

            def _cache_size(self):
                return next(self.sizes)

        fn = FakeJitted()
        events = []
        w = RetraceWatch(emit=events.append)
        assert w.observe("decode", (4,), fn=fn) is True  # first compile
        assert w.observe("decode", (4,), fn=fn) is True  # cache grew!
        snap = w.snapshot()
        assert snap["programs"]["decode"]["unexpected_recompiles"] == 1
        assert snap["unexpected_recompiles"] == 1
        assert [e.unexpected for e in events] == [False, True]

    def test_reset_keeps_baselines_clear_forgets_them(self):
        """Per-invocation reset() zeroes COUNTS but keeps seen keys and
        the cache-size baseline: the jit caches live for the process, so
        round 2's first warm dispatch must not report a fresh compile.
        clear() is the cold-start variant (test isolation)."""
        w = RetraceWatch()
        assert w.observe("decode", (4,)) is True
        w.reset()
        assert w.observe("decode", (4,)) is False  # warm: same key
        snap = w.snapshot()
        assert snap["programs"]["decode"]["compiles"] == 0
        assert snap["programs"]["decode"]["dispatches"] == 1
        w.clear()
        assert w.observe("decode", (4,)) is True  # cold start again

    def test_cache_size_steady_suppresses_false_positive(self):
        """A repeated key with a steady cache size is NOT a compile even
        though the probe is available."""

        class FakeJitted:
            def _cache_size(self):
                return 1

        w = RetraceWatch()
        assert w.observe("decode", (4,), fn=FakeJitted()) is True
        assert w.observe("decode", (4,), fn=FakeJitted()) is False


class TestSchedulerInstrumentation:
    @pytest.fixture(scope="class")
    def tiny_model(self):
        import jax
        import jax.numpy as jnp

        from adversarial_spec_tpu.models import transformer as T
        from adversarial_spec_tpu.models.config import get_config

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        return params, cfg

    def _drain(self, params, cfg, **kw):
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=8, chunk=4, **kw
        )
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5, 9], max_new_tokens=6))
        b.submit(SchedRequest(req_id=1, prompt_ids=[2, 6], max_new_tokens=6))
        return b.run_all()

    def test_drain_emits_full_lifecycle_and_steps(self, tiny_model):
        params, cfg = tiny_model
        obs.reset_stats()
        results = self._drain(params, cfg)
        assert len(results) == 2
        events = obs.recorder.events()
        for line in obs.recorder.to_jsonl().splitlines():
            assert validate_event(json.loads(line)) == []
        reqs = [e for e in events if e["type"] == "request"]
        for rid in (0, 1):
            states = [e["state"] for e in reqs if e["req_id"] == rid]
            # queued → admitted → ... → decode → finished, in order.
            assert states[0] == "queued"
            assert "admitted" in states and "decode" in states
            assert states[-1] == "finished"
            assert states.index("admitted") < states.index("decode")
        steps = [e for e in events if e["type"] == "step"]
        assert steps, "drive loop emitted no StepEvents"
        # Metrics: TTFT observed once per admission, steps timed, pool
        # utilization gauge live, sanctioned syncs labeled.
        snap = obs.metrics.snapshot()
        assert snap["advspec_ttft_seconds"]["count"] == 2
        assert snap["advspec_step_wall_seconds"]["count"] >= 1
        assert "advspec_page_pool_utilization" in snap
        assert (
            snap['advspec_requests_total{outcome="finished"}'] == 2
        )
        assert any(
            k.startswith("advspec_host_syncs_total") for k in snap
        )

    def test_retrace_watch_sees_scheduler_programs(self, tiny_model):
        params, cfg = tiny_model
        obs.reset_stats()
        self._drain(params, cfg)
        snap = obs.retrace.snapshot()
        assert "prefill_chunk" in snap["programs"]
        assert snap["programs"]["prefill_chunk"]["compiles"] >= 1
        # Pow2 chunking bounds the shapes: nothing unexpected.
        assert snap["unexpected_recompiles"] == 0

    def test_legacy_loop_emits_same_schema(self, tiny_model):
        params, cfg = tiny_model
        obs.reset_stats()
        self._drain(params, cfg, interleave=False)
        events = obs.recorder.events()
        kinds = {e["type"] for e in events}
        assert {"request", "step"} <= kinds
        syncs = obs.snapshot()["host_syncs"]
        assert "legacy_step" in syncs

    def test_disabled_obs_records_nothing(self, tiny_model):
        params, cfg = tiny_model
        obs.configure(enabled=False)
        obs.reset_stats()
        results = self._drain(params, cfg)
        assert len(results) == 2
        assert len(obs.recorder) == 0
        # Families registered by earlier (enabled) drains survive reset
        # as zeroed series; disabled means no NEW observations land.
        for key, value in obs.metrics.snapshot().items():
            if isinstance(value, dict):
                assert value["count"] == 0, key
            else:
                assert value == 0, key


class TestCliObs:
    def _run(self, tmp_path, tag):
        from adversarial_spec_tpu.engine.dispatch import _ENGINE_CACHE

        _ENGINE_CACHE.pop("mock", None)  # fresh engine: fresh mock cache
        m = tmp_path / f"metrics-{tag}.prom"
        e = tmp_path / f"events-{tag}.jsonl"
        import sys

        stdin0 = sys.stdin
        sys.stdin = io.StringIO("# Spec body\n\nA paragraph.")
        try:
            code = cli.main(
                [
                    "critique",
                    "--models",
                    "mock://critic,mock://agree",
                    "--json",
                    "--metrics-out",
                    str(m),
                    "--events-out",
                    str(e),
                ]
            )
        finally:
            sys.stdin = stdin0
        assert code == 0
        return m.read_bytes(), e.read_bytes()

    def test_mock_round_outputs_are_byte_deterministic(
        self, tmp_path, capsys
    ):
        """Acceptance pin: a mock debate round with --metrics-out /
        --events-out produces a Prometheus file and a JSONL stream that
        are byte-identical across two runs on CPU."""
        m1, e1 = self._run(tmp_path, "a")
        capsys.readouterr()
        m2, e2 = self._run(tmp_path, "b")

        def _drop_wallclock(blob: bytes) -> bytes:
            # The lockdep sanitizer's hold/wait histograms (armed
            # suite-wide by conftest) measure real wall time on real
            # lock acquisitions — the one telemetry family that is
            # wall-clock by definition and cannot be byte-reproducible.
            # Everything else in the file stays pinned byte-for-byte.
            return b"\n".join(
                ln
                for ln in blob.splitlines()
                if b"advspec_lock_hold_seconds" not in ln
                and b"advspec_lock_wait_seconds" not in ln
            )

        assert _drop_wallclock(m1) == _drop_wallclock(m2)
        assert e1 == e2
        # Schema-pinned content, not just determinism:
        text = m1.decode()
        for family in (
            "advspec_engine_chat_requests_total",
            "advspec_ttft_seconds_bucket",
            "advspec_prefill_chunk_wall_seconds_sum",
            "advspec_requests_total",
        ):
            assert family in text, family
        for line in e1.decode().splitlines():
            assert validate_event(json.loads(line)) == []

    def test_perf_obs_block_and_flag_plumbing(self, monkeypatch, capsys):
        monkeypatch.setattr("sys.stdin", io.StringIO("# Spec"))
        code = cli.main(
            [
                "critique",
                "--models",
                "mock://critic",
                "--json",
                "--flight-recorder-size",
                "64",
            ]
        )
        out, _ = capsys.readouterr()
        assert code == 0
        perf = json.loads(out)["perf"]
        assert perf["obs"]["enabled"] is True
        assert perf["obs"]["recorder"]["size"] == 64
        assert perf["obs"]["events_by_type"]["request"] >= 5
        assert perf["obs"]["retrace"]["unexpected_recompiles"] == 0
        # The merged debate-layer spans ride the same report.
        assert "debate/engine_chat" in perf["spans"]
        assert perf["span_tree"]["debate"]["count"] >= 1

    def test_obs_flags_do_not_leak_across_invocations(
        self, monkeypatch, capsys
    ):
        """One invocation = one round: a --no-obs (or shrunken ring)
        round must not bleed into the next flagless invocation — every
        knob re-resolves to flag-else-env-default."""
        monkeypatch.setattr("sys.stdin", io.StringIO("# Spec"))
        assert (
            cli.main(
                [
                    "critique", "--models", "mock://critic", "--json",
                    "--no-obs", "--flight-recorder-size", "16",
                ]
            )
            == 0
        )
        capsys.readouterr()
        monkeypatch.setattr("sys.stdin", io.StringIO("# Spec"))
        assert (
            cli.main(["critique", "--models", "mock://critic", "--json"])
            == 0
        )
        out, _ = capsys.readouterr()
        perf = json.loads(out)["perf"]
        assert perf["obs"]["enabled"] is True
        assert perf["obs"]["recorder"]["size"] == obs.DEFAULT_RECORDER_SIZE
        assert perf["obs"]["recorder"]["recorded"] > 0

    def test_fault_autodump_goes_to_trigger_sibling(self, tmp_path):
        """autodump writes <stem>.<trigger>.jsonl next to events_out so
        the end-of-round dump can never clobber the fault snapshot."""
        obs.configure(events_out=str(tmp_path / "ev.jsonl"))
        obs.emit(StepEvent(kind="decode"))
        path = obs.autodump("fault")
        assert path == str(tmp_path / "ev.fault.jsonl")
        assert (tmp_path / "ev.fault.jsonl").exists()
        assert obs.autodump_path("timeout") == str(
            tmp_path / "ev.timeout.jsonl"
        )
        # Unarmed: no dump.
        obs.configure(events_out="")
        assert obs.autodump("fault") is None

    def test_no_obs_disables_everything(self, monkeypatch, capsys):
        monkeypatch.setattr("sys.stdin", io.StringIO("# Spec"))
        code = cli.main(
            ["critique", "--models", "mock://critic", "--json", "--no-obs"]
        )
        out, _ = capsys.readouterr()
        assert code == 0
        perf = json.loads(out)["perf"]
        assert perf["obs"]["enabled"] is False
        assert perf["obs"]["recorder"]["recorded"] == 0
        assert perf["obs"]["events_by_type"] == {}


class TestBreakerEvents:
    def test_transitions_emit_events_and_metrics(self):
        from adversarial_spec_tpu.resilience.breaker import (
            OPEN,
            BreakerRegistry,
        )
        from adversarial_spec_tpu.resilience.faults import FaultKind

        obs.reset_stats()
        clock = [0.0]
        reg = BreakerRegistry(
            threshold=1, cooldown_s=5.0, clock=lambda: clock[0]
        )
        reg.record("tpu://m", ok=False, kind=FaultKind.OOM)
        assert reg.breaker("tpu://m").state == OPEN
        clock[0] = 5.0
        assert reg.allow("tpu://m")  # half-open probe
        reg.record("tpu://m", ok=True)  # closes
        transitions = [
            (e["frm"], e["to"])
            for e in obs.recorder.events()
            if e["type"] == "breaker" and e["model"] == "tpu://m"
        ]
        assert transitions == [
            ("closed", "open"),
            ("open", "half_open"),
            ("half_open", "closed"),
        ]
        snap = obs.metrics.snapshot()
        assert snap['advspec_breaker_transitions_total{to="open"}'] == 1
        assert snap['advspec_breaker_transitions_total{to="closed"}'] == 1


class TestHandoffTelemetry:
    """Disaggregation telemetry (fleet/handoff.py): ship/prefetch
    SwapEvents validate against the schema and the handoff ledger's
    surgery updates the counter + latency histogram exactly once."""

    def test_ship_and_prefetch_swap_events_validate(self):
        from adversarial_spec_tpu.obs.events import SwapEvent

        r = FlightRecorder(size=8)
        r.append(SwapEvent(op="ship", tier="disk", blocks=4, slot=0))
        r.append(SwapEvent(op="prefetch", tier="disk", blocks=4))
        for line in r.to_jsonl().splitlines():
            assert validate_event(json.loads(line)) == []
        bad = json.loads(r.to_jsonl().splitlines()[0])
        bad["op"] = "teleport"
        assert validate_event(bad)  # unknown swap op rejects

    def test_surgery_updates_counter_and_histogram_once(self):
        from adversarial_spec_tpu import fleet as fleet_mod
        from adversarial_spec_tpu import obs as obs_mod
        from adversarial_spec_tpu.fleet.handoff import HandoffLedger

        obs_mod.configure(enabled=True)
        fleet_mod.reset_stats()
        led = HandoffLedger(stats=fleet_mod.stats)
        led.begin("k", "r0", "r1")
        led.note_published("k", ["c1"], blocks=1)
        led._finish_adopt("k")
        led._finish_adopt("k")  # idempotent: no double count
        led.begin("k2", "r0", "r1")
        led._degrade("k2", "store_miss")
        snap = obs_mod.metrics.snapshot()
        assert snap['advspec_kv_handoff_total{outcome="adopted"}'] == 1
        assert snap['advspec_kv_handoff_total{outcome="degraded"}'] == 1
        assert snap["advspec_kv_handoff_seconds"]["count"] == 2
