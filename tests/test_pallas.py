"""Pallas kernel tests under interpret mode (CPU) against jnp references,
plus end-to-end decode parity when the fused kernel is routed into the
generation loop."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine.generate import generate
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config
from adversarial_spec_tpu.ops.pallas_decode import decode_attention
from adversarial_spec_tpu.ops.pallas_paged import paged_decode_attention


def test_pick_block_t_refuses_indivisible_T():
    """No silent [Hkv, T, D] VMEM-exploding fallback for direct callers
    with a non-8-multiple cache length (ADVICE r3)."""
    from adversarial_spec_tpu.ops.pallas_decode import _pick_block_t

    assert _pick_block_t(1280, 8, 64, 2) in (512, 256, 128)
    with pytest.raises(ValueError, match="no block_t divisor"):
        _pick_block_t(1283, 8, 64, 2)


def _dense_ref(q, k, v, bounds, attn_softcap=0.0):
    B, Hq, D = q.shape
    Hkv, T_ = k.shape[1], k.shape[2]
    g = Hq // Hkv
    qg = q.reshape(B, Hkv, g, D)
    s = jnp.einsum("bhgd,bhtd->bhgt", qg, k) / math.sqrt(D)
    if attn_softcap > 0:
        s = jnp.tanh(s / attn_softcap) * attn_softcap
    slot = jnp.arange(T_)
    valid = (slot[None, :] >= bounds[:, 0:1]) & (slot[None, :] < bounds[:, 1:2])
    s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhgt,bhtd->bhgd", p, v).reshape(B, Hq, D)


class TestDecodeKernel:
    def _rand(self, B=3, Hq=8, Hkv=2, D=64, T_=512, dtype=jnp.float32):
        ks = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(ks[0], (B, Hq, D), dtype)
        k = jax.random.normal(ks[1], (B, Hkv, T_, D), dtype)
        v = jax.random.normal(ks[2], (B, Hkv, T_, D), dtype)
        return q, k, v

    def test_matches_dense(self):
        q, k, v = self._rand()
        bounds = jnp.array([[0, 100], [37, 412], [5, 6]], jnp.int32)
        out = decode_attention(q, k, v, bounds, interpret=True)
        ref = _dense_ref(q, k, v, bounds)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_softcap(self):
        q, k, v = self._rand(T_=256)
        bounds = jnp.array([[0, 256], [0, 128], [10, 200]], jnp.int32)
        out = decode_attention(q, k, v, bounds, attn_softcap=50.0, interpret=True)
        ref = _dense_ref(q, k, v, bounds, attn_softcap=50.0)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_mha_no_gqa(self):
        q, k, v = self._rand(Hq=4, Hkv=4, T_=256)
        bounds = jnp.array([[0, 256], [0, 10], [100, 256]], jnp.int32)
        out = decode_attention(q, k, v, bounds, interpret=True)
        ref = _dense_ref(q, k, v, bounds)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_single_valid_slot(self):
        """end-start == 1: softmax over one key must return exactly v."""
        q, k, v = self._rand(B=1, T_=256)
        bounds = jnp.array([[17, 18]], jnp.int32)
        out = decode_attention(q, k, v, bounds, interpret=True)
        g = 8 // 2
        expect = jnp.repeat(v[:, :, 17], g, axis=1).reshape(1, 8, 64)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), rtol=1e-5, atol=1e-5
        )

    def test_non_block_aligned_window(self):
        """Bounds crossing block_t tile boundaries mask correctly."""
        q, k, v = self._rand(B=1, T_=512)
        bounds = jnp.array([[250, 270]], jnp.int32)  # spans block edge 256
        out = decode_attention(q, k, v, bounds, interpret=True)
        ref = _dense_ref(q, k, v, bounds)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


class TestPagedKernel:
    def test_matches_gathered_dense(self):
        B, Hq, Hkv, D = 2, 8, 2, 64
        page_size, n_pages, P = 16, 32, 8
        ks = jax.random.split(jax.random.key(1), 3)
        q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
        kp = jax.random.normal(ks[1], (n_pages, Hkv, page_size, D), jnp.float32)
        vp = jax.random.normal(ks[2], (n_pages, Hkv, page_size, D), jnp.float32)
        table = np.full((B, P), -1, np.int32)
        table[0, :3] = [3, 7, 1]
        table[1, 0] = 5
        bounds = jnp.array([[2, 40], [0, 9]], jnp.int32)

        out = paged_decode_attention(
            q, kp, vp, jnp.asarray(table), bounds, interpret=True
        )

        for b in range(B):
            pages = [p for p in table[b] if p > 0]
            k = jnp.concatenate([kp[p] for p in pages], 1)[None]
            v = jnp.concatenate([vp[p] for p in pages], 1)[None]
            ref = _dense_ref(q[b : b + 1], k, v, bounds[b : b + 1])
            np.testing.assert_allclose(
                np.asarray(out[b]), np.asarray(ref[0]), rtol=2e-5, atol=2e-5
            )

    def test_unmapped_rows_after_first_page(self):
        """A row using 1 of 8 table slots must ignore the -1 slots."""
        B, Hq, Hkv, D = 1, 4, 2, 64
        page_size, n_pages, P = 8, 4, 8
        ks = jax.random.split(jax.random.key(2), 3)
        q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
        kp = jax.random.normal(ks[1], (n_pages, Hkv, page_size, D), jnp.float32)
        vp = jax.random.normal(ks[2], (n_pages, Hkv, page_size, D), jnp.float32)
        table = np.full((B, P), -1, np.int32)
        table[0, 0] = 2
        bounds = jnp.array([[0, 8]], jnp.int32)
        out = paged_decode_attention(
            q, kp, vp, jnp.asarray(table), bounds, interpret=True
        )
        ref = _dense_ref(q, kp[2][None], vp[2][None], bounds)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_trash_page_zero_is_masked(self):
        """Physical page 0 is the reserved trash page (callers shift real
        ids +1): a table entry of 0 must contribute nothing, even when
        bounds would otherwise admit its slots. Kills a '> 0' → '>= 0'
        regression that every other case in this class would miss (their
        tables never contain 0)."""
        B, Hq, Hkv, D = 1, 4, 2, 64
        page_size, n_pages, P = 8, 4, 4
        ks = jax.random.split(jax.random.key(3), 3)
        q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
        kp = jax.random.normal(ks[1], (n_pages, Hkv, page_size, D), jnp.float32)
        vp = jax.random.normal(ks[2], (n_pages, Hkv, page_size, D), jnp.float32)
        # Logical page 0 → physical 2 (real), logical page 1 → physical 0
        # (trash). Bounds cover both pages' slots.
        table = np.array([[2, 0, 0, 0]], np.int32)
        bounds = jnp.array([[0, 16]], jnp.int32)
        out = paged_decode_attention(
            q, kp, vp, jnp.asarray(table), bounds, interpret=True
        )
        # Reference attends ONLY to physical page 2's slots.
        ref = _dense_ref(q, kp[2][None], vp[2][None], jnp.array([[0, 8]]))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


class TestPallasInGenerate:
    @pytest.mark.parametrize("family", ["llama", "gemma2", "mistral"])
    def test_generate_parity_with_jnp_path(self, family):
        """Routing decode through the fused kernel must not change greedy
        tokens. Windowed families run with sliding_window=8 so the window
        start actually exceeds the pad boundary during decode (prompts pad
        to bucket 128, so cache_index - 8 + 1 > pad_len from the first
        decode steps) — otherwise the windowed and global paths would
        compute identical bounds and window bugs would pass unnoticed."""
        from dataclasses import replace

        cfg = get_config(family, "tiny")
        if cfg.sliding_window > 0:
            cfg = replace(cfg, sliding_window=8)
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3] * 4, [2, 6] * 5]
        # speculative=False: these tests target the shared-slot single-
        # query decode loop (decode_chunk_steps); the MQ/spec path has
        # its own parity tests in TestMultiQueryKernel.
        kw = dict(
            max_new_tokens=12, eos_ids=[], greedy=True, speculative=False
        )
        ref = generate(params, cfg, prompts, use_pallas_decode=False, **kw)
        out = generate(params, cfg, prompts, use_pallas_decode=True, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)

    def test_window_actually_truncates_in_this_setup(self):
        """Guard for the test above: with window=8 the pallas bounds start
        must differ between windowed and unwindowed configs (i.e. the
        window path is genuinely exercised, not vacuously equal)."""
        from dataclasses import replace

        cfg = get_config("mistral", "tiny")
        cfg_w = replace(cfg, sliding_window=8)
        cfg_g = replace(cfg, sliding_window=0)
        params = T.init_params(jax.random.key(0), cfg_w, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3] * 4]
        kw = dict(
            max_new_tokens=12, eos_ids=[], greedy=True, speculative=False
        )
        out_w = generate(params, cfg_w, prompts, use_pallas_decode=True, **kw)
        out_g = generate(params, cfg_g, prompts, use_pallas_decode=True, **kw)
        assert not np.array_equal(out_w.tokens, out_g.tokens)


class TestShardedPallasDecode:
    """decode_attention_tp: the fused kernel under shard_map (dp×tp).

    VERDICT r1 item 2 — BASELINE configs 3-5 decode through Pallas instead
    of the jnp fallback. Parity on the virtual 8-device mesh is the
    correctness bar; interpret mode stands in for the Mosaic compile.
    """

    @pytest.fixture(autouse=True)
    def _needs_8_devices(self):
        if len(jax.devices()) < 8:
            pytest.skip("requires 8 virtual devices")

    def test_kernel_parity_on_mesh(self):
        from adversarial_spec_tpu.ops.pallas_decode import (
            decode_attention,
            decode_attention_tp,
        )
        from adversarial_spec_tpu.parallel.mesh import make_mesh

        B, Hq, Hkv, D, T_ = 4, 8, 2, 64, 256
        ks = jax.random.split(jax.random.key(7), 3)
        q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, Hkv, T_, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, Hkv, T_, D), jnp.float32)
        bounds = jnp.array(
            [[0, 256], [3, 100], [100, 256], [17, 18]], jnp.int32
        )
        ref = decode_attention(q, k, v, bounds, interpret=True)
        mesh = make_mesh({"dp": 4, "tp": 2})
        with mesh:
            out = decode_attention_tp(
                q, k, v, bounds, mesh, interpret=True
            )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.parametrize("mesh_spec", [{"tp": 2}, {"dp": 4, "tp": 2}])
    def test_generate_parity_sharded_kernel_vs_jnp(self, mesh_spec):
        """Greedy decode through the shard_mapped kernel must reproduce
        the single-device jnp tokens on dp×tp meshes."""
        from adversarial_spec_tpu.engine.generate import generate
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        cfg = get_config("llama", "tiny")  # n_kv_heads=2 — tp=2 divides
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3], [2, 6], [8, 8, 8], [4]]
        kw = dict(max_new_tokens=6, eos_ids=[], greedy=True)

        ref = generate(params, cfg, prompts, use_pallas_decode=False, **kw)
        mesh = make_mesh(mesh_spec)
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=True, speculative=False, **kw,
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)


class TestInt8KernelTiles:
    """int8 KV dequant inside the fused kernel tiles (VERDICT r1 item 4):
    the int8 cache and the Pallas kernel are no longer mutually
    exclusive."""

    def test_kernel_matches_dequant_dense(self):
        B, Hq, Hkv, D, T_ = 2, 8, 2, 64, 256
        ks = jax.random.split(jax.random.key(9), 3)
        q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, Hkv, T_, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, Hkv, T_, D), jnp.float32)
        # Quantize exactly as the cache does (per-token-head symmetric).
        amax = jnp.max(jnp.abs(k), axis=-1, keepdims=True)
        ksc = jnp.maximum(amax, 1e-8) / 127.0
        k8 = jnp.clip(jnp.round(k / ksc), -127, 127).astype(jnp.int8)
        amax = jnp.max(jnp.abs(v), axis=-1, keepdims=True)
        vsc = jnp.maximum(amax, 1e-8) / 127.0
        v8 = jnp.clip(jnp.round(v / vsc), -127, 127).astype(jnp.int8)
        bounds = jnp.array([[0, 200], [37, 256]], jnp.int32)

        out = decode_attention(
            q, k8, v8, bounds, interpret=True, k_scale=ksc, v_scale=vsc
        )
        # Reference: dense attention over the DEQUANTIZED cache.
        ref = _dense_ref(q, k8 * ksc, v8 * vsc, bounds)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_generate_int8_pallas_matches_int8_jnp(self):
        """Greedy tokens through (int8 cache + fused kernel) must equal
        (int8 cache + jnp path) — same quantization, different attention
        implementation."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[3, 7, 11, 15], [2, 4]]
        kw = dict(
            max_new_tokens=8, eos_ids=[], greedy=True,
            kv_dtype="int8", speculative=False,
        )
        jnp_path = generate(params, cfg, prompts, use_pallas_decode=False, **kw)
        kern = generate(params, cfg, prompts, use_pallas_decode=True, **kw)
        np.testing.assert_array_equal(jnp_path.tokens, kern.tokens)

    def test_generate_int8_on_mesh(self):
        """int8 KV + sharded fused kernel on a dp×tp mesh."""
        if len(jax.devices()) < 8:
            pytest.skip("requires 8 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3], [2, 6], [8, 8, 8], [4]]
        kw = dict(
            max_new_tokens=6, eos_ids=[], greedy=True,
            kv_dtype="int8", speculative=False,
        )
        ref = generate(params, cfg, prompts, use_pallas_decode=False, **kw)
        mesh = make_mesh({"dp": 4, "tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=True, **kw,
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)


class TestMultiQueryKernel:
    """decode_attention_mq: γ+1-wide speculative verification spans in
    one pass over the KV cache (reunifies speculation with the fused
    kernels — round-1's 'speculation forces jnp attention' shortcut)."""

    def test_matches_dense_per_query_bounds(self):
        import math as _math

        from adversarial_spec_tpu.ops.pallas_decode import (
            decode_attention_mq,
        )

        B, S, Hq, Hkv, D, T_ = 2, 9, 8, 2, 64, 256
        ks = jax.random.split(jax.random.key(11), 3)
        q = jax.random.normal(ks[0], (B, S, Hq, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, Hkv, T_, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, Hkv, T_, D), jnp.float32)
        base = np.array([100, 37])
        starts = np.tile(np.array([[3], [0]]), (1, S)).astype(np.int32)
        ends = (base[:, None] + np.arange(1, S + 1)[None, :]).astype(np.int32)

        out = decode_attention_mq(
            q, k, v, jnp.asarray(starts), jnp.asarray(ends), interpret=True
        )

        g = Hq // Hkv
        qg = q.reshape(B, S, Hkv, g, D)
        s = jnp.einsum("bshgd,bhtd->bhsgt", qg, k) / _math.sqrt(D)
        slot = np.arange(T_)
        mask = (slot[None, None, :] >= starts[:, :, None]) & (
            slot[None, None, :] < ends[:, :, None]
        )
        s = jnp.where(jnp.asarray(mask)[:, None, :, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, -1)
        ref = jnp.einsum("bhsgt,bhtd->bshgd", p, v).reshape(B, S, Hq, D)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_speculative_with_kernels_matches_jnp(self):
        """Greedy speculative decode routed through the MQ (verify) +
        SQ (tail) kernels must produce the same tokens as the jnp
        speculative path — and as plain decode (transitivity)."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [
            [((i * 13) % 500) + 3 for i in range(40)],
            [5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9],
        ]
        kw = dict(
            max_new_tokens=24, eos_ids=[], greedy=True, speculative=True
        )
        jnp_spec = generate(params, cfg, prompts, use_pallas_decode=False, **kw)
        kern_spec = generate(params, cfg, prompts, use_pallas_decode=True, **kw)
        np.testing.assert_array_equal(jnp_spec.tokens, kern_spec.tokens)
        plain = generate(
            params, cfg, prompts,
            max_new_tokens=24, eos_ids=[], greedy=True, speculative=False,
        )
        np.testing.assert_array_equal(plain.tokens, kern_spec.tokens)

    def test_windowed_family_mq_path(self):
        """Sliding-window layers tighten per-query starts inside the MQ
        span; gemma2-style alternation must match the jnp path."""
        from dataclasses import replace

        cfg = replace(get_config("gemma2", "tiny"), sliding_window=8)
        params = T.init_params(jax.random.key(2), cfg, dtype=jnp.float32)
        prompts = [[((i * 7) % 500) + 3 for i in range(30)]]
        kw = dict(
            max_new_tokens=20, eos_ids=[], greedy=True, speculative=True
        )
        a = generate(params, cfg, prompts, use_pallas_decode=False, **kw)
        b = generate(params, cfg, prompts, use_pallas_decode=True, **kw)
        np.testing.assert_array_equal(a.tokens, b.tokens)


class TestInt8PagedPool:
    """int8 pages + scale pages: the paged pool and the int8 KV cache are
    no longer mutually exclusive (round-2 shortcut in NOTES.md)."""

    def test_paged_kernel_matches_gathered_dequant(self):
        from adversarial_spec_tpu.ops.pallas_paged import (
            paged_decode_attention,
        )

        B, Hq, Hkv, D, page, P_ = 2, 4, 2, 64, 16, 6
        ks = jax.random.split(jax.random.key(11), 3)
        n_pages = 1 + B * P_  # page 0 = trash
        q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
        kf = jax.random.normal(ks[1], (n_pages, Hkv, page, D), jnp.float32)
        vf = jax.random.normal(ks[2], (n_pages, Hkv, page, D), jnp.float32)
        amax = jnp.max(jnp.abs(kf), axis=-1, keepdims=True)
        ksc = jnp.maximum(amax, 1e-8) / 127.0
        k8 = jnp.clip(jnp.round(kf / ksc), -127, 127).astype(jnp.int8)
        amax = jnp.max(jnp.abs(vf), axis=-1, keepdims=True)
        vsc = jnp.maximum(amax, 1e-8) / 127.0
        v8 = jnp.clip(jnp.round(vf / vsc), -127, 127).astype(jnp.int8)
        table = (
            1 + jnp.arange(B * P_, dtype=jnp.int32).reshape(B, P_)
        )
        bounds = jnp.array([[0, 90], [5, 96]], jnp.int32)

        out = paged_decode_attention(
            q, k8, v8, table, bounds, interpret=True,
            k_scale=ksc, v_scale=vsc,
        )
        # Reference: dense attention over the DEQUANTIZED gathered pages.
        kd = (k8 * ksc)[table]  # [B, P, Hkv, page, D]
        vd = (v8 * vsc)[table]
        kd = jnp.swapaxes(kd, 1, 2).reshape(B, Hkv, P_ * page, D)
        vd = jnp.swapaxes(vd, 1, 2).reshape(B, Hkv, P_ * page, D)
        ref = _dense_ref(q, kd, vd, bounds)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_generate_paged_int8_matches_dense_int8(self):
        """Greedy tokens through (int8 paged pool) must equal (int8 dense
        cache) — identical per-token quantization, different storage."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[3, 7, 11, 15], [2, 4]]
        kw = dict(
            max_new_tokens=8, eos_ids=[], greedy=True,
            kv_dtype="int8", speculative=False, share_prefix=False,
        )
        dense = generate(params, cfg, prompts, paged=False, **kw)
        paged = generate(params, cfg, prompts, paged=True, page_size=16, **kw)
        np.testing.assert_array_equal(dense.tokens, paged.tokens)

    def test_generate_paged_int8_kernel_matches_gather(self):
        """Same quantized pool, kernel (interpret) vs gather path."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3, 7, 2]]
        kw = dict(
            max_new_tokens=8, eos_ids=[], greedy=True,
            kv_dtype="int8", speculative=False, paged=True, page_size=16,
        )
        gather = generate(params, cfg, prompts, use_pallas_decode=False, **kw)
        kern = generate(params, cfg, prompts, use_pallas_decode=True, **kw)
        np.testing.assert_array_equal(gather.tokens, kern.tokens)


class TestInt8MqKernel:
    def test_mq_kernel_matches_dequant_reference(self):
        from adversarial_spec_tpu.ops.pallas_decode import (
            decode_attention_mq,
        )

        B, S, Hq, Hkv, D, T_ = 2, 5, 4, 2, 64, 128
        ks = jax.random.split(jax.random.key(13), 3)
        q = jax.random.normal(ks[0], (B, S, Hq, D), jnp.float32)
        kf = jax.random.normal(ks[1], (B, Hkv, T_, D), jnp.float32)
        vf = jax.random.normal(ks[2], (B, Hkv, T_, D), jnp.float32)
        amax = jnp.max(jnp.abs(kf), axis=-1, keepdims=True)
        ksc = jnp.maximum(amax, 1e-8) / 127.0
        k8 = jnp.clip(jnp.round(kf / ksc), -127, 127).astype(jnp.int8)
        amax = jnp.max(jnp.abs(vf), axis=-1, keepdims=True)
        vsc = jnp.maximum(amax, 1e-8) / 127.0
        v8 = jnp.clip(jnp.round(vf / vsc), -127, 127).astype(jnp.int8)
        starts = jnp.zeros((B, S), jnp.int32)
        ends = 100 + jnp.arange(S, dtype=jnp.int32)[None, :] + jnp.array(
            [[0], [7]], jnp.int32
        )

        out = decode_attention_mq(
            q, k8, v8, starts, ends, interpret=True,
            k_scale=ksc, v_scale=vsc,
        )
        ref = decode_attention_mq(
            q, k8 * ksc, v8 * vsc, starts, ends, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_int8_speculative_generate_matches_int8_plain(self, ):
        """Greedy speculation with an int8 cache (MQ kernel verify +
        single-query kernel tail, both on int8 tiles) must equal plain
        int8 greedy decode bit-for-bit."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompt = [5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9]
        kw = dict(
            max_new_tokens=20, eos_ids=[], greedy=True,
            kv_dtype="int8", use_pallas_decode=True,
        )
        plain = generate(params, cfg, [prompt], speculative=False, **kw)
        spec = generate(params, cfg, [prompt], speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)


class TestFusedQuantMatmul:
    """ops/pallas_quant.py: the in-kernel dequant-matmul over int8 /
    packed-int4 weights (interpret mode) against the XLA dequant-fusion
    path in ops/quant.py — the stream-packed-once contract must not
    change the math."""

    def _xw(self, M=24, K=256, N=128, key=0):
        ks = jax.random.split(jax.random.key(key), 2)
        x = jax.random.normal(ks[0], (M, K), jnp.float32)
        w = jax.random.normal(ks[1], (K, N), jnp.float32)
        return x, w

    def test_int8_bit_exact_vs_xla(self):
        from adversarial_spec_tpu.ops import pallas_quant, quant

        x, w = self._xw()
        w8 = quant.quantize_int8(w)
        got = pallas_quant.matmul_int8(
            x, w8["q"], w8["scale"], interpret=True
        )
        # Whole-K accumulation matches XLA's order: byte parity.
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(quant.matmul(x, w8))
        )

    def test_int4_matches_xla_even_and_odd_width(self):
        from adversarial_spec_tpu.ops import pallas_quant, quant

        for K in (256, 255):  # odd width: the packed zero-row pad
            x, w = self._xw(M=8, K=K, key=K)
            w4 = quant.quantize_int4(w)
            got = pallas_quant.matmul_int4(
                x, w4["q4"], w4["scale"], interpret=True
            )
            # The kernel contracts x_even@lo + x_odd@hi — a reassociated
            # sum vs XLA's single contraction, so close not bit-equal.
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(quant.matmul(x, w4)),
                rtol=2e-4, atol=2e-4,
            )

    def test_stacked_activation_batch(self):
        from adversarial_spec_tpu.ops import pallas_quant, quant

        x = jax.random.normal(jax.random.key(3), (2, 3, 256), jnp.float32)
        _, w = self._xw(key=4)
        w8 = quant.quantize_int8(w)
        got = pallas_quant.matmul_int8(
            x, w8["q"], w8["scale"], interpret=True
        )
        assert got.shape == (2, 3, 128)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(quant.matmul(x, w8))
        )

    def test_dispatch_and_fallback(self):
        """quant.matmul(use_pallas=True) routes supported shapes to the
        kernel and silently keeps the XLA path for layer-stacked
        weights (3-D q: no flat [K, N] operand to stream)."""
        from adversarial_spec_tpu.ops import pallas_quant, quant

        x, w = self._xw(M=4)
        w4 = quant.quantize_int4(w)
        assert pallas_quant.fused_supported(x, w4)
        got = quant.matmul(x, w4, use_pallas=True, interpret=True)
        np.testing.assert_array_equal(
            np.asarray(got),
            np.asarray(
                pallas_quant.matmul_int4(
                    x, w4["q4"], w4["scale"], interpret=True
                )
            ),
        )
        # Layer-stacked leaves (3-D q) have no flat [K, N] operand to
        # stream: not fused (the model scans per-layer slices, so the
        # dispatcher only ever sees 2-D weights — this pins the guard).
        stacked = {
            "q4": jnp.stack([w4["q4"]] * 2),
            "scale": jnp.stack([w4["scale"]] * 2),
        }
        assert not pallas_quant.fused_supported(x, stacked)
        assert not pallas_quant.fused_supported(x, w)  # plain array

    def test_preferred_element_type(self):
        from adversarial_spec_tpu.ops import pallas_quant, quant

        x, w = self._xw(M=8)
        w8 = quant.quantize_int8(w)
        xb = x.astype(jnp.bfloat16)
        got = pallas_quant.matmul_int8(
            xb, w8["q"], w8["scale"],
            preferred_element_type=jnp.float32, interpret=True,
        )
        assert got.dtype == jnp.float32
        default = pallas_quant.matmul_int8(
            xb, w8["q"], w8["scale"], interpret=True
        )
        assert default.dtype == jnp.bfloat16


class TestPagedMqKernel:
    """paged_decode_attention_mq: the γ+1-position verify span over the
    PAGED pool — per-position causal bounds, one pass over the row's
    pages, trash/unmapped sentinel discipline unchanged."""

    def _pool(self, B=2, Hkv=2, D=64, page=16, P=6, key=21, poison=False):
        n_pages = 1 + B * P  # physical page 0 = trash
        ks = jax.random.split(jax.random.key(key), 2)
        kp = jax.random.normal(ks[0], (n_pages, Hkv, page, D), jnp.float32)
        vp = jax.random.normal(ks[1], (n_pages, Hkv, page, D), jnp.float32)
        if poison:
            kp = kp.at[0].set(1e9)
            vp = vp.at[0].set(1e9)
        return kp, vp

    def _ref(self, q, kp, vp, table, starts, ends):
        """Dense gather + per-position masked softmax (numpy, f64)."""
        qn, kn, vn = (np.asarray(a, np.float64) for a in (q, kp, vp))
        tb, st, en = (np.asarray(a) for a in (table, starts, ends))
        B, S, Hq, D = qn.shape
        Hkv, page = kn.shape[1], kn.shape[2]
        g, T_ = Hq // Hkv, tb.shape[1] * page
        out = np.zeros((B, S, Hq, D))
        slot = np.arange(T_)
        for b in range(B):
            ids = np.maximum(tb[b], 0)
            kd = kn[ids].transpose(1, 0, 2, 3).reshape(Hkv, T_, D)
            vd = vn[ids].transpose(1, 0, 2, 3).reshape(Hkv, T_, D)
            mapped = np.repeat(tb[b] > 0, page)
            for s in range(S):
                ok = mapped & (slot >= st[b, s]) & (slot < en[b, s])
                for h in range(Hq):
                    logits = kd[h // g] @ qn[b, s, h] / math.sqrt(D)
                    logits[~ok] = -np.inf
                    p = np.exp(logits - logits.max())
                    p[~ok] = 0.0
                    out[b, s, h] = (p @ vd[h // g]) / max(p.sum(), 1e-30)
        return out

    def test_matches_gathered_dense_per_position_bounds(self):
        from adversarial_spec_tpu.ops.pallas_paged import (
            paged_decode_attention_mq,
        )

        B, S, Hq, Hkv, D, page, P = 2, 5, 8, 2, 64, 16, 6
        q = jax.random.normal(jax.random.key(22), (B, S, Hq, D), jnp.float32)
        kp, vp = self._pool(B=B, Hkv=Hkv, D=D, page=page, P=P)
        table = np.full((B, P), -1, np.int32)
        table[0, :4] = 1 + np.arange(4)
        table[1, :3] = 1 + P + np.arange(3)
        base = np.array([[50], [33]])
        starts = np.zeros((B, S), np.int32)
        starts[0, :] = 3  # a windowed row
        ends = (base + 1 + np.arange(S)[None, :]).astype(np.int32)

        out = paged_decode_attention_mq(
            q, kp, vp, jnp.asarray(table),
            jnp.asarray(starts), jnp.asarray(ends), interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), self._ref(q, kp, vp, table, starts, ends),
            rtol=2e-5, atol=2e-5,
        )

    def test_trash_page_zero_is_masked(self):
        """Speculative verify parks non-writable span positions on
        physical page 0; a poisoned trash page must not leak into any
        span position's output."""
        from adversarial_spec_tpu.ops.pallas_paged import (
            paged_decode_attention_mq,
        )

        B, S, Hq, Hkv, D, page, P = 1, 3, 4, 2, 64, 8, 4
        q = jax.random.normal(jax.random.key(23), (B, S, Hq, D), jnp.float32)
        kp, vp = self._pool(B=B, Hkv=Hkv, D=D, page=page, P=P, poison=True)
        table = np.array([[1, 0, 2, -1]], np.int32)  # a 0 sentinel mid-table
        starts = np.zeros((B, S), np.int32)
        ends = np.array([[20, 21, 22]], np.int32)  # spans the unmapped page

        out = paged_decode_attention_mq(
            q, kp, vp, jnp.asarray(table),
            jnp.asarray(starts), jnp.asarray(ends), interpret=True,
        )
        assert np.all(np.isfinite(np.asarray(out)))
        np.testing.assert_allclose(
            np.asarray(out), self._ref(q, kp, vp, table, starts, ends),
            rtol=2e-5, atol=2e-5,
        )

    def test_row_count_not_sublane_multiple(self):
        """S·g = 6 pads to the 8-sublane tile; pad rows get an empty
        window and must not perturb the real rows."""
        from adversarial_spec_tpu.ops.pallas_paged import (
            paged_decode_attention_mq,
        )

        B, S, Hq, Hkv, D, page, P = 2, 3, 4, 2, 64, 16, 4
        q = jax.random.normal(jax.random.key(24), (B, S, Hq, D), jnp.float32)
        kp, vp = self._pool(B=B, Hkv=Hkv, D=D, page=page, P=P)
        table = 1 + np.arange(B * P, dtype=np.int32).reshape(B, P)
        starts = np.zeros((B, S), np.int32)
        ends = np.asarray(
            40 + np.arange(S)[None, :] + np.zeros((B, 1), np.int32),
            np.int32,
        )
        out = paged_decode_attention_mq(
            q, kp, vp, jnp.asarray(table),
            jnp.asarray(starts), jnp.asarray(ends), interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), self._ref(q, kp, vp, table, starts, ends),
            rtol=2e-5, atol=2e-5,
        )

    def test_single_position_matches_single_query_kernel(self):
        """S=1 must agree with paged_decode_attention — the MQ kernel is
        a strict generalization of the decode kernel's contract."""
        from adversarial_spec_tpu.ops.pallas_paged import (
            paged_decode_attention,
            paged_decode_attention_mq,
        )

        B, Hq, Hkv, D, page, P = 2, 8, 2, 64, 16, 6
        q = jax.random.normal(jax.random.key(25), (B, 1, Hq, D), jnp.float32)
        kp, vp = self._pool(B=B, Hkv=Hkv, D=D, page=page, P=P)
        table = 1 + np.arange(B * P, dtype=np.int32).reshape(B, P)
        bounds = jnp.array([[2, 40], [0, 90]], jnp.int32)
        mq = paged_decode_attention_mq(
            q, kp, vp, jnp.asarray(table),
            bounds[:, 0:1], bounds[:, 1:2], interpret=True,
        )
        sq = paged_decode_attention(
            q[:, 0], kp, vp, jnp.asarray(table), bounds, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(mq[:, 0]), np.asarray(sq), rtol=2e-5, atol=2e-5
        )

    def test_int8_pool_scales_match_dequant_reference(self):
        from adversarial_spec_tpu.ops.pallas_paged import (
            paged_decode_attention_mq,
        )

        B, S, Hq, Hkv, D, page, P = 2, 3, 4, 2, 64, 16, 4
        q = jax.random.normal(jax.random.key(26), (B, S, Hq, D), jnp.float32)
        kf, vf = self._pool(B=B, Hkv=Hkv, D=D, page=page, P=P)
        amax = jnp.max(jnp.abs(kf), axis=-1, keepdims=True)
        ksc = jnp.maximum(amax, 1e-8) / 127.0
        k8 = jnp.clip(jnp.round(kf / ksc), -127, 127).astype(jnp.int8)
        amax = jnp.max(jnp.abs(vf), axis=-1, keepdims=True)
        vsc = jnp.maximum(amax, 1e-8) / 127.0
        v8 = jnp.clip(jnp.round(vf / vsc), -127, 127).astype(jnp.int8)
        table = 1 + np.arange(B * P, dtype=np.int32).reshape(B, P)
        starts = np.zeros((B, S), np.int32)
        ends = np.asarray(
            30 + np.arange(S)[None, :] + np.zeros((B, 1), np.int32),
            np.int32,
        )
        out = paged_decode_attention_mq(
            q, k8, v8, jnp.asarray(table),
            jnp.asarray(starts), jnp.asarray(ends), interpret=True,
            k_scale=ksc, v_scale=vsc,
        )
        ref = paged_decode_attention_mq(
            q, k8 * ksc, v8 * vsc, jnp.asarray(table),
            jnp.asarray(starts), jnp.asarray(ends), interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


class TestFusedMatmulInGenerate:
    """End-to-end: the fused dequant-matmul routed through the model's
    projection/MLP/lm-head sites must leave greedy transcripts
    byte-identical, for both quantized formats, dense and paged."""

    def _quantized(self, fmt):
        from adversarial_spec_tpu.ops import quant

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        return quant.quantize_params(params, fmt=fmt), cfg

    @pytest.mark.parametrize("fmt", ["int8", "int4"])
    def test_generate_transcript_parity(self, fmt):
        qp, cfg = self._quantized(fmt)
        prompts = [[((i * 13) % 500) + 3 for i in range(24)], [5, 9, 7, 5]]
        kw = dict(
            max_new_tokens=8, eos_ids=[], greedy=True,
            speculative=False, share_prefix=False,
        )
        off = generate(qp, cfg, prompts, use_pallas_matmul=False, **kw)
        on = generate(qp, cfg, prompts, use_pallas_matmul=True, **kw)
        np.testing.assert_array_equal(off.tokens, on.tokens)

    def test_generate_paged_int4_parity(self):
        qp, cfg = self._quantized("int4")
        prompts = [[3, 7, 11, 15, 2, 4, 6, 8]]
        kw = dict(
            max_new_tokens=8, eos_ids=[], greedy=True,
            speculative=False, paged=True, page_size=16,
        )
        off = generate(qp, cfg, prompts, use_pallas_matmul=False, **kw)
        on = generate(qp, cfg, prompts, use_pallas_matmul=True, **kw)
        np.testing.assert_array_equal(off.tokens, on.tokens)

    def test_batcher_both_kernels_zero_recompiles(self):
        """Two drains through the batcher with the span-verify kernel
        AND the fused int4 matmul live: greedy parity with the XLA
        batcher and no seen-key recompile (the promoted-q4 residency
        contract rides on this same signature stability)."""
        from adversarial_spec_tpu import obs
        from adversarial_spec_tpu.engine import spec as spec_mod
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        qp, cfg = self._quantized("int4")
        prompt = [5 + (i % 7) for i in range(40)]
        spec_mod.configure(enabled=True, gamma=4)
        was_enabled = obs.config().enabled
        obs.configure(enabled=True)
        obs.retrace.clear()

        def drain(use_pallas, n=6):
            b = ContinuousBatcher(
                qp, cfg, max_batch=1, max_new_cap=n,
                speculative=True, gamma=4,
                use_pallas_matmul=use_pallas,
            )
            b._use_pallas = use_pallas
            b._pallas_interpret = True
            out = {}
            for _ in range(2):  # two drains: reuse, not recompile
                b.submit(
                    SchedRequest(
                        req_id=0, prompt_ids=list(prompt), max_new_tokens=n
                    )
                )
                [r] = b.run_all()
                out = r.tokens.tolist()
            return out

        try:
            ref = drain(False)
            obs.retrace.clear()
            fused = drain(True)
            snap = obs.retrace.snapshot()
        finally:
            obs.retrace.clear()
            obs.configure(enabled=was_enabled)
            spec_mod.configure(enabled=True, gamma=spec_mod.DEFAULT_GAMMA)
        assert fused == ref
        assert snap["programs"], "no program dispatched"
        assert snap["unexpected_recompiles"] == 0, snap
