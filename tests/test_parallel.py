"""Mesh, sharding, and ring-attention tests on the virtual 8-device CPU
mesh (SURVEY §4: the host-platform device-count trick — multi-chip
semantics in one process; the reference has no multi-node story to copy)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine.generate import generate
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config
from adversarial_spec_tpu.parallel.mesh import DP, SP, TP, make_mesh, mesh_shape_from_spec
from adversarial_spec_tpu.parallel.ring import ring_attention
from adversarial_spec_tpu.parallel.sharding import (
    param_shardings,
    shard_params,
)


@pytest.fixture(scope="module", autouse=True)
def _needs_8_devices():
    if len(jax.devices()) < 8:
        pytest.skip("requires 8 virtual devices (see conftest XLA_FLAGS)")


class TestMeshShape:
    def test_defaults_fill_dp(self):
        assert mesh_shape_from_spec({"tp": 2}, 8) == {DP: 4, TP: 2, SP: 1}

    def test_empty_spec_all_dp(self):
        assert mesh_shape_from_spec({}, 8) == {DP: 8, TP: 1, SP: 1}

    def test_explicit_full(self):
        assert mesh_shape_from_spec({"dp": 2, "tp": 2, "sp": 2}, 8) == {
            DP: 2,
            TP: 2,
            SP: 2,
        }

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="does not divide"):
            mesh_shape_from_spec({"tp": 3}, 8)

    def test_overcommit_raises(self):
        with pytest.raises(ValueError, match="!= device count"):
            mesh_shape_from_spec({"dp": 8, "tp": 2}, 8)

    def test_unknown_axis_raises(self):
        with pytest.raises(ValueError, match="unknown mesh axes"):
            mesh_shape_from_spec({"pp": 2}, 8)

    def test_make_mesh_axis_names(self):
        mesh = make_mesh({"tp": 2})
        assert set(mesh.axis_names) == {DP, SP, TP}
        assert mesh.shape[TP] == 2


class TestShardedParams:
    def test_tp_shards_heads_and_ffn(self):
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        mesh = make_mesh({"tp": 2})
        sharded = shard_params(mesh, params)
        # Column-parallel: wq last dim split over tp.
        wq_shard = sharded["layers"]["wq"].sharding
        assert wq_shard.spec == jax.sharding.PartitionSpec(None, None, TP)
        # Row-parallel: wo middle dim split.
        assert sharded["layers"]["wo"].sharding.spec == (
            jax.sharding.PartitionSpec(None, TP, None)
        )
        # Values unchanged by sharding.
        np.testing.assert_array_equal(
            np.asarray(sharded["layers"]["wq"]),
            np.asarray(params["layers"]["wq"]),
        )

    def test_materialize_random_respects_tp_rules(self):
        """The random-checkpoint branch hands jax DictKey paths to the
        loader's device_put hook; the hook must still resolve the rule
        (a miss silently replicates every param — OOM at 70B/tp=8)."""
        from adversarial_spec_tpu.engine.loader import materialize_params
        from adversarial_spec_tpu.parallel.sharding import make_device_put

        mesh = make_mesh({"tp": 2})
        params, _ = materialize_params(
            "random",
            "llama",
            "tiny",
            dtype=jnp.float32,
            device_put=make_device_put(mesh, jnp.float32),
        )
        assert params["layers"]["wq"].sharding.spec == (
            jax.sharding.PartitionSpec(None, None, TP)
        )
        assert params["layers"]["wo"].sharding.spec == (
            jax.sharding.PartitionSpec(None, TP, None)
        )
        assert params["lm_head"].sharding.spec == (
            jax.sharding.PartitionSpec(None, TP)
        )

    def test_sharding_tree_matches_params_tree(self):
        cfg = get_config("qwen2", "tiny")  # includes biases
        params = T.init_params(jax.random.key(0), cfg)
        mesh = make_mesh({"tp": 2})
        shardings = param_shardings(mesh, params)
        assert jax.tree_util.tree_structure(
            shardings
        ) == jax.tree_util.tree_structure(params)


class TestShardedGenerate:
    @pytest.mark.parametrize(
        "mesh_spec", [{"tp": 2}, {"dp": 4, "tp": 2}, {"dp": 8}]
    )
    def test_sharded_matches_single_device(self, mesh_spec):
        """Greedy decode on a dp×tp mesh must reproduce the single-device
        tokens exactly — numerical parity across sharding layouts is the
        correctness bar for the TP/DP implementation."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3], [2, 6], [8, 8, 8], [4]]
        kw = dict(max_new_tokens=6, eos_ids=[], greedy=True)

        ref = generate(params, cfg, prompts, **kw)

        mesh = make_mesh(mesh_spec)
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(sharded, cfg, prompts, mesh=mesh, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)
        np.testing.assert_array_equal(ref.n_generated, out.n_generated)

    def test_batch_not_multiple_of_dp(self):
        """3 opponents on dp=4: rows padded internally, result unpadded."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 2], [3, 4, 5], [6]]
        ref = generate(
            params, cfg, prompts, max_new_tokens=4, eos_ids=[], greedy=True
        )
        mesh = make_mesh({"dp": 4, "tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded,
                cfg,
                prompts,
                max_new_tokens=4,
                eos_ids=[],
                greedy=True,
                mesh=mesh,
            )
        assert out.tokens.shape[0] == 3
        np.testing.assert_array_equal(ref.tokens, out.tokens)


class TestSequenceParallelPrefill:
    def test_sp_prefill_matches_dense(self):
        """Full-model sequence-parallel prefill (ring attention inside the
        layer scan) must reproduce the dense single-device prefill: same
        last-position logits, same KV cache contents."""
        from adversarial_spec_tpu.engine.generate import prefill_chunk
        from adversarial_spec_tpu.parallel.sp import (
            reshard_cache_for_decode,
            sp_prefill,
        )

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        mesh = make_mesh({"sp": 4})
        B, S = 2, 32
        tokens = jax.random.randint(
            jax.random.key(5), (B, S), 0, cfg.vocab_size
        )
        pad_lens = jnp.array([3, 0], jnp.int32)
        # Left-pad semantics: zero out the pad slots.
        tokens = jnp.where(
            jnp.arange(S)[None, :] < pad_lens[:, None], 0, tokens
        )

        with mesh:
            logits_sp, cache_sp = sp_prefill(params, cfg, tokens, pad_lens, mesh)

        dense_cache = T.init_cache(cfg, B, S, dtype=jnp.float32)
        dense_cache, last_logits = prefill_chunk(
            params, cfg, tokens, pad_lens, dense_cache, jnp.int32(0)
        )
        np.testing.assert_allclose(
            np.asarray(logits_sp),
            np.asarray(last_logits),
            rtol=2e-4,
            atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(cache_sp["k"]),
            np.asarray(dense_cache["k"]),
            rtol=2e-4,
            atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(cache_sp["v"]),
            np.asarray(dense_cache["v"]),
            rtol=2e-4,
            atol=2e-4,
        )

        with mesh:
            resharded = reshard_cache_for_decode(cache_sp, mesh, S + 8)
        assert resharded["k"].shape[3] == S + 8
        np.testing.assert_allclose(
            np.asarray(resharded["k"][..., :S, :]),
            np.asarray(dense_cache["k"]),
            rtol=2e-4,
            atol=2e-4,
        )

    def test_generate_end_to_end_on_sp_mesh(self):
        """generate() on an sp>1 mesh routes prefill through the
        sequence-parallel path and must reproduce single-device tokens."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3, 7, 2], [4, 4, 8]]
        kw = dict(max_new_tokens=6, eos_ids=[], greedy=True)
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"sp": 4, "dp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(sharded, cfg, prompts, mesh=mesh, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)

    def test_sp_times_tp_matches_dense(self):
        """tp×sp composition (the config-5 shape: TP judge + long
        context): manual-collective TP inside the sp shard_map must
        reproduce dense single-device prefill exactly."""
        from adversarial_spec_tpu.engine.generate import prefill_chunk
        from adversarial_spec_tpu.parallel.sp import sp_prefill

        cfg = get_config("llama", "tiny")  # 4 heads, 2 kv heads
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        mesh = make_mesh({"sp": 4, "tp": 2, "dp": 1})
        sharded = shard_params(mesh, params)
        B, S = 2, 32
        tokens = jax.random.randint(
            jax.random.key(7), (B, S), 0, cfg.vocab_size
        )
        pad_lens = jnp.array([5, 0], jnp.int32)
        tokens = jnp.where(
            jnp.arange(S)[None, :] < pad_lens[:, None], 0, tokens
        )
        with mesh:
            logits_sp, cache_sp = sp_prefill(
                sharded, cfg, tokens, pad_lens, mesh
            )
        dense_cache = T.init_cache(cfg, B, S, dtype=jnp.float32)
        dense_cache, ref_logits = prefill_chunk(
            params, cfg, tokens, pad_lens, dense_cache, jnp.int32(0)
        )
        np.testing.assert_allclose(
            np.asarray(logits_sp), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(cache_sp["k"]),
            np.asarray(dense_cache["k"]),
            rtol=2e-4,
            atol=2e-4,
        )

    def test_generate_on_sp_tp_dp_mesh(self):
        """All three axes at once through the public generate()."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3], [2, 6, 4, 8]]
        kw = dict(max_new_tokens=4, eos_ids=[], greedy=True)
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"sp": 2, "tp": 2, "dp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(sharded, cfg, prompts, mesh=mesh, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)

    def test_speculative_decode_on_sp_mesh_matches_dense(self):
        """The 16k-context config's decode lever (VERDICT r3 item 9):
        after sp prefill reshards the cache into the standard decode
        layout, speculation runs as one GSPMD program (sp axis
        replicated) and must reproduce single-device greedy tokens.
        max_new > GAMMA+1 so the speculative path actually engages;
        repetitive prompts so drafts actually accept."""
        from adversarial_spec_tpu.engine.speculative import GAMMA

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        base = [3, 7, 11, 5] * 4
        prompts = [base + [9], base + [13]]
        # Budget derived from GAMMA so an ADVSPEC_GAMMA override can't
        # silently disable the speculative path under test.
        kw = dict(max_new_tokens=2 * GAMMA + 8, eos_ids=[], greedy=True)
        ref = generate(params, cfg, prompts, speculative=False, **kw)
        mesh = make_mesh({"sp": 4, "dp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh, speculative=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)

    def test_speculative_decode_on_sp_tp_mesh_matches_dense(self):
        """Speculation composes with sp×tp×dp (config-5 shape)."""
        from adversarial_spec_tpu.engine.speculative import GAMMA

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        base = [2, 6, 4, 8] * 4
        prompts = [base, base[::-1]]
        kw = dict(max_new_tokens=2 * GAMMA + 4, eos_ids=[], greedy=True)
        ref = generate(params, cfg, prompts, speculative=False, **kw)
        mesh = make_mesh({"sp": 2, "tp": 2, "dp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh, speculative=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)

    def test_sp_tp_indivisible_heads_raises(self):
        from adversarial_spec_tpu.parallel.sp import sp_prefill

        cfg = get_config("llama", "tiny")  # 2 kv heads
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        mesh = make_mesh({"sp": 2, "tp": 4})
        tokens = jnp.zeros((1, 32), jnp.int32)
        with pytest.raises(ValueError, match="must divide"):
            sp_prefill(params, cfg, tokens, jnp.zeros((1,), jnp.int32), mesh)

    @pytest.mark.parametrize("family", ["mistral", "gemma2"])
    def test_sp_prefill_windowed_families(self, family):
        """Sliding windows (incl. gemma-2's alternating layers) inside the
        ring must reproduce dense prefill exactly. Window shrunk to 8 so
        it genuinely truncates across block boundaries (blocks of 8 at
        sp=4, S=32)."""
        from dataclasses import replace as dc_replace

        from adversarial_spec_tpu.engine.generate import prefill_chunk
        from adversarial_spec_tpu.parallel.sp import sp_prefill

        cfg = dc_replace(get_config(family, "tiny"), sliding_window=8)
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        mesh = make_mesh({"sp": 4})
        B, S = 2, 32
        tokens = jax.random.randint(
            jax.random.key(9), (B, S), 0, cfg.vocab_size
        )
        pad_lens = jnp.array([3, 0], jnp.int32)
        tokens = jnp.where(
            jnp.arange(S)[None, :] < pad_lens[:, None], 0, tokens
        )
        with mesh:
            logits_sp, cache_sp = sp_prefill(
                params, cfg, tokens, pad_lens, mesh
            )
        dense_cache = T.init_cache(cfg, B, S, dtype=jnp.float32)
        dense_cache, ref_logits = prefill_chunk(
            params, cfg, tokens, pad_lens, dense_cache, jnp.int32(0)
        )
        np.testing.assert_allclose(
            np.asarray(logits_sp), np.asarray(ref_logits), rtol=3e-4, atol=3e-4
        )
        np.testing.assert_allclose(
            np.asarray(cache_sp["k"]),
            np.asarray(dense_cache["k"]),
            rtol=3e-4,
            atol=3e-4,
        )
        np.testing.assert_allclose(
            np.asarray(cache_sp["v"]),
            np.asarray(dense_cache["v"]),
            rtol=3e-4,
            atol=3e-4,
        )


class TestRingAttention:
    def _dense_ref(self, q, k, v, causal=True):
        B, S, H, D = q.shape
        Hkv = k.shape[2]
        g = H // Hkv
        qg = q.reshape(B, S, Hkv, g, D)
        s = jnp.einsum("bshgd,bthd->bhgst", qg, k) / math.sqrt(D)
        if causal:
            mask = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(mask[None, None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, -1)
        return jnp.einsum("bhgst,bthd->bshgd", p, v).reshape(B, S, H, D)

    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_causal_matches_dense(self, sp):
        mesh = make_mesh({"sp": sp})
        B, S, H, Hkv, D = 2, 32, 4, 2, 16
        ks = jax.random.split(jax.random.key(1), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
        out = ring_attention(q, k, v, mesh, causal=True)
        ref = self._dense_ref(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=3e-5, atol=3e-5
        )

    def test_non_causal_matches_dense(self):
        mesh = make_mesh({"sp": 4})
        ks = jax.random.split(jax.random.key(2), 3)
        q = jax.random.normal(ks[0], (1, 16, 2, 8), jnp.float32)
        k = jax.random.normal(ks[1], (1, 16, 2, 8), jnp.float32)
        v = jax.random.normal(ks[2], (1, 16, 2, 8), jnp.float32)
        out = ring_attention(q, k, v, mesh, causal=False)
        ref = self._dense_ref(q, k, v, causal=False)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=3e-5, atol=3e-5
        )

    def test_indivisible_sequence_raises(self):
        mesh = make_mesh({"sp": 4})
        x = jnp.zeros((1, 30, 2, 8))
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(x, x, x, mesh)

    def test_matches_jitted(self):
        """Ring attention must be jittable (it runs inside prefill)."""
        mesh = make_mesh({"sp": 4})
        ks = jax.random.split(jax.random.key(3), 3)
        q = jax.random.normal(ks[0], (1, 16, 2, 8), jnp.float32)
        k = jax.random.normal(ks[1], (1, 16, 2, 8), jnp.float32)
        v = jax.random.normal(ks[2], (1, 16, 2, 8), jnp.float32)
        jit_out = jax.jit(
            lambda a, b, c: ring_attention(a, b, c, mesh, causal=True)
        )(q, k, v)
        eager = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(jit_out), np.asarray(eager), rtol=1e-6, atol=1e-6
        )


class TestLongContext16k:
    """16k-token sp prefill numerics (VERDICT r1 item 6 / BASELINE
    config 5's context scale). A thin 2-layer model keeps the CPU cost
    tractable; the sequence length is the real thing."""

    @pytest.mark.slow
    def test_sp_prefill_matches_chunked_at_16k(self):
        """Ring-attention sp prefill vs the chunked dense reference at a
        REAL 16384-token sequence (a 1-layer thin model keeps the S²
        attention tractable on CPU; ~80 s)."""
        from dataclasses import replace

        from adversarial_spec_tpu.engine.generate import prefill_chunk
        from adversarial_spec_tpu.parallel.sp import sp_prefill

        S = 16384
        cfg = replace(
            get_config("llama", "tiny"),
            n_layers=1,
            n_heads=2,
            n_kv_heads=2,
            dim=128,
            ffn_dim=256,
            max_seq_len=S + 64,
        )
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(5).integers(3, cfg.vocab_size, (1, S)),
            jnp.int32,
        )
        pads = jnp.zeros((1,), jnp.int32)

        mesh = make_mesh({"sp": 4, "dp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            logits_sp, _ = sp_prefill(sharded, cfg, tokens, pads, mesh)

        cache = T.init_cache(cfg, 1, S, dtype=jnp.float32)
        last = None
        for ci in range(0, S, 1024):
            cache, last = prefill_chunk(
                params, cfg, tokens[:, ci : ci + 1024], pads, cache,
                jnp.int32(ci),
            )
        np.testing.assert_allclose(
            np.asarray(logits_sp), np.asarray(last), rtol=3e-4, atol=3e-4
        )


class TestWindowedRingEarlyOut:
    """Sliding-window layers stop the ring after ring_hops hops instead
    of masking dead compute (NOTES round-2 shortcut)."""

    def test_hop_bound_formula(self):
        from adversarial_spec_tpu.parallel.ring import ring_hops

        # Global attention or non-causal: every hop can contribute.
        assert ring_hops(8, 512, 0, True) == 8
        assert ring_hops(8, 512, 64, False) == 8
        # Window within one block: diagonal + one predecessor.
        assert ring_hops(8, 512, 8, True) == 2
        assert ring_hops(8, 512, 512, True) == 2
        # Window a hair past a block boundary pulls in one more hop.
        assert ring_hops(8, 512, 513, True) == 2
        assert ring_hops(8, 512, 514, True) == 3
        # Huge windows clamp at sp.
        assert ring_hops(4, 512, 10**6, True) == 4
        # Traced window (gemma2 alternation) gives the same numbers.
        import jax.numpy as jnp

        assert int(ring_hops(8, 512, jnp.int32(8), True)) == 2
        assert int(ring_hops(8, 512, jnp.int32(0), True)) == 8

    def test_windowed_ring_matches_full_ring(self):
        """Early-out must not change the result: windowed ring output ==
        the same ring forced to run all sp hops (window as mask only)."""
        if len(jax.devices()) < 4:
            pytest.skip("requires 4 virtual devices")
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from adversarial_spec_tpu.parallel import ring as ring_mod
        from adversarial_spec_tpu.parallel.mesh import (
            compat_shard_map,
            make_mesh,
        )

        B, S, H, Hkv, D, W = 2, 64, 4, 2, 16, 7
        ks = jax.random.split(jax.random.key(21), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
        mesh = make_mesh({"sp": 4, "dp": 2})
        spec = P(None, "sp", None, None)

        def run(window):
            def local(qb, kb, vb):
                return ring_mod.ring_attention_local(
                    qb, kb, vb, 4, causal=True, window=window
                )

            return compat_shard_map(
                local, mesh=mesh,
                in_specs=(spec, spec, spec), out_specs=spec,
            )(q, k, v)

        early = run(W)  # static int window → shortened fori_loop
        # Force all hops by passing the window traced-but-equal: trip
        # count identical math, exercises the traced path too.
        traced = run(jnp.int32(W))
        np.testing.assert_allclose(
            np.asarray(early), np.asarray(traced), rtol=1e-6, atol=1e-6
        )
        # And against the full-hop reference: window big enough to keep
        # all hops, then mask manually via a huge-window run on the
        # windowed mask — i.e., compare W-windowed early-out vs the old
        # behavior (all hops, W mask) reconstructed with hops forced to
        # sp by monkeypatching ring_hops.
        orig = ring_mod.ring_hops
        ring_mod.ring_hops = lambda sp_, b_, w_, c_: sp_
        try:
            full = run(W)
        finally:
            ring_mod.ring_hops = orig
        np.testing.assert_allclose(
            np.asarray(early), np.asarray(full), rtol=1e-6, atol=1e-6
        )


class TestSpInt8:
    def test_generate_int8_on_sp_mesh(self):
        """kv_dtype=int8 on an sp mesh: prefill rides the ring at full
        precision, the decode cache quantizes at the reshard boundary —
        greedy tokens must match the single-device int8 run (identical
        prompt-KV quantization; decode math identical)."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1, 5, 9, 3, 7, 2], [4, 4, 8]]
        kw = dict(
            max_new_tokens=6, eos_ids=[], greedy=True,
            kv_dtype="int8", speculative=False,
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"sp": 4, "dp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(sharded, cfg, prompts, mesh=mesh, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)
