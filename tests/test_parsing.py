"""Tag-protocol parser tests (reference analog: tests/test_models.py parser
sections — mutation-hardened assertions on exact boundaries)."""

from adversarial_spec_tpu.debate.parsing import (
    detect_agreement,
    extract_spec,
    extract_tasks,
    generate_diff,
    get_critique_summary,
    has_malformed_spec,
)


class TestDetectAgreement:
    def test_bare_marker(self):
        assert detect_agreement("[AGREE]")

    def test_marker_with_commentary(self):
        assert detect_agreement("Looks great.\n[AGREE]\nShip it.")

    def test_no_marker(self):
        assert not detect_agreement("I agree with most of this")

    def test_case_sensitive(self):
        assert not detect_agreement("[agree]")

    def test_empty(self):
        assert not detect_agreement("")


class TestExtractSpec:
    def test_simple(self):
        assert extract_spec("x [SPEC]the spec[/SPEC] y") == "the spec"

    def test_strips_whitespace(self):
        assert extract_spec("[SPEC]\n  body \n[/SPEC]") == "body"

    def test_missing_open(self):
        assert extract_spec("no tags here") is None

    def test_missing_close(self):
        assert extract_spec("[SPEC] unterminated") is None

    def test_close_before_open(self):
        assert extract_spec("[/SPEC] backwards [SPEC]") is None

    def test_widest_span_preserves_nested_tags(self):
        text = "[SPEC]outer [SPEC]inner[/SPEC] tail[/SPEC]"
        assert extract_spec(text) == "outer [SPEC]inner[/SPEC] tail"

    def test_multi_close_takes_last(self):
        """Deliberate departure from the reference (which stops at the
        FIRST [/SPEC]): an embedded literal close tag does not truncate.
        Pins the divergence called out in extract_spec's docstring."""
        text = "[SPEC]a[/SPEC]b[/SPEC]"
        assert extract_spec(text) == "a[/SPEC]b"

    def test_multiline(self):
        spec = "# Title\n\nBody line 1\nBody line 2"
        assert extract_spec(f"critique\n[SPEC]\n{spec}\n[/SPEC]\ndone") == spec

    def test_malformed_detection(self):
        assert has_malformed_spec("[SPEC] oops no close")
        assert not has_malformed_spec("[SPEC]ok[/SPEC]")
        assert not has_malformed_spec("no tags")


class TestExtractTasks:
    def test_full_fields(self):
        text = """[TASK]
title: Build the API
description: REST endpoints for CRUD.
priority: high
dependencies: Schema design, Auth
estimate: 3d
[/TASK]"""
        tasks = extract_tasks(text)
        assert len(tasks) == 1
        t = tasks[0]
        assert t.title == "Build the API"
        assert t.description == "REST endpoints for CRUD."
        assert t.priority == "high"
        assert t.dependencies == ["Schema design", "Auth"]
        assert t.estimate == "3d"

    def test_multiple_blocks(self):
        text = "[TASK]\ntitle: A\n[/TASK]\nnoise\n[TASK]\ntitle: B\n[/TASK]"
        assert [t.title for t in extract_tasks(text)] == ["A", "B"]

    def test_priority_normalized(self):
        text = "[TASK]\ntitle: X\npriority: URGENT!!\n[/TASK]"
        assert extract_tasks(text)[0].priority == "medium"

    def test_priority_case_insensitive(self):
        text = "[TASK]\ntitle: X\npriority: CRITICAL\n[/TASK]"
        assert extract_tasks(text)[0].priority == "critical"

    def test_unstructured_block_uses_first_line_as_title(self):
        text = "[TASK]\nDo the thing\nwith details\n[/TASK]"
        t = extract_tasks(text)[0]
        assert t.title == "Do the thing"
        assert t.description == "with details"

    def test_empty_block_skipped(self):
        assert extract_tasks("[TASK]\n\n[/TASK]") == []

    def test_no_blocks(self):
        assert extract_tasks("just prose") == []

    def test_bulleted_fields(self):
        text = "[TASK]\n- title: Bulleted\n- priority: low\n[/TASK]"
        t = extract_tasks(text)[0]
        assert t.title == "Bulleted"
        assert t.priority == "low"


class TestCritiqueSummary:
    def test_first_line(self):
        assert get_critique_summary("First point.\nSecond.") == "First point."

    def test_strips_agree_and_spec(self):
        text = "[AGREE]\n[SPEC]hidden[/SPEC]\nActual comment"
        assert get_critique_summary(text) == "Actual comment"

    def test_truncation_boundary(self):
        # Mutation hardening: exactly max_chars passes through untruncated.
        line = "x" * 200
        assert get_critique_summary(line, max_chars=200) == line
        longer = "x" * 201
        out = get_critique_summary(longer, max_chars=200)
        assert len(out) == 200 and out.endswith("...")

    def test_empty(self):
        assert get_critique_summary("") == ""


class TestGenerateDiff:
    def test_identical(self):
        assert generate_diff("same\n", "same\n") == ""

    def test_labels_and_change(self):
        d = generate_diff("a\nb\n", "a\nc\n")
        assert "--- previous_spec" in d
        assert "+++ revised_spec" in d
        assert "-b" in d and "+c" in d
