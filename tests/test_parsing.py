"""Tag-protocol parser tests (reference analog: tests/test_models.py parser
sections — mutation-hardened assertions on exact boundaries)."""

from adversarial_spec_tpu.debate.parsing import (
    Task,
    detect_agreement,
    extract_spec,
    extract_tasks,
    generate_diff,
    get_critique_summary,
    has_malformed_spec,
)


class TestDetectAgreement:
    def test_bare_marker(self):
        assert detect_agreement("[AGREE]")

    def test_marker_with_commentary(self):
        assert detect_agreement("Looks great.\n[AGREE]\nShip it.")

    def test_no_marker(self):
        assert not detect_agreement("I agree with most of this")

    def test_case_sensitive(self):
        assert not detect_agreement("[agree]")

    def test_empty(self):
        assert not detect_agreement("")


class TestExtractSpec:
    def test_simple(self):
        assert extract_spec("x [SPEC]the spec[/SPEC] y") == "the spec"

    def test_strips_whitespace(self):
        assert extract_spec("[SPEC]\n  body \n[/SPEC]") == "body"

    def test_missing_open(self):
        assert extract_spec("no tags here") is None

    def test_missing_close(self):
        assert extract_spec("[SPEC] unterminated") is None

    def test_close_before_open(self):
        assert extract_spec("[/SPEC] backwards [SPEC]") is None

    def test_widest_span_preserves_nested_tags(self):
        text = "[SPEC]outer [SPEC]inner[/SPEC] tail[/SPEC]"
        assert extract_spec(text) == "outer [SPEC]inner[/SPEC] tail"

    def test_multi_close_takes_last(self):
        """Deliberate departure from the reference (which stops at the
        FIRST [/SPEC]): an embedded literal close tag does not truncate.
        Pins the divergence called out in extract_spec's docstring."""
        text = "[SPEC]a[/SPEC]b[/SPEC]"
        assert extract_spec(text) == "a[/SPEC]b"

    def test_multiline(self):
        spec = "# Title\n\nBody line 1\nBody line 2"
        assert extract_spec(f"critique\n[SPEC]\n{spec}\n[/SPEC]\ndone") == spec

    def test_malformed_detection(self):
        assert has_malformed_spec("[SPEC] oops no close")
        assert not has_malformed_spec("[SPEC]ok[/SPEC]")
        assert not has_malformed_spec("no tags")


class TestExtractTasks:
    def test_full_fields(self):
        text = """[TASK]
title: Build the API
description: REST endpoints for CRUD.
priority: high
dependencies: Schema design, Auth
estimate: 3d
[/TASK]"""
        tasks = extract_tasks(text)
        assert len(tasks) == 1
        t = tasks[0]
        assert t.title == "Build the API"
        assert t.description == "REST endpoints for CRUD."
        assert t.priority == "high"
        assert t.dependencies == ["Schema design", "Auth"]
        assert t.estimate == "3d"

    def test_multiple_blocks(self):
        text = "[TASK]\ntitle: A\n[/TASK]\nnoise\n[TASK]\ntitle: B\n[/TASK]"
        assert [t.title for t in extract_tasks(text)] == ["A", "B"]

    def test_priority_normalized(self):
        text = "[TASK]\ntitle: X\npriority: URGENT!!\n[/TASK]"
        assert extract_tasks(text)[0].priority == "medium"

    def test_priority_case_insensitive(self):
        text = "[TASK]\ntitle: X\npriority: CRITICAL\n[/TASK]"
        assert extract_tasks(text)[0].priority == "critical"

    def test_unstructured_block_uses_first_line_as_title(self):
        text = "[TASK]\nDo the thing\nwith details\n[/TASK]"
        t = extract_tasks(text)[0]
        assert t.title == "Do the thing"
        assert t.description == "with details"

    def test_empty_block_skipped(self):
        assert extract_tasks("[TASK]\n\n[/TASK]") == []

    def test_no_blocks(self):
        assert extract_tasks("just prose") == []

    def test_bulleted_fields(self):
        text = "[TASK]\n- title: Bulleted\n- priority: low\n[/TASK]"
        t = extract_tasks(text)[0]
        assert t.title == "Bulleted"
        assert t.priority == "low"


class TestCritiqueSummary:
    def test_first_line(self):
        assert get_critique_summary("First point.\nSecond.") == "First point."

    def test_strips_agree_and_spec(self):
        text = "[AGREE]\n[SPEC]hidden[/SPEC]\nActual comment"
        assert get_critique_summary(text) == "Actual comment"

    def test_truncation_boundary(self):
        # Mutation hardening: exactly max_chars passes through untruncated.
        line = "x" * 200
        assert get_critique_summary(line, max_chars=200) == line
        longer = "x" * 201
        out = get_critique_summary(longer, max_chars=200)
        assert len(out) == 200 and out.endswith("...")

    def test_empty(self):
        assert get_critique_summary("") == ""


class TestGenerateDiff:
    def test_identical(self):
        assert generate_diff("same\n", "same\n") == ""

    def test_labels_and_change(self):
        d = generate_diff("a\nb\n", "a\nc\n")
        assert "--- previous_spec" in d
        assert "+++ revised_spec" in d
        assert "-b" in d and "+c" in d


class TestMutationHardening:
    """Pins that kill the round-5 mutation-sweep survivors
    (tools/mutation_run.py; each assertion names the mutant it kills)."""

    def test_close_without_open_is_none(self):
        """Kills the find() sentinel mutant (-1 -> -2): a close tag with
        no open tag must not slice garbage from the tail of the text."""
        assert extract_spec("preamble [/SPEC] trailing") is None

    def test_all_priority_levels_accepted_verbatim(self):
        """Kills the _PRIORITIES member mutants."""
        for level in ("critical", "high", "medium", "low"):
            tasks = extract_tasks(
                f"[TASK]title: t\npriority: {level}[/TASK]"
            )
            assert tasks[0].priority == level

    def test_task_defaults_and_dict_schema(self):
        """Kills Task default mutants and the to_dict key mutants (the
        dict is export-tasks' JSON contract)."""
        t = Task()
        assert t.priority == "medium"
        assert t.to_dict() == {
            "title": "",
            "description": "",
            "priority": "medium",
            "dependencies": [],
            "estimate": "",
        }

    def test_unknown_field_not_title_like(self):
        """Kills the lstrip("-* ") charset mutant: 'xtitle' must stay an
        unknown field (only bullet markers are stripped), so the block
        falls back to first-line-as-title."""
        tasks = extract_tasks("[TASK]xtitle: foo[/TASK]")
        assert tasks[0].title == "xtitle: foo"

    def test_known_field_with_empty_value_is_skipped(self):
        """Kills the `or` -> `and` mutant on the field filter: a known
        key with an empty value must not count as a recognized field."""
        tasks = extract_tasks("[TASK]priority:\nSome task text[/TASK]")
        assert tasks[0].title == "priority:"
        assert tasks[0].description == "Some task text"
        assert tasks[0].priority == "medium"

    def test_summary_truncates_to_exactly_max_chars(self):
        """Kills the max_chars default mutant (200 -> 201)."""
        out = get_critique_summary("x" * 250)
        assert len(out) == 200
        assert out.endswith("...")

    def test_diff_labels_and_default_context(self):
        """Kills the fromfile/tofile label mutants and the n_context
        default mutant (3 -> 4): the hunk header pins 3 context lines."""
        old = "\n".join(f"line {i}" for i in range(1, 10)) + "\n"
        new = old.replace("line 5", "line five")
        diff = generate_diff(old, new)
        # Trailing \n: an exact-label pin (substring matching would let
        # a mutated "previous_specXX" label survive).
        assert "--- previous_spec\n" in diff
        assert "+++ revised_spec\n" in diff
        assert "@@ -2,7 +2,7 @@" in diff
