"""Cross-round prefix KV cache tests.

Covers the three layers of the feature:
- allocator hardening: ref-counted pages, adopt/share/free, invariant
  checks, and a model-based fuzz interleaving admit/evict/fault/free;
- the radix block index: longest-prefix lookup, LRU leaf eviction,
  page-cap enforcement;
- end-to-end: scheduler admissions prefill ONLY the delta across rounds
  with byte-identical greedy tokens (dense reference vs paged batcher,
  single device and tp=2 mesh), the mock engine pins deterministic
  hit-rates on CPU, and the CLI reports perf.prefix_cache.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
from adversarial_spec_tpu.engine.kvcache import OutOfPages, PageAllocator
from adversarial_spec_tpu.engine.prefix_cache import PrefixCache
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config


@pytest.fixture(autouse=True)
def _fresh_prefix_state():
    prefix_mod.configure(enabled=True, max_pages=0)
    prefix_mod.reset_stats()
    yield
    prefix_mod.configure(enabled=True, max_pages=0)
    prefix_mod.reset_stats()


@pytest.fixture(autouse=True)
def _spec_off(monkeypatch):
    """This module pins prefix-cache adoption/eviction semantics;
    speculation is default-on and only multiplies the jit programs each
    batcher here compiles. The spec × prefix-cache interaction (shared
    tails surviving rollback, the γ-clamp found by the replay shape) is
    pinned in tests/test_spec_batcher.py."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    return params, cfg


class TestPageAllocatorRefs:
    def test_adopt_shares_and_frees_at_zero(self):
        a = PageAllocator(8, 4)
        a.new_sequence(0)
        pages = a.extend(0, 8)
        a.new_sequence(1)
        a.adopt(1, pages, 8)
        assert all(a.refcount(p) == 2 for p in pages)
        a.free_sequence(0)
        assert all(a.refcount(p) == 1 for p in pages)
        assert a.free_pages == 6  # shared pages still live
        a.free_sequence(1)
        assert a.free_pages == 8
        a.check_invariants()

    def test_adopt_must_come_first_and_cover_pages(self):
        a = PageAllocator(8, 4)
        a.new_sequence(0)
        pages = a.extend(0, 4)
        a.new_sequence(1)
        a.extend(1, 1)
        with pytest.raises(ValueError, match="adopt must come first"):
            a.adopt(1, pages, 4)
        a.new_sequence(2)
        with pytest.raises(ValueError, match="exactly"):
            a.adopt(2, pages, 3)

    def test_adopt_unallocated_page_rejected(self):
        a = PageAllocator(8, 4)
        a.new_sequence(0)
        with pytest.raises(ValueError, match="unallocated"):
            a.adopt(0, [5], 4)

    def test_double_free_detected(self):
        a = PageAllocator(4, 4)
        a.new_sequence(0)
        [p] = a.extend(0, 4)
        a.free_sequence(0)
        with pytest.raises(RuntimeError, match="double free"):
            a.cache_unref(p)

    def test_out_of_pages_rollback_keeps_refs_clean(self):
        a = PageAllocator(2, 4)
        a.new_sequence(0)
        a.extend(0, 4)
        a.new_sequence(1)
        with pytest.raises(OutOfPages):
            a.extend(1, 12)
        a.check_invariants()
        assert a.free_pages == 1  # the rollback returned page 2's page

    def test_invariant_check_catches_corruption(self):
        a = PageAllocator(4, 4)
        a.new_sequence(0)
        [p] = a.extend(0, 4)
        a._free.append(p)  # corrupt: page both free and referenced
        with pytest.raises(RuntimeError, match="both free and referenced"):
            a.check_invariants()


class TestPrefixCacheIndex:
    def _cached(self, n_tokens, page_size=4, n_pages=32):
        a = PageAllocator(n_pages, page_size)
        c = PrefixCache(a, stats=prefix_mod.PrefixCacheStats())
        toks = list(range(n_tokens))
        a.new_sequence(0)
        a.extend(0, n_tokens)
        full = n_tokens // page_size
        c.insert(toks[: full * page_size], a.table(0)[:full])
        a.free_sequence(0)
        return a, c, toks

    def test_longest_prefix_and_divergence(self):
        a, c, toks = self._cached(12)
        m, pages = c.lookup(toks)
        assert m == 12 and len(pages) == 3
        m, pages = c.lookup(toks[:8] + [99, 99, 99, 99])
        assert m == 8
        m, pages = c.lookup([99] + toks[1:])
        assert m == 0

    def test_lookup_matches_whole_blocks_only(self):
        a, c, toks = self._cached(12)
        m, _ = c.lookup(toks[:7])  # mid-block prefix
        assert m == 4

    def test_lru_leaf_eviction_frees_pages(self):
        a, c, toks = self._cached(12)
        # Touch the chain so the leaf is the LRU *evictable* block —
        # only leaves ever go, keeping cached chains contiguous.
        assert c.evict_pages(1) == 1
        assert a.free_pages == 32 - 2
        m, _ = c.lookup(toks)
        assert m == 8  # chain shrank from the tail

    def test_eviction_skips_pages_shared_with_live_sequences(self):
        a, c, toks = self._cached(8)
        m, pages = c.lookup(toks[:8])
        a.new_sequence(7)
        a.adopt(7, pages, 8)
        # Both blocks' pages are held by seq 7: nothing can free.
        assert c.evict_pages(2) == 0
        a.free_sequence(7)
        assert c.evict_pages(2) == 2

    def test_max_pages_cap_enforced_on_insert(self):
        a = PageAllocator(32, 4)
        c = PrefixCache(a, max_pages=2, stats=prefix_mod.PrefixCacheStats())
        for base in (0, 100):
            toks = list(range(base, base + 8))
            a.new_sequence(base)
            a.extend(base, 8)
            c.insert(toks, a.table(base))
            a.free_sequence(base)
        assert c.cached_pages <= 2
        a.check_invariants()

    def test_clear_releases_everything(self):
        a, c, toks = self._cached(12)
        c.clear()
        assert c.cached_pages == 0
        assert a.free_pages == 32
        a.check_invariants()


class TestAllocatorFuzz:
    """Satellite: model-based fuzz interleaving admit / evict / fault /
    free. The model independently tracks the expected refcount of every
    page (table memberships + cache holdings) and is compared to the
    allocator after every operation, alongside check_invariants()."""

    def test_fuzz_against_refcount_model(self):
        rng = random.Random(0xC0FFEE)
        page_size = 4
        a = PageAllocator(24, page_size)
        cache = PrefixCache(a, stats=prefix_mod.PrefixCacheStats())
        live: dict[int, list[int]] = {}  # seq -> its table (model copy)
        seq_counter = 0
        bases = [
            [rng.randrange(1000) for _ in range(20)] for _ in range(3)
        ]

        def model_check():
            a.check_invariants()
            expected: dict[int, int] = {}
            for table in live.values():
                for p in table:
                    expected[p] = expected.get(p, 0) + 1
            for p in cache._by_page:
                expected[p] = expected.get(p, 0) + 1
            for p in range(a.n_pages):
                assert a.refcount(p) == expected.get(p, 0), (
                    f"page {p}: model {expected.get(p, 0)} != "
                    f"allocator {a.refcount(p)}"
                )
            assert a.free_pages == a.n_pages - len(expected)

        for _ in range(400):
            op = rng.random()
            if op < 0.5:  # admit
                toks = list(rng.choice(bases))
                toks += [rng.randrange(1000) for _ in range(rng.randrange(9))]
                matched, pages = cache.lookup(toks)
                matched = min(matched, ((len(toks) - 1) // page_size) * page_size)
                pages = pages[: matched // page_size]
                seq = seq_counter
                seq_counter += 1
                a.new_sequence(seq)
                try:
                    if matched:
                        a.adopt(seq, pages, matched)
                    delta = len(toks) - matched
                    try:
                        a.extend(seq, delta)
                    except OutOfPages:
                        need = a.pages_needed(seq, delta) - a.free_pages
                        if cache.evict_pages(need) < need:
                            raise
                        a.extend(seq, delta)
                    full = len(toks) // page_size
                    cache.insert(toks[: full * page_size], a.table(seq)[:full])
                    live[seq] = a.table(seq)
                except OutOfPages:
                    a.free_sequence(seq)
            elif op < 0.8:  # finish or fault a live sequence (same release)
                if live:
                    seq = rng.choice(list(live))
                    a.free_sequence(seq)
                    del live[seq]
            else:  # pressure eviction
                cache.evict_pages(rng.randrange(1, 4))
            model_check()

        for seq in list(live):
            a.free_sequence(seq)
        cache.clear()
        assert a.free_pages == a.n_pages
        a.check_invariants()


def _reference(params, cfg, prompt, max_new):
    from adversarial_spec_tpu.engine.generate import generate

    out = generate(
        params,
        cfg,
        [prompt],
        max_new_tokens=max_new,
        eos_ids=[],
        greedy=True,
        speculative=False,
    )
    return np.asarray(out.tokens[0, : out.n_generated[0]])


class TestSchedulerPrefixCache:
    def test_three_round_replay_prefills_only_the_delta(self, tiny_model):
        """One batcher across 3 'rounds' of a growing prompt: rounds 2+
        must prefill exactly the page-rounded delta, produce the same
        greedy tokens as the dense reference, and report cached_tokens.
        """
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=8, page_size=16,
            prefix_cache=True,
        )
        prompt = [((i * 7) % 400) + 3 for i in range(96)]
        prefills, cached = [], []
        for rnd in range(3):
            before = prefix_mod.stats.prefilled_tokens
            b.submit(
                SchedRequest(req_id=0, prompt_ids=list(prompt),
                             max_new_tokens=8)
            )
            [res] = b.run_all()
            prefills.append(prefix_mod.stats.prefilled_tokens - before)
            cached.append(res.cached_tokens)
            np.testing.assert_array_equal(
                res.tokens, _reference(params, cfg, prompt, 8),
                err_msg=f"round {rnd}",
            )
            assert res.prefill_time_s > 0
            b.allocator.check_invariants()
            prompt = prompt + [((i * 5) % 400) + 3 for i in range(32)]
        # Round 1: 96 tokens → 6 pages, all prefilled. Rounds 2/3: all
        # previously-seen blocks adopted; only the 32-token delta runs.
        assert prefills == [96, 32, 32]
        assert cached == [0, 96, 128]

    def test_same_round_opponents_share_prefix(self, tiny_model):
        """Two same-prompt requests in one drain: the second admission
        reuses the first's blocks (round-1 within-batch sharing)."""
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=8, page_size=16,
            prefix_cache=True,
        )
        prompt = [((i * 11) % 400) + 3 for i in range(64)]
        for i in range(2):
            b.submit(
                SchedRequest(req_id=i, prompt_ids=list(prompt),
                             max_new_tokens=6)
            )
        results = b.run_all()
        ref = _reference(params, cfg, prompt, 6)
        for r in results:
            np.testing.assert_array_equal(r.tokens, ref)
        assert results[0].cached_tokens == 0
        # 64 tokens; last block is held back (last-token logits rule).
        assert results[1].cached_tokens == 48
        b.allocator.check_invariants()

    def test_cache_disabled_matches_enabled_tokens(self, tiny_model):
        """Greedy token parity: paged batcher with the cache on vs off
        (off = the original left-padded admission layout)."""
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        params, cfg = tiny_model
        prompts = [
            [((i * 13) % 400) + 3 for i in range(40)],
            [((i * 3) % 400) + 5 for i in range(25)],
        ]
        outs = {}
        for enabled in (False, True):
            b = ContinuousBatcher(
                params, cfg, max_batch=2, max_new_cap=8, page_size=16,
                prefix_cache=enabled,
            )
            for i, p in enumerate(prompts):
                b.submit(
                    SchedRequest(req_id=i, prompt_ids=list(p),
                                 max_new_tokens=8)
                )
            outs[enabled] = [r.tokens.tolist() for r in b.run_all()]
        assert outs[True] == outs[False]

    def test_full_prompt_hit_still_samples_first_token(self, tiny_model):
        """An exact-repeat prompt (100% cacheable) must still re-run its
        last token for logits and decode correctly."""
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=1, max_new_cap=8, page_size=16,
            prefix_cache=True,
        )
        prompt = [((i * 7) % 400) + 3 for i in range(32)]  # page-aligned
        ref = _reference(params, cfg, prompt, 6)
        for _ in range(2):
            b.submit(
                SchedRequest(req_id=0, prompt_ids=list(prompt),
                             max_new_tokens=6)
            )
            [res] = b.run_all()
            np.testing.assert_array_equal(res.tokens, ref)
        assert res.cached_tokens == 16  # 32 minus the held-back block

    def test_fault_releases_refs_without_corrupting_cache(self, tiny_model):
        """Chaos at the scheduler seam evicts a slot whose prompt pages
        are shared with the prefix cache: the eviction must only drop
        references (invariants hold) and a replay must still hit."""
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )
        from adversarial_spec_tpu.resilience import injector

        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=1, max_new_cap=8, page_size=16,
            prefix_cache=True,
        )
        prompt = [((i * 7) % 400) + 3 for i in range(64)]
        b.submit(
            SchedRequest(req_id=0, prompt_ids=list(prompt), max_new_tokens=8)
        )
        b.run_all()
        cached_before = b.prefix_cache.cached_pages
        injector.install(
            injector.FaultInjector(
                injector.parse_chaos_spec("bug@scheduler_chunk:times=1")
            )
        )
        try:
            b.submit(
                SchedRequest(req_id=1, prompt_ids=list(prompt),
                             max_new_tokens=8)
            )
            [res] = b.run_all()
        finally:
            injector.reset()
        assert res.error is not None and res.fault_kind == "bug"
        b.allocator.check_invariants()
        assert b.prefix_cache.cached_pages >= cached_before
        # The cache survived the fault: a clean replay still hits.
        b.submit(
            SchedRequest(req_id=2, prompt_ids=list(prompt), max_new_tokens=8)
        )
        [res] = b.run_all()
        assert res.error is None and res.cached_tokens > 0
        np.testing.assert_array_equal(
            res.tokens, _reference(params, cfg, prompt, 8)
        )
        b.allocator.check_invariants()

    def test_kv_alloc_chaos_contained_with_cache_enabled(self, tiny_model):
        """An injected kv_alloc fault on a cache-enabled admission is
        isolated to that request; allocator state stays clean and later
        admissions (which exercise eviction paths) proceed."""
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )
        from adversarial_spec_tpu.resilience import injector

        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=1, max_new_cap=8, page_size=16,
            prefix_cache=True,
        )
        prompt = [((i * 7) % 400) + 3 for i in range(48)]
        injector.install(
            injector.FaultInjector(
                injector.parse_chaos_spec("bug@kv_alloc:times=1")
            )
        )
        try:
            b.submit(
                SchedRequest(req_id=0, prompt_ids=list(prompt),
                             max_new_tokens=4)
            )
            b.submit(
                SchedRequest(req_id=1, prompt_ids=list(prompt),
                             max_new_tokens=4)
            )
            results = b.run_all()
        finally:
            injector.reset()
        assert results[0].error is not None
        assert results[1].error is None
        b.allocator.check_invariants()

    def test_eviction_under_pool_pressure(self, tiny_model):
        """A pool sized for ~one resident: cached blocks from earlier
        requests must LRU-evict (not deadlock admission) when a new
        divergent prompt needs their pages."""
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=1, max_new_cap=8, page_size=16,
            capacity_tokens=256, prefix_cache=True,
        )
        for i in range(3):  # three DISJOINT prompts; pool holds ~one
            prompt = [((i + 2) * 97 + j * 7) % 400 + 3 for j in range(96)]
            b.submit(
                SchedRequest(req_id=i, prompt_ids=prompt, max_new_tokens=4)
            )
            [res] = b.run_all()
            assert res.error is None, res.error
            np.testing.assert_array_equal(
                res.tokens, _reference(params, cfg, prompt, 4)
            )
            b.allocator.check_invariants()
        assert prefix_mod.stats.evicted_pages > 0

    def test_timeout_mid_prefill_admission_frees_pages_and_refs(
        self, tiny_model
    ):
        """run_all timeout expiry with a cache-enabled, MID-PREFILL
        admission: the admission's fresh pages free, its refs on the
        adopted cached prefix drop (cache blocks themselves survive),
        allocator invariants hold, and every queued request still gets
        its zero-token SchedResult."""
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=1, max_new_cap=8, page_size=16,
            prefix_cache=True,
        )
        # Round 1 populates the cache with this prompt's blocks.
        head = [((i * 7) % 400) + 3 for i in range(96)]
        b.submit(SchedRequest(req_id=0, prompt_ids=list(head),
                              max_new_tokens=4))
        [r1] = b.run_all()
        assert r1.error is None
        free0 = b.allocator.free_pages
        cached0 = b.prefix_cache.cached_pages
        # Round 2: a multi-chunk prompt that ADOPTS the cached head,
        # plus a queued follower. _admit reserves pages and leaves the
        # long admission mid-prefill (remaining > one admission chunk).
        long_prompt = head + [((i * 5) % 400) + 3 for i in range(600)]
        b.submit(SchedRequest(req_id=0, prompt_ids=long_prompt,
                              max_new_tokens=8))
        b.submit(SchedRequest(req_id=1, prompt_ids=[2, 6],
                              max_new_tokens=8))
        b._admit()
        adm = b._admission
        assert adm is not None and adm.matched == 96
        assert adm.remaining > 0  # genuinely mid-prefill
        assert b.allocator.free_pages < free0  # pages reserved
        # Expired deadline at loop entry: the drain must unwind the
        # admission, not decode it.
        results = b.run_all(timeout_s=1e-9)
        assert [r.req_id for r in results] == [0, 1]
        assert all(r.n_generated == 0 and r.error is None for r in results)
        # All of the admission's pages returned; the cache kept its own
        # refs (blocks survive for the next drain to adopt).
        assert b.allocator.free_pages == free0
        assert b.prefix_cache.cached_pages == cached0
        b.allocator.check_invariants()
        # The cache is still warm: a fresh drain adopts the head again.
        b.submit(SchedRequest(req_id=0, prompt_ids=list(head),
                              max_new_tokens=4))
        [r3] = b.run_all()
        assert r3.error is None
        assert r3.cached_tokens > 0
        np.testing.assert_array_equal(
            r3.tokens, _reference(params, cfg, head, 4)
        )


class TestGenerateSharedPrefix:
    def test_partial_share_parity_dense_and_paged(
        self, tiny_model, monkeypatch
    ):
        """Equal-length prompts with a shared prefix: prefilling the
        prefix once (B=1) and tiling must not change greedy tokens, on
        the dense and paged paths alike."""
        import adversarial_spec_tpu.engine.generate as G

        params, cfg = tiny_model
        monkeypatch.setattr(G, "PREFILL_CHUNK", 32)
        base = [((i * 7) % 400) + 3 for i in range(120)]
        prompts = [base[:100] + [10 + i] * 20 for i in range(3)]
        kw = dict(
            max_new_tokens=6, eos_ids=[], greedy=True, speculative=False
        )
        ref = G.generate(params, cfg, prompts, share_prefix=False, **kw)
        saved0 = prefix_mod.stats.saved_tokens
        out = G.generate(params, cfg, prompts, share_prefix=True, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)
        assert prefix_mod.stats.saved_tokens > saved0
        outp = G.generate(
            params, cfg, prompts, share_prefix=True, paged=True,
            page_size=16, **kw
        )
        np.testing.assert_array_equal(ref.tokens, outp.tokens)

    def test_tp2_mesh_parity_with_share_enabled(self, tiny_model):
        """Paged greedy decode on a tp=2 mesh with share_prefix enabled
        (the default) must match the single-device share-disabled
        reference — the prefix machinery must not perturb mesh paths."""
        if len(jax.devices()) < 2:
            pytest.skip("requires 2 virtual devices")
        from adversarial_spec_tpu.engine.generate import generate
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompt = [((i * 7) % 400) + 3 for i in range(24)]
        prompts = [list(prompt), list(prompt)]
        kw = dict(
            max_new_tokens=6, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
        )
        ref = generate(params, cfg, prompts, share_prefix=False, **kw)
        mesh = make_mesh({"tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh, share_prefix=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)


class TestMockEngineHitRates:
    def _chat(self, engine, user="hello " * 60, model="mock://critic"):
        from adversarial_spec_tpu.engine.types import (
            ChatRequest,
            SamplingParams,
        )

        req = ChatRequest(model=model, system="sys " * 40, user=user)
        return engine.chat([req], SamplingParams())[0]

    def test_deterministic_hits_and_cached_tokens(self):
        from adversarial_spec_tpu.engine.mock import MockEngine

        eng = MockEngine()
        c1 = self._chat(eng)
        assert c1.usage.cached_tokens == 0
        assert prefix_mod.stats.misses == 1
        c2 = self._chat(eng)
        assert prefix_mod.stats.hits == 1
        assert c2.usage.cached_tokens > 0
        assert c2.text == c1.text
        # A diverging prompt re-hits exactly the shared head.
        c3 = self._chat(eng, user="hello " * 60 + "MORE " * 30)
        assert c3.usage.cached_tokens >= c2.usage.cached_tokens

    def test_disabled_cache_counts_full_prefill(self):
        from adversarial_spec_tpu.engine.mock import MockEngine

        prefix_mod.configure(enabled=False)
        eng = MockEngine()
        c1 = self._chat(eng)
        c2 = self._chat(eng)
        assert c1.usage.cached_tokens == 0 and c2.usage.cached_tokens == 0
        assert prefix_mod.stats.lookups == 0
        assert prefix_mod.stats.prefilled_tokens > 0

    def test_three_round_debate_replay_saves_60_percent(self):
        """THE acceptance criterion: a 3-round mock debate replay
        prefills ≥60% fewer tokens in rounds 2+ with the cache on, with
        byte-identical transcripts, and the counters account exactly for
        the savings (prefilled_on + saved_on == prefilled_off)."""
        from adversarial_spec_tpu.debate.core import run_round
        from adversarial_spec_tpu.engine import dispatch

        spec = "# Spec\n" + "\n".join(
            f"Requirement {i}: the system shall handle case {i}."
            for i in range(40)
        )

        def replay(enabled):
            dispatch.clear_engine_cache()
            prefix_mod.configure(enabled=enabled)
            prefix_mod.reset_stats()
            cur, transcripts, per_round = spec, [], []
            for rn in range(1, 4):
                before = prefix_mod.stats.prefilled_tokens
                res = run_round(cur, ["mock://critic"], round_num=rn)
                per_round.append(
                    prefix_mod.stats.prefilled_tokens - before
                )
                transcripts.append([r.critique for r in res.responses])
                rev = next(
                    (
                        r.revised_spec
                        for r in reversed(res.successful)
                        if r.revised_spec
                    ),
                    None,
                )
                cur = rev or cur
            return transcripts, per_round, prefix_mod.stats.saved_tokens

        t_on, pr_on, saved_on = replay(True)
        t_off, pr_off, _ = replay(False)
        assert t_on == t_off  # byte-identical transcripts
        for r in (1, 2):  # rounds 2 and 3
            assert 1 - pr_on[r] / pr_off[r] >= 0.6, (pr_on, pr_off)
        assert sum(pr_on) + saved_on == sum(pr_off)


class TestCliPrefixFlags:
    SPEC = "# S\n" + "body line\n" * 50

    def _run(self, argv, monkeypatch, capsys):
        import io
        import json as json_mod

        from adversarial_spec_tpu import cli

        monkeypatch.setattr("sys.stdin", io.StringIO(self.SPEC))
        code = cli.main(argv)
        out, err = capsys.readouterr()
        return code, json_mod.loads(out), err

    def test_json_carries_prefix_cache_section(self, monkeypatch, capsys):
        code, data, _ = self._run(
            ["critique", "--models", "mock://critic", "--json"],
            monkeypatch, capsys,
        )
        assert code == 0
        snap = data["perf"]["prefix_cache"]
        assert snap["enabled"] is True
        assert snap["lookups"] == 1
        assert "cached_tokens" in data["results"][0]

    def test_no_prefix_cache_flag_disables(self, monkeypatch, capsys):
        code, data, _ = self._run(
            [
                "critique", "--models", "mock://critic", "--json",
                "--no-prefix-cache",
            ],
            monkeypatch, capsys,
        )
        assert code == 0
        snap = data["perf"]["prefix_cache"]
        assert snap["enabled"] is False
        assert snap["lookups"] == 0 and snap["prefilled_tokens"] > 0

    def test_second_round_reports_hits(self, monkeypatch, capsys):
        import io

        from adversarial_spec_tpu import cli

        argv = ["critique", "--models", "mock://critic", "--json"]
        monkeypatch.setattr("sys.stdin", io.StringIO(self.SPEC))
        assert cli.main(argv) == 0
        capsys.readouterr()
        monkeypatch.setattr("sys.stdin", io.StringIO(self.SPEC))
        assert cli.main(argv + ["--round", "2"]) == 0
        out, err = capsys.readouterr()
        import json as json_mod

        data = json_mod.loads(out)
        snap = data["perf"]["prefix_cache"]
        assert snap["hits"] == 1 and snap["saved_tokens"] > 0
        assert "prefix cache:" in err
