"""Profile and global-config tests (reference analog: profile sections of
tests/test_providers.py — flag-over-profile precedence)."""

import argparse

import pytest

from adversarial_spec_tpu.debate.profiles import (
    apply_profile,
    list_profiles,
    load_global_config,
    load_profile,
    save_global_config,
    save_profile,
)


class TestProfiles:
    def test_save_load_roundtrip(self):
        save_profile("fast", {"models": ["mock://agree"], "doc_type": "tech"})
        p = load_profile("fast")
        assert p == {"models": ["mock://agree"], "doc_type": "tech"}

    def test_unknown_fields_rejected_on_save(self):
        with pytest.raises(ValueError, match="unknown profile fields"):
            save_profile("bad", {"nonsense": 1})

    def test_unknown_fields_filtered_on_load(self, tmp_path, monkeypatch):
        from adversarial_spec_tpu.debate import profiles as mod

        mod.PROFILES_DIR.mkdir(parents=True, exist_ok=True)
        (mod.PROFILES_DIR / "hand.json").write_text(
            '{"doc_type": "prd", "hacked": true}'
        )
        assert load_profile("hand") == {"doc_type": "prd"}

    def test_load_missing_raises(self):
        with pytest.raises(FileNotFoundError):
            load_profile("absent")

    def test_list_profiles(self):
        save_profile("a", {"doc_type": "prd"})
        save_profile("b", {"focus": "cost"})
        profs = list_profiles()
        assert set(profs) == {"a", "b"}

    def test_list_profiles_empty(self):
        assert list_profiles() == {}


class TestApplyProfile:
    def _args(self, **kw):
        ns = argparse.Namespace(
            models=None,
            doc_type=None,
            focus=None,
            persona=None,
            preserve_intent=False,
            timeout=None,
            max_new_tokens=None,
            temperature=None,
            mesh=None,
            dtype=None,
        )
        for k, v in kw.items():
            setattr(ns, k, v)
        return ns

    def test_fills_unset_only(self):
        args = self._args(doc_type="tech")
        applied = apply_profile(
            args, {"doc_type": "prd", "focus": "security"}
        )
        assert args.doc_type == "tech"  # explicit flag wins
        assert args.focus == "security"
        assert applied == ["focus"]

    def test_preserve_intent_false_is_fillable(self):
        args = self._args()
        apply_profile(args, {"preserve_intent": True})
        assert args.preserve_intent is True

    def test_unknown_profile_keys_ignored(self):
        args = self._args()
        applied = apply_profile(args, {"rogue": 1})
        assert applied == []
        assert not hasattr(args, "rogue")


class TestGlobalConfig:
    def test_roundtrip(self):
        save_global_config({"default_mesh": {"tp": 4}})
        assert load_global_config() == {"default_mesh": {"tp": 4}}

    def test_missing_returns_empty(self):
        assert load_global_config() == {}

    def test_corrupt_returns_empty(self, tmp_path):
        from adversarial_spec_tpu.debate import profiles as mod

        mod.GLOBAL_CONFIG_PATH.parent.mkdir(parents=True, exist_ok=True)
        mod.GLOBAL_CONFIG_PATH.write_text("{broken")
        assert load_global_config() == {}


class TestMutationHardening:
    """Pins that kill the round-5 mutation-sweep survivors
    (tools/mutation_run.py; each assertion names the mutant it kills)."""

    def test_profile_fields_pinned(self):
        """Kills PROFILE_FIELDS member mutants: the field set is the
        save-validation + load-filter contract."""
        from adversarial_spec_tpu.debate.profiles import PROFILE_FIELDS

        assert PROFILE_FIELDS == (
            "models",
            "doc_type",
            "focus",
            "persona",
            "preserve_intent",
            "timeout",
            "max_new_tokens",
            "temperature",
        )

    def test_config_paths_pinned(self):
        """Kills path-component mutants (source-pinned: conftest patches
        the live constants)."""
        from pathlib import Path

        from adversarial_spec_tpu.debate import profiles as mod

        src = Path(mod.__file__).read_text()
        assert (
            'Path.home() / ".config" / "adversarial-spec-tpu" / "profiles"'
            in src
        )
        assert '"adversarial-spec-tpu" / "config.json"' in src

    def test_save_profile_nested_dir_and_return(self, tmp_path):
        """Kills the mkdir flag flips and the `return path` -> None."""
        nested = tmp_path / "deep" / "profiles"
        p = save_profile("n", {"doc_type": "tech"}, profiles_dir=nested)
        assert p is not None and p.is_file()
        p2 = save_profile("n", {"doc_type": "prd"}, profiles_dir=nested)
        assert p2 == p

    def test_error_messages_name_the_problem(self, tmp_path):
        with pytest.raises(ValueError, match="unknown profile fields"):
            save_profile("bad", {"zzz": 1}, profiles_dir=tmp_path)
        with pytest.raises(FileNotFoundError, match="not found at"):
            load_profile("ghost", profiles_dir=tmp_path)

    def test_explicit_list_flag_beats_profile(self):
        """Kills the unset-detection mutants (`and` -> `or`, dropped
        `not`): a NON-empty list is an explicit user choice and must
        never be overridden; an empty one is unset and must be."""
        args = argparse.Namespace(models=["tpu://chosen"], focus=None)
        applied = apply_profile(
            args, {"models": ["mock://p"], "focus": "security"}
        )
        assert args.models == ["tpu://chosen"]
        assert args.focus == "security"
        assert applied == ["focus"]
        args2 = argparse.Namespace(models=[])
        assert apply_profile(args2, {"models": ["mock://p"]}) == ["models"]
        assert args2.models == ["mock://p"]

    def test_save_global_config_nested_dir_and_return(self, tmp_path):
        target = tmp_path / "cfg" / "dir" / "config.json"
        p = save_global_config({"a": 1}, config_path=target)
        assert p == target and p.is_file()
        p2 = save_global_config({"a": 2}, config_path=target)
        assert p2 == target
