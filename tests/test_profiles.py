"""Profile and global-config tests (reference analog: profile sections of
tests/test_providers.py — flag-over-profile precedence)."""

import argparse

import pytest

from adversarial_spec_tpu.debate.profiles import (
    apply_profile,
    list_profiles,
    load_global_config,
    load_profile,
    save_global_config,
    save_profile,
)


class TestProfiles:
    def test_save_load_roundtrip(self):
        save_profile("fast", {"models": ["mock://agree"], "doc_type": "tech"})
        p = load_profile("fast")
        assert p == {"models": ["mock://agree"], "doc_type": "tech"}

    def test_unknown_fields_rejected_on_save(self):
        with pytest.raises(ValueError, match="unknown profile fields"):
            save_profile("bad", {"nonsense": 1})

    def test_unknown_fields_filtered_on_load(self, tmp_path, monkeypatch):
        from adversarial_spec_tpu.debate import profiles as mod

        mod.PROFILES_DIR.mkdir(parents=True, exist_ok=True)
        (mod.PROFILES_DIR / "hand.json").write_text(
            '{"doc_type": "prd", "hacked": true}'
        )
        assert load_profile("hand") == {"doc_type": "prd"}

    def test_load_missing_raises(self):
        with pytest.raises(FileNotFoundError):
            load_profile("absent")

    def test_list_profiles(self):
        save_profile("a", {"doc_type": "prd"})
        save_profile("b", {"focus": "cost"})
        profs = list_profiles()
        assert set(profs) == {"a", "b"}

    def test_list_profiles_empty(self):
        assert list_profiles() == {}


class TestApplyProfile:
    def _args(self, **kw):
        ns = argparse.Namespace(
            models=None,
            doc_type=None,
            focus=None,
            persona=None,
            preserve_intent=False,
            timeout=None,
            max_new_tokens=None,
            temperature=None,
            mesh=None,
            dtype=None,
        )
        for k, v in kw.items():
            setattr(ns, k, v)
        return ns

    def test_fills_unset_only(self):
        args = self._args(doc_type="tech")
        applied = apply_profile(
            args, {"doc_type": "prd", "focus": "security"}
        )
        assert args.doc_type == "tech"  # explicit flag wins
        assert args.focus == "security"
        assert applied == ["focus"]

    def test_preserve_intent_false_is_fillable(self):
        args = self._args()
        apply_profile(args, {"preserve_intent": True})
        assert args.preserve_intent is True

    def test_unknown_profile_keys_ignored(self):
        args = self._args()
        applied = apply_profile(args, {"rogue": 1})
        assert applied == []
        assert not hasattr(args, "rogue")


class TestGlobalConfig:
    def test_roundtrip(self):
        save_global_config({"default_mesh": {"tp": 4}})
        assert load_global_config() == {"default_mesh": {"tp": 4}}

    def test_missing_returns_empty(self):
        assert load_global_config() == {}

    def test_corrupt_returns_empty(self, tmp_path):
        from adversarial_spec_tpu.debate import profiles as mod

        mod.GLOBAL_CONFIG_PATH.parent.mkdir(parents=True, exist_ok=True)
        mod.GLOBAL_CONFIG_PATH.write_text("{broken")
        assert load_global_config() == {}
