"""Prompt-library integrity tests (reference analog: tests/test_prompts.py —
content assertions on placeholders, keys, and lookup normalization)."""

from adversarial_spec_tpu.debate import prompts


class TestConstants:
    def test_six_focus_areas(self):
        assert set(prompts.FOCUS_AREAS) == {
            "security",
            "scalability",
            "performance",
            "ux",
            "reliability",
            "cost",
        }

    def test_ten_personas(self):
        assert len(prompts.PERSONAS) == 10
        assert "security-engineer" in prompts.PERSONAS
        assert "legal-compliance" in prompts.PERSONAS

    def test_personas_start_with_you_are(self):
        for key, text in prompts.PERSONAS.items():
            assert text.startswith("You are"), key

    def test_round_placeholder_in_templates(self):
        assert "{round}" in prompts.REVIEW_PROMPT_TEMPLATE
        assert "{spec}" in prompts.REVIEW_PROMPT_TEMPLATE
        assert "{round}" in prompts.PRESS_PROMPT_TEMPLATE
        assert "{spec}" in prompts.PRESS_PROMPT_TEMPLATE
        assert "{spec}" in prompts.EXPORT_TASKS_PROMPT

    def test_templates_format_cleanly(self):
        out = prompts.REVIEW_PROMPT_TEMPLATE.format(round=3, spec="S")
        assert "Debate round 3" in out and "S" in out

    def test_system_prompts_carry_protocol(self):
        for p in (
            prompts.SYSTEM_PROMPT_PRD,
            prompts.SYSTEM_PROMPT_TECH,
            prompts.SYSTEM_PROMPT_GENERIC,
        ):
            assert "[AGREE]" in p
            assert "[SPEC]" in p and "[/SPEC]" in p


class TestGetSystemPrompt:
    def test_doc_type_selection(self):
        assert "Product Requirements" in prompts.get_system_prompt("prd")
        assert "technical specification" in prompts.get_system_prompt("tech")
        assert prompts.get_system_prompt("nonsense") == prompts.get_system_prompt(
            "generic"
        )

    def test_focus_appended(self):
        p = prompts.get_system_prompt("tech", focus="security")
        assert "PRIORITY FOCUS: security" in p

    def test_unknown_focus_ignored(self):
        base = prompts.get_system_prompt("tech")
        assert prompts.get_system_prompt("tech", focus="nope") == base

    def test_persona_key_lookup_and_normalization(self):
        p = prompts.get_system_prompt("prd", persona="Security Engineer")
        assert p.startswith(prompts.PERSONAS["security-engineer"])
        p2 = prompts.get_system_prompt("prd", persona="security_engineer")
        assert p2.startswith(prompts.PERSONAS["security-engineer"])

    def test_freeform_persona_passthrough(self):
        custom = "You are a grumpy kernel maintainer."
        p = prompts.get_system_prompt("tech", persona=custom)
        assert p.startswith(custom)

    def test_preserve_intent_appended(self):
        p = prompts.get_system_prompt("prd", preserve_intent=True)
        assert "preserve the author's intent" in p

    def test_all_options_compose(self):
        p = prompts.get_system_prompt(
            "tech",
            focus="reliability",
            persona="qa-engineer",
            preserve_intent=True,
        )
        assert p.startswith(prompts.PERSONAS["qa-engineer"])
        assert "PRIORITY FOCUS: reliability" in p
        assert "preserve the author's intent" in p


class TestDocTypeName:
    def test_names(self):
        assert prompts.get_doc_type_name("prd") == "Product Requirements Document"
        assert prompts.get_doc_type_name("tech") == "Technical Specification"
        assert prompts.get_doc_type_name("other") == "Document"
