"""Weight-only quantization tests: int8 and packed int4."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine.generate import generate
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config
from adversarial_spec_tpu.ops.quant import (
    dequantize,
    is_quantized,
    is_quantized_int4,
    matmul,
    pack_int4,
    quantize_int4,
    quantize_int8,
    quantize_params,
    unpack_int4,
)


class TestQuantizeInt8:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.key(0), (64, 32), jnp.float32)
        qw = quantize_int8(w)
        assert qw["q"].dtype == jnp.int8
        assert qw["scale"].shape == (1, 32)
        deq = qw["q"].astype(jnp.float32) * qw["scale"]
        # Per-channel symmetric: max error ≤ scale/2 per element.
        err = jnp.abs(deq - w)
        assert float((err <= qw["scale"] / 2 + 1e-6).mean()) == 1.0

    def test_matmul_dispatch(self):
        w = jax.random.normal(jax.random.key(1), (16, 8), jnp.float32)
        x = jax.random.normal(jax.random.key(2), (4, 16), jnp.float32)
        plain = matmul(x, w)
        quant = matmul(x, quantize_int8(w))
        np.testing.assert_array_equal(np.asarray(plain), np.asarray(x @ w))
        # Quantized result close to full precision.
        rel = float(
            jnp.linalg.norm(quant - plain) / jnp.linalg.norm(plain)
        )
        assert rel < 0.02

    def test_layer_stacked_scales(self):
        w = jax.random.normal(jax.random.key(3), (2, 16, 8), jnp.float32)
        qw = quantize_int8(w)
        assert qw["scale"].shape == (2, 1, 8)

    def test_is_quantized(self):
        assert not is_quantized(jnp.zeros((2, 2)))
        assert is_quantized(quantize_int8(jnp.ones((2, 2))))


def _np_pack_int4(q: np.ndarray) -> np.ndarray:
    """Numpy oracle of ops.quant.pack_int4: two's-complement nibble
    packing along the contraction (-2) axis, zero-padded to even."""
    rows = q.shape[-2]
    if rows % 2:
        pad = [(0, 0)] * q.ndim
        pad[-2] = (0, 1)
        q = np.pad(q, pad)
    lo = q[..., 0::2, :].astype(np.int16) & 0x0F
    hi = (q[..., 1::2, :].astype(np.int16) << 4) & 0xF0
    return (lo | hi).astype(np.uint8).view(np.int8)


def _np_unpack_int4(packed: np.ndarray, rows: int) -> np.ndarray:
    lo = ((packed.astype(np.int8) << 4).astype(np.int8) >> 4)
    hi = packed.astype(np.int8) >> 4
    q = np.stack([lo, hi], axis=-2)
    q = q.reshape(q.shape[:-3] + (q.shape[-3] * 2, q.shape[-1]))
    return q[..., :rows, :]


class TestQuantizeInt4:
    def test_pack_unpack_exact_roundtrip(self):
        rng = np.random.default_rng(0)
        q = rng.integers(-7, 8, size=(9, 5), dtype=np.int8)
        back = unpack_int4(pack_int4(jnp.asarray(q)), 9)
        np.testing.assert_array_equal(np.asarray(back), q)

    def test_fuzz_roundtrip_vs_numpy_oracle(self):
        """Property fuzz (the ISSUE-15 satellite): random shapes
        (stacked and flat, ODD and even contraction widths) and extreme
        magnitudes round-trip exactly against an independent numpy
        oracle — packed bytes AND dequantized values."""
        rng = np.random.default_rng(7)
        for case in range(60):
            r = int(rng.integers(1, 18))
            c = int(rng.integers(1, 10))
            shape = (r, c) if case % 3 else (int(rng.integers(1, 4)), r, c)
            # Extreme scales: denormal-tiny through near-f32-max.
            mag = 10.0 ** float(rng.integers(-30, 30))
            w = (rng.standard_normal(shape) * mag).astype(np.float32)
            if case % 7 == 0:
                w[..., 0] = 0.0  # a whole zero output channel
            qd = quantize_int4(jnp.asarray(w))
            assert qd["q4"].dtype == jnp.int8
            assert qd["q4"].shape[-2] == (r + 1) // 2
            assert qd["scale"].shape == shape[:-2] + (1, c)
            # Oracle: same per-channel symmetric int4 quantization.
            amax = np.max(np.abs(w), axis=-2, keepdims=True)
            scale = np.maximum(amax, 1e-8) / 7.0
            q_ref = np.clip(np.round(w / scale), -7, 7).astype(np.int8)
            np.testing.assert_array_equal(
                np.asarray(qd["q4"]), _np_pack_int4(q_ref)
            )
            # Unpack matches the oracle and the original ints exactly.
            np.testing.assert_array_equal(
                np.asarray(unpack_int4(qd["q4"], r)),
                _np_unpack_int4(np.asarray(qd["q4"]), r),
            )
            np.testing.assert_array_equal(
                np.asarray(unpack_int4(qd["q4"], r)), q_ref
            )
            # Dequant error bounded by half a step per element.
            deq = np.asarray(dequantize(qd, rows=r))
            assert np.all(
                np.abs(deq - w) <= np.asarray(scale) / 2 + 1e-6 * mag
            )

    def test_matmul_dispatch_matches_dequantized_dense(self):
        w = jax.random.normal(jax.random.key(1), (17, 8), jnp.float32)
        x = jax.random.normal(jax.random.key(2), (4, 17), jnp.float32)
        q4 = quantize_int4(w)
        got = matmul(x, q4)
        want = jnp.matmul(x, dequantize(q4, rows=17))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )
        rel = float(jnp.linalg.norm(got - x @ w) / jnp.linalg.norm(x @ w))
        assert rel < 0.1  # 4-bit: coarser than int8 but bounded

    def test_is_quantized_int4(self):
        assert is_quantized_int4(quantize_int4(jnp.ones((2, 2))))
        assert not is_quantized_int4(quantize_int8(jnp.ones((2, 2))))
        assert not is_quantized(quantize_int4(jnp.ones((2, 2))))

    def test_quantize_params_int4_selective_and_validated(self):
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        qp = quantize_params(params, fmt="int4")
        assert is_quantized_int4(qp["layers"]["wq"])
        assert is_quantized_int4(qp["lm_head"])
        assert not is_quantized_int4(qp["embed"])
        with pytest.raises(ValueError, match="int8, int4"):
            quantize_params(params, fmt="int2")

    def test_int4_halves_int8_matmul_bytes(self):
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        q8 = quantize_params(params, fmt="int8")["layers"]["wq"]
        q4 = quantize_params(params, fmt="int4")["layers"]["wq"]
        assert q4["q4"].nbytes * 2 == q8["q"].nbytes

    def test_int4_sharding_rules(self):
        """q4 shards like the weight, scale keeps only the output
        axis — the same contract the int8 dict leaves already pin."""
        from jax.sharding import PartitionSpec as P

        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import param_shardings

        if len(jax.devices()) < 2:
            pytest.skip("needs >1 device")
        mesh = make_mesh({"tp": 2})
        cfg = get_config("llama", "tiny")
        shapes = jax.eval_shape(
            lambda: quantize_params(
                T.init_params(jax.random.key(0), cfg, jnp.float32),
                fmt="int4",
            )
        )
        sh = param_shardings(mesh, shapes)
        assert sh["layers"]["wq"]["q4"].spec == P(None, None, "tp")
        assert sh["layers"]["wq"]["scale"].spec == P(None, None, "tp")
        assert sh["layers"]["wo"]["q4"].spec == P(None, "tp", None)
        assert sh["layers"]["wo"]["scale"].spec == P(None, None, None)

    def test_int4_generate_matches_dense_of_same_quant(self):
        """Dequant-in-kernel parity: int4 params through the jitted
        generate() produce the same greedy tokens as an eager dense
        matmul over the dequantized weights would predict — pinned by
        running the SAME quantized params on the same mesh twice."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        qp = quantize_params(params, fmt="int4")
        out = generate(
            qp,
            cfg,
            [[1, 2, 3, 4]],
            max_new_tokens=6,
            eos_ids=[],
            pad_id=0,
            greedy=True,
        )
        assert out.tokens.shape[0] == 1
        assert int(out.n_generated[0]) == 6


class TestQuantizedModel:
    def test_quantize_params_selective(self):
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        qp = quantize_params(params)
        assert is_quantized(qp["layers"]["wq"])
        assert is_quantized(qp["lm_head"])
        assert not is_quantized(qp["embed"])
        assert qp["layers"]["attn_norm"].dtype == jnp.float32

    def test_quantized_forward_close_to_fp(self):
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        qp = quantize_params(params)
        ids = jnp.array([[1, 7, 42, 9]], jnp.int32)
        cache = T.init_cache(cfg, 1, 4, dtype=jnp.float32)
        pos = jnp.arange(4, dtype=jnp.int32)[None]
        kv = jnp.ones((1, 4), bool)
        ref, _ = T.forward(params, cfg, ids, pos, cache, jnp.int32(0), kv)
        cache2 = T.init_cache(cfg, 1, 4, dtype=jnp.float32)
        out, _ = T.forward(qp, cfg, ids, pos, cache2, jnp.int32(0), kv)
        # Cosine similarity of logits stays high under int8 weights.
        a = np.asarray(ref).reshape(-1)
        b = np.asarray(out).reshape(-1)
        cos = a @ b / (np.linalg.norm(a) * np.linalg.norm(b))
        assert cos > 0.999

    def test_quantized_generate_runs(self):
        cfg = get_config("qwen2", "tiny")  # exercises bias path too
        params = quantize_params(
            T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        )
        out = generate(
            params, cfg, [[1, 2, 3]], max_new_tokens=4, eos_ids=[], greedy=True
        )
        assert out.tokens.shape == (1, 4)
        assert (out.tokens >= 0).all()

    def test_quantized_sharding_rules(self):
        if len(jax.devices()) < 2:
            pytest.skip("needs multiple devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        cfg = get_config("llama", "tiny")
        params = quantize_params(T.init_params(jax.random.key(0), cfg))
        mesh = make_mesh({"tp": 2})
        sharded = shard_params(mesh, params)
        wq = sharded["layers"]["wq"]
        assert wq["q"].sharding.spec == jax.sharding.PartitionSpec(
            None, None, "tp"
        )
        # Scale keeps only the output-axis sharding.
        wo = sharded["layers"]["wo"]
        assert wo["scale"].sharding.spec == jax.sharding.PartitionSpec(
            None, None, None
        )

    def test_int8_kv_cache_close_to_fp(self, monkeypatch):
        """Quantized-KV decode won't be bit-identical to fp, but greedy
        tokens on a tiny model should track closely — and the int8 cache
        must ACTUALLY be built (spy guards against the flag silently not
        reaching init_cache)."""
        from adversarial_spec_tpu.engine import generate as gen_mod

        built_kv_dtypes = []
        real_init = gen_mod.init_cache

        def spy(*a, **k):
            built_kv_dtypes.append(k.get("kv_dtype", ""))
            return real_init(*a, **k)

        monkeypatch.setattr(gen_mod, "init_cache", spy)

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompt = [[1, 5, 9, 3, 7, 2]]
        kw = dict(max_new_tokens=8, eos_ids=[], greedy=True, speculative=False)
        fp = generate(params, cfg, prompt, **kw)
        q8 = generate(params, cfg, prompt, kv_dtype="int8", **kw)
        assert built_kv_dtypes == ["", "int8"]
        # Same shapes; overwhelming token agreement on a short decode.
        assert q8.tokens.shape == fp.tokens.shape
        agree = (q8.tokens == fp.tokens).mean()
        assert agree >= 0.75, (fp.tokens, q8.tokens)

    def test_int8_kv_cache_structure(self):
        cache = T.init_cache(
            get_config("llama", "tiny"), 2, 16, kv_dtype="int8"
        )
        assert set(cache) == {"k", "v", "ks", "vs"}
        assert cache["k"].dtype == jnp.int8
        assert cache["ks"].dtype == jnp.float32
        assert cache["ks"].shape == cache["k"].shape[:-1] + (1,)

    def test_int8_kv_incremental_matches_full(self):
        """Self-consistency: chunked prefill + decode over the quantized
        cache equals one full forward over the same quantized cache."""
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        ids = jax.random.randint(jax.random.key(3), (1, 12), 0, cfg.vocab_size)
        full_cache = T.init_cache(cfg, 1, 12, dtype=jnp.float32, kv_dtype="int8")
        pos = jnp.arange(12, dtype=jnp.int32)[None]
        kv = jnp.ones((1, 12), bool)
        full_logits, _ = T.forward(
            params, cfg, ids, pos, full_cache, jnp.int32(0), kv
        )
        cache = T.init_cache(cfg, 1, 12, dtype=jnp.float32, kv_dtype="int8")
        logits8, cache = T.forward(
            params, cfg, ids[:, :8], pos[:, :8], cache, jnp.int32(0), kv
        )
        np.testing.assert_allclose(
            np.asarray(logits8), np.asarray(full_logits[:, :8]),
            rtol=2e-4, atol=2e-4,
        )
        step_logits, cache = T.forward(
            params, cfg, ids[:, 8:9], pos[:, 8:9], cache, jnp.int32(8), kv
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full_logits[:, 8]),
            rtol=2e-4, atol=2e-4,
        )

    def test_int8_kv_composes_with_mesh(self, capsys):
        """int8 KV no longer falls back on sharded meshes: the sharded
        decode matches the single-device int8 tokens exactly."""
        import jax as _jax
        from adversarial_spec_tpu.engine.generate import generate
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        if len(_jax.devices()) < 2:
            pytest.skip("needs multiple devices")
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        kw = dict(
            max_new_tokens=4, eos_ids=[], greedy=True, kv_dtype="int8",
            speculative=False,
        )
        ref = generate(params, cfg, [[1, 2, 3]], **kw)
        mesh = make_mesh({"tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(sharded, cfg, [[1, 2, 3]], mesh=mesh, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)
        assert "full-precision KV" not in capsys.readouterr().err

    def test_int8_kv_composes_with_paged(self, capsys):
        """int8 + paged is a supported composition (int8 pages + scale
        pages): no downgrade warning, output matches the dense int8 run."""
        import numpy as np

        from adversarial_spec_tpu.engine.generate import generate

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        kw = dict(
            max_new_tokens=4, eos_ids=[], greedy=True, kv_dtype="int8",
            speculative=False,
        )
        dense = generate(params, cfg, [[1, 2, 3]], **kw)
        out = generate(
            params, cfg, [[1, 2, 3]], paged=True, page_size=16, **kw
        )
        np.testing.assert_array_equal(dense.tokens, out.tokens)
        assert "full-precision KV" not in capsys.readouterr().err

    def test_registry_quant_field_roundtrip(self):
        from adversarial_spec_tpu.engine.registry import (
            ModelSpec,
            load_registry,
            save_registry_entry,
        )

        save_registry_entry(ModelSpec(alias="q8", quant="int8"))
        assert load_registry()["q8"].quant == "int8"

    def test_int8_kv_composes_with_sp_prefill(self, capsys):
        """int8 + sp prefill is a supported composition (quantized at
        the reshard-to-decode boundary): no downgrade warning."""
        import jax as _jax
        from adversarial_spec_tpu.engine.generate import generate
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        if len(_jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        mesh = make_mesh({"sp": 4})
        sharded = shard_params(mesh, params)
        prompt = list(range(3, 3 + 128))  # S % sp == 0 → sp prefill
        with mesh:
            out = generate(
                sharded, cfg, [prompt], mesh=mesh,
                max_new_tokens=4, eos_ids=[], greedy=True,
                kv_dtype="int8", speculative=False,
            )
        assert out.tokens.shape == (1, 4)
        assert "full-precision KV" not in capsys.readouterr().err


class TestFusedMatmulFuzz:
    """Property fuzz for the fused Pallas dequant-matmul path
    (ops/pallas_quant.py, interpret mode) against a pure-numpy oracle:
    odd and even contraction widths, extreme scale magnitudes, stacked
    activation batches, and the ``dequantize(rows=)`` padded-row edge —
    the in-kernel unpack must do the same int math the oracle does."""

    def test_fuzz_fused_vs_numpy_oracle(self):
        from adversarial_spec_tpu.ops import pallas_quant

        rng = np.random.default_rng(11)
        for case in range(8):
            K = int(rng.integers(1, 97))
            N = int(rng.integers(1, 40))
            M = int(rng.integers(1, 20))
            xshape = (M, K) if case % 2 else (2, M, K)
            # Extreme magnitudes (bounded away from f32 overflow in the
            # K-length accumulation).
            mag = 10.0 ** float(rng.integers(-12, 12))
            w = (rng.standard_normal((K, N)) * mag).astype(np.float32)
            x = rng.standard_normal(xshape).astype(np.float32)
            xj = jnp.asarray(x)

            w8 = quantize_int8(jnp.asarray(w))
            ref8 = x.astype(np.float64) @ (
                np.asarray(w8["q"], np.float64)
                * np.asarray(w8["scale"], np.float64)
            )
            got8 = np.asarray(
                pallas_quant.matmul_int8(
                    xj, w8["q"], w8["scale"], interpret=True
                )
            )
            tol = 2e-4 * (np.max(np.abs(ref8)) + 1e-30)
            assert np.max(np.abs(got8 - ref8)) <= tol, (case, K, N, mag)

            w4 = quantize_int4(jnp.asarray(w))
            # Oracle via the independent numpy unpack — also the
            # dequantize(rows=) edge: odd K packed one zero row.
            deq = _np_unpack_int4(np.asarray(w4["q4"]), K).astype(
                np.float64
            ) * np.asarray(w4["scale"], np.float64)
            np.testing.assert_array_equal(
                np.asarray(dequantize(w4, rows=K)),
                deq.astype(np.float32),
            )
            ref4 = x.astype(np.float64) @ deq
            got4 = np.asarray(
                pallas_quant.matmul_int4(
                    xj, w4["q4"], w4["scale"], interpret=True
                )
            )
            tol = 2e-4 * (np.max(np.abs(ref4)) + 1e-30)
            assert np.max(np.abs(got4 - ref4)) <= tol, (case, K, N, mag)

    def test_fused_dispatch_matches_kernel_exactly(self):
        """quant.matmul(use_pallas=True) must BE the kernel result (no
        silent fallback for a supported shape)."""
        from adversarial_spec_tpu.ops import pallas_quant

        x = jax.random.normal(jax.random.key(5), (6, 33), jnp.float32)
        w4 = quantize_int4(
            jax.random.normal(jax.random.key(6), (33, 24), jnp.float32)
        )
        assert pallas_quant.fused_supported(x, w4)
        np.testing.assert_array_equal(
            np.asarray(matmul(x, w4, use_pallas=True, interpret=True)),
            np.asarray(
                pallas_quant.matmul_int4(
                    x, w4["q4"], w4["scale"], interpret=True
                )
            ),
        )
