"""Continuous batching scheduler tests.

Correctness bar: every request's greedy output through the scheduler must
equal its output through plain generate() — admission order, slot reuse,
and co-residency with other sequences must never change tokens."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine.generate import generate
from adversarial_spec_tpu.engine.scheduler import (
    ContinuousBatcher,
    SchedRequest,
)
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    return params, cfg


@pytest.fixture(autouse=True)
def _spec_off(monkeypatch):
    """This module pins admission/interleave/slot semantics; speculation
    is default-on and would only multiply the jit programs every batcher
    here compiles (each distinct (B, cap) pair adds a γ-wide verify
    program). Spec-on coverage of these same paths — parity, legacy
    loop, slot churn, tp=2 — lives in tests/test_spec_batcher.py."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)


def _reference(params, cfg, prompt, max_new):
    out = generate(
        params,
        cfg,
        [prompt],
        max_new_tokens=max_new,
        eos_ids=[],
        greedy=True,
        speculative=False,
    )
    return out.tokens[0, : out.n_generated[0]]


class TestContinuousBatcher:
    def test_single_request_matches_generate(self, tiny_model):
        params, cfg = tiny_model
        b = ContinuousBatcher(params, cfg, max_batch=2, max_new_cap=16)
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5, 9], max_new_tokens=8))
        results = b.run_all()
        assert len(results) == 1
        ref = _reference(params, cfg, [1, 5, 9], 8)
        np.testing.assert_array_equal(results[0].tokens, np.asarray(ref))

    def test_more_requests_than_slots(self, tiny_model):
        """5 requests through 2 slots: queueing + slot reuse + co-residency
        must leave every output identical to its solo reference."""
        params, cfg = tiny_model
        prompts = [
            [1, 5, 9],
            [2, 6],
            [8, 8, 8, 4],
            [3],
            [7, 1, 4, 1, 5],
        ]
        budgets = [8, 5, 9, 4, 7]
        b = ContinuousBatcher(params, cfg, max_batch=2, max_new_cap=16)
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            b.submit(SchedRequest(req_id=i, prompt_ids=p, max_new_tokens=n))
        results = b.run_all()
        assert [r.req_id for r in results] == [0, 1, 2, 3, 4]
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            ref = _reference(params, cfg, p, n)
            np.testing.assert_array_equal(
                results[i].tokens, np.asarray(ref), err_msg=f"req {i}"
            )

    def test_different_budgets_finish_independently(self, tiny_model):
        params, cfg = tiny_model
        b = ContinuousBatcher(params, cfg, max_batch=3, max_new_cap=32)
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 2], max_new_tokens=2))
        b.submit(SchedRequest(req_id=1, prompt_ids=[3, 4], max_new_tokens=20))
        results = b.run_all()
        assert results[0].n_generated == 2
        assert results[1].n_generated == 20

    def test_eos_stops_row(self, tiny_model):
        params, cfg = tiny_model
        probe = _reference(params, cfg, [1, 2], 4)
        eos = int(probe[0])
        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=32, eos_ids=[eos]
        )
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 2], max_new_tokens=30))
        results = b.run_all()
        n = results[0].n_generated
        assert n < 30
        assert int(results[0].tokens[n - 1]) == eos

    def test_pages_recycled_across_requests(self, tiny_model):
        """Sequential requests through one slot must free and reuse pages
        (allocator returns to full free count at drain)."""
        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=1, max_new_cap=8, capacity_tokens=512
        )
        total_pages = b.allocator.free_pages
        for i in range(4):
            b.submit(
                SchedRequest(req_id=i, prompt_ids=[1 + i], max_new_tokens=4)
            )
        results = b.run_all()
        assert len(results) == 4
        assert b.allocator.free_pages == total_pages

    def test_cap_violation_rejected(self, tiny_model):
        params, cfg = tiny_model
        b = ContinuousBatcher(params, cfg, max_batch=1, max_new_cap=8)
        with pytest.raises(ValueError, match="exceeds scheduler"):
            b.submit(
                SchedRequest(req_id=0, prompt_ids=[1], max_new_tokens=99)
            )

    def test_oversized_request_rejected_at_submit(self, tiny_model):
        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=1, max_new_cap=64, capacity_tokens=128
        )
        with pytest.raises(ValueError, match="pool holds only"):
            b.submit(
                SchedRequest(
                    req_id=0, prompt_ids=[1] * 100, max_new_tokens=64
                )
            )

    def test_full_pool_defers_admission(self, tiny_model):
        """Two slots, pool sized for ~one resident: the second request
        must wait for the first to finish (deferred, not crashed) and
        still produce its exact reference output."""
        params, cfg = tiny_model
        # Prompt buckets to 128; 128+8=136 tokens → 3 pages of 64. Pool of
        # 4 pages fits one resident but not two.
        b = ContinuousBatcher(
            params,
            cfg,
            max_batch=2,
            max_new_cap=8,
            page_size=64,
            capacity_tokens=256,
        )
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5], max_new_tokens=8))
        b.submit(SchedRequest(req_id=1, prompt_ids=[2, 6], max_new_tokens=8))
        results = b.run_all()
        assert len(results) == 2
        for i, p in enumerate([[1, 5], [2, 6]]):
            ref = _reference(params, cfg, p, 8)
            np.testing.assert_array_equal(results[i].tokens, np.asarray(ref))


class TestPagedUnderDp:
    """Paged decode over a dp-sharded mesh: per-device page pools,
    device-local tables, zero cross-device page traffic (VERDICT r1
    item 4 — paged no longer excludes multi-device)."""

    @pytest.fixture(autouse=True)
    def _needs_8_devices(self):
        if len(jax.devices()) < 8:
            pytest.skip("requires 8 virtual devices")

    @pytest.mark.parametrize("n_prompts", [4, 3])
    def test_paged_dp_matches_single_device(self, n_prompts):
        """Greedy paged decode on dp=4 (with dp-padding for 3 prompts)
        must reproduce single-device paged tokens exactly."""
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        prompts = [[1 + i, 5, 9, 3 + i] for i in range(n_prompts)]
        kw = dict(
            max_new_tokens=6, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({})  # all 8 devices on dp
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(sharded, cfg, prompts, mesh=mesh, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)
        np.testing.assert_array_equal(ref.n_generated, out.n_generated)


def _spy_dispatches(sched_mod, calls):
    """Wrap the dispatch entry points with call-order spies; returns the
    originals for restoration. The speculative siblings map onto the
    same letters — "D" is a decode-side program (token-at-a-time or
    draft+verify), "F" is a fused ride (either flavor) — so the
    interleave properties hold under whatever the speculation default
    is."""
    real_prefill = sched_mod.prefill_chunk
    real_decode = sched_mod.scheduler_decode_chunk
    real_fused = sched_mod.fused_prefill_decode_chunk
    real_spec = sched_mod.scheduler_spec_chunk
    real_fused_spec = sched_mod.fused_prefill_spec_chunk

    def spy_prefill(*a, **kw):
        calls.append("P")
        return real_prefill(*a, **kw)

    def spy_decode(*a, **kw):
        calls.append("D")
        return real_decode(*a, **kw)

    def spy_fused(*a, **kw):
        calls.append("F")
        return real_fused(*a, **kw)

    def spy_spec(*a, **kw):
        calls.append("D")
        return real_spec(*a, **kw)

    def spy_fused_spec(*a, **kw):
        calls.append("F")
        return real_fused_spec(*a, **kw)

    sched_mod.prefill_chunk = spy_prefill
    sched_mod.scheduler_decode_chunk = spy_decode
    sched_mod.fused_prefill_decode_chunk = spy_fused
    sched_mod.scheduler_spec_chunk = spy_spec
    sched_mod.fused_prefill_spec_chunk = spy_fused_spec
    return (
        real_prefill,
        real_decode,
        real_fused,
        real_spec,
        real_fused_spec,
    )


class TestChunkedPrefillInterleave:
    """Admission prefill no longer pauses decode: a multi-chunk prompt's
    chunks ride INSIDE the residents' decode program (the fused step),
    and the legacy --no-interleave loop still interleaves them as
    separate serialized dispatches."""

    def _workload(self, params, cfg, **kw):
        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=64, chunk=8, **kw
        )
        long_prompt = [((i * 11) % 500) + 3 for i in range(600)]
        b.submit(
            SchedRequest(req_id=0, prompt_ids=[1, 5, 9],
                         max_new_tokens=64)
        )
        b.submit(
            SchedRequest(req_id=1, prompt_ids=long_prompt,
                         max_new_tokens=8)
        )
        return b, long_prompt

    def test_admission_chunks_ride_fused_with_decode(self, tiny_model):
        import adversarial_spec_tpu.engine.scheduler as sched_mod

        params, cfg = tiny_model
        calls = []
        real = _spy_dispatches(sched_mod, calls)
        try:
            b, long_prompt = self._workload(params, cfg, interleave=True)
            results = b.run_all()
        finally:
            (
                sched_mod.prefill_chunk,
                sched_mod.scheduler_decode_chunk,
                sched_mod.fused_prefill_decode_chunk,
                sched_mod.scheduler_spec_chunk,
                sched_mod.fused_prefill_spec_chunk,
            ) = real

        assert len(results) == 2
        s = "".join(calls)
        # The 600-token prompt's multi-chunk prefill must ride the
        # resident row's decode program — fused dispatches, not
        # standalone prefills between decode chunks.
        assert "F" in s, f"no fused prefill+decode step: {s}"
        # The fused steps carry the admission: no standalone decode may
        # run between two standalone prefills while it is in flight.
        assert "PDP" not in s, f"admission stalled decode: {s}"
        # Fusion must not change tokens (row independence).
        ref0 = _reference(params, cfg, [1, 5, 9], 64)
        ref1 = _reference(params, cfg, long_prompt, 8)
        np.testing.assert_array_equal(results[0].tokens, np.asarray(ref0))
        np.testing.assert_array_equal(results[1].tokens, np.asarray(ref1))
        # Telemetry: the ride-along chunks were accounted as overlapped.
        assert b.overlapped_prefill_s > 0
        assert b.prefill_time_s == (
            b.stalled_prefill_s + b.overlapped_prefill_s
        )

    def test_legacy_loop_interleaves_serialized_dispatches(self, tiny_model):
        """--no-interleave escape hatch: the original loop — a decode
        chunk between two standalone admission chunks, never a fused
        dispatch — and identical greedy tokens."""
        import adversarial_spec_tpu.engine.scheduler as sched_mod

        params, cfg = tiny_model
        calls = []
        real = _spy_dispatches(sched_mod, calls)
        try:
            b, long_prompt = self._workload(params, cfg, interleave=False)
            results = b.run_all()
        finally:
            (
                sched_mod.prefill_chunk,
                sched_mod.scheduler_decode_chunk,
                sched_mod.fused_prefill_decode_chunk,
                sched_mod.scheduler_spec_chunk,
                sched_mod.fused_prefill_spec_chunk,
            ) = real

        s = "".join(calls)
        assert "F" not in s, f"legacy loop dispatched a fused step: {s}"
        assert "PDP" in s, f"no decode between admission chunks: {s}"
        ref0 = _reference(params, cfg, [1, 5, 9], 64)
        ref1 = _reference(params, cfg, long_prompt, 8)
        np.testing.assert_array_equal(results[0].tokens, np.asarray(ref0))
        np.testing.assert_array_equal(results[1].tokens, np.asarray(ref1))
        # Legacy prefill is all stall: nothing rode a fused step.
        assert b.overlapped_prefill_s == 0
        assert b.stalled_prefill_s > 0

    def test_fused_and_legacy_loops_token_identical(self, tiny_model):
        """The bench's acceptance invariant, pinned in-tree: the same
        mixed admit-while-decoding workload produces byte-identical
        greedy tokens through both drive loops."""
        params, cfg = tiny_model
        outs = {}
        for enabled in (True, False):
            b, _ = self._workload(params, cfg, interleave=enabled)
            outs[enabled] = [r.tokens.tolist() for r in b.run_all()]
        assert outs[True] == outs[False]

    def test_slot_reuse_mid_flight_does_not_truncate(self, tiny_model):
        """Regression: a step dispatched while slot s ran request A,
        fetched AFTER s was freed and re-admitted to request B, must not
        apply A's completion flag to B (the per-slot generation guard).
        Mixed lengths/budgets force exactly that slot churn; every row
        must still emit its full reference output."""
        params, cfg = tiny_model
        prompts = [
            [((i * 13 + j * 7) % 500) + 3 for j in range(296 if i % 2 == 0 else 5)]
            for i in range(6)
        ]
        budgets = [8 if i % 2 == 0 else 24 for i in range(6)]
        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=32, chunk=8,
            interleave=True, prefix_cache=False,
        )
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            b.submit(SchedRequest(req_id=i, prompt_ids=p, max_new_tokens=n))
        results = b.run_all()
        assert [r.req_id for r in results] == list(range(6))
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            ref = _reference(params, cfg, p, n)
            assert results[i].n_generated == len(ref), f"req {i} truncated"
            np.testing.assert_array_equal(
                results[i].tokens, np.asarray(ref), err_msg=f"req {i}"
            )

    def test_prefill_time_telemetry_accumulates(self, tiny_model):
        params, cfg = tiny_model
        b = ContinuousBatcher(params, cfg, max_batch=1, max_new_cap=8)
        b.submit(SchedRequest(req_id=0, prompt_ids=[2, 4, 6],
                              max_new_tokens=4))
        b.run_all()
        assert b.prefill_time_s > 0
        assert b.decode_time_s > 0
        assert b.prefill_time_s == (
            b.stalled_prefill_s + b.overlapped_prefill_s
        )

    def test_pipeline_depth_one_matches_depth_two(self, tiny_model):
        """Depth 1 (fused but synchronous) and depth 2 (double-buffered)
        are scheduling choices only — tokens must be identical."""
        params, cfg = tiny_model
        outs = {}
        for depth in (1, 2):
            b, _ = self._workload(
                params, cfg, interleave=True, pipeline_depth=depth
            )
            outs[depth] = [r.tokens.tolist() for r in b.run_all()]
        assert outs[1] == outs[2]


class TestBatcherInt8Pool:
    def test_int8_pool_matches_int8_reference(self, tiny_model):
        """ContinuousBatcher with kv_dtype=int8: output must match the
        round-synchronous int8 dense-cache generate() for each request."""
        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=16, kv_dtype="int8"
        )
        assert "ks" in b.pool
        b.submit(SchedRequest(req_id=0, prompt_ids=[1, 5, 9], max_new_tokens=8))
        results = b.run_all()
        ref = generate(
            params,
            cfg,
            [[1, 5, 9]],
            max_new_tokens=8,
            eos_ids=[],
            greedy=True,
            speculative=False,
            kv_dtype="int8",
        )
        np.testing.assert_array_equal(
            results[0].tokens,
            np.asarray(ref.tokens[0, : ref.n_generated[0]]),
        )


class TestPagedUnderTp:
    def test_paged_tp_matches_single_device(self, tiny_model):
        """Paged decode on a tp-only mesh (head-sharded global pool, the
        fused kernel under shard_map in interpret mode) must reproduce
        single-device paged tokens."""
        if len(jax.devices()) < 2:
            pytest.skip("requires 2 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model  # n_kv_heads=2 → tp=2 divides
        prompts = [[1, 5, 9, 3, 7, 2], [4, 4, 8]]
        kw = dict(
            max_new_tokens=8, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
            share_prefix=False,
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            # Exercise the shard_mapped KERNEL (interpret on CPU), not
            # just the GSPMD gather path.
            out = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)
        # And the gather path for completeness.
        with mesh:
            out2 = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=False, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out2.tokens)

    def test_paged_tp_int8_pool(self, tiny_model):
        """int8 pages compose with the tp-sharded pool."""
        if len(jax.devices()) < 2:
            pytest.skip("requires 2 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [[1, 5, 9, 3, 7, 2], [4, 4, 8]]
        kw = dict(
            max_new_tokens=6, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
            share_prefix=False, kv_dtype="int8",
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)

    def test_paged_mixed_dp_tp_matches_single_device(self, tiny_model):
        """Paged decode on a MIXED dp=2×tp=2 mesh (per-dp-slice pool
        layout, GSPMD chunk loop, kernel under the dp×tp shard_map with
        global→local id shift) must reproduce single-device paged
        tokens — on both the kernel and gather paths."""
        if len(jax.devices()) < 4:
            pytest.skip("requires 4 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [[1, 5, 9, 3, 7, 2], [4, 4, 8], [6, 1, 1, 2], [9, 9]]
        kw = dict(
            max_new_tokens=8, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
            share_prefix=False,
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"dp": 2, "tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)
        with mesh:
            out2 = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=False, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out2.tokens)

    def test_paged_mixed_dp_tp_int8_pool(self, tiny_model):
        """int8 pages compose with the mixed dp×tp pool."""
        if len(jax.devices()) < 4:
            pytest.skip("requires 4 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [[1, 5, 9, 3, 7, 2], [4, 4, 8], [6, 1, 1, 2], [9, 9]]
        kw = dict(
            max_new_tokens=6, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
            share_prefix=False, kv_dtype="int8",
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"dp": 2, "tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)

    def test_paged_sp_only_matches_single_device(self, tiny_model):
        """Paged decode on an sp-only mesh: sp is a prefill axis — during
        decode it idles/replicates (pool replicated, same semantics as the
        dense decode path after reshard_cache_for_decode) — so paged
        tokens must reproduce single-device paged tokens. Exercises the
        sp_prefill → reshard → page-migration handoff (the 16k-context
        config's paged decode, VERDICT r4 item 9)."""
        if len(jax.devices()) < 2:
            pytest.skip("requires 2 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [[1, 5, 9, 3, 7, 2], [4, 4, 8], [6, 1, 1, 2], [9, 9]]
        kw = dict(
            max_new_tokens=8, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
            share_prefix=False,
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"sp": 2, "tp": 1}, devices=jax.devices()[:2])
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)
        with mesh:
            out2 = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=False, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out2.tokens)

    def test_paged_sp_tp_int8_pool(self, tiny_model):
        """Paged + int8 pages on an sp×tp mesh (heads over tp, pool
        replicated over sp; int8 quantization happens at the sp→decode
        reshard boundary before page migration)."""
        if len(jax.devices()) < 4:
            pytest.skip("requires 4 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model  # n_kv_heads=2 → tp=2 divides
        prompts = [[1, 5, 9, 3, 7, 2], [4, 4, 8]]
        kw = dict(
            max_new_tokens=6, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
            share_prefix=False, kv_dtype="int8",
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"sp": 2, "tp": 2}, devices=jax.devices()[:4])
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)

    def test_paged_dp_sp_mixed_matches_single_device(self, tiny_model):
        """Paged decode on a dp×sp mesh reuses the per-dp-slice mixed
        layout (rows + page slabs over dp, sp replicated during decode)."""
        if len(jax.devices()) < 4:
            pytest.skip("requires 4 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [[1, 5, 9, 3, 7, 2], [4, 4, 8], [6, 1, 1, 2], [9, 9]]
        kw = dict(
            max_new_tokens=8, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
            share_prefix=False,
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"dp": 2, "sp": 2, "tp": 1})
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=True, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out.tokens)
        with mesh:
            out2 = generate(
                sharded, cfg, prompts, mesh=mesh,
                use_pallas_decode=False, **kw
            )
        np.testing.assert_array_equal(ref.tokens, out2.tokens)

    def test_paged_tp_not_dividing_heads_falls_back_dense(
        self, tiny_model, capsys
    ):
        """tp ∤ n_kv_heads warns + refuses paged BEFORE touching pool
        layout. The dense fallback then hits the same divisibility wall
        in its own cache sharding (dense KV heads shard over tp too), so
        pin that SPECIFIC ValueError — a blanket except would also pass
        if the fallback path crashed some new way after the warning
        (ADVICE r5)."""
        if len(jax.devices()) < 8:
            pytest.skip("requires 8 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh

        params, cfg = tiny_model  # n_kv_heads=2; tp=8 does not divide
        mesh = make_mesh({"dp": 1, "sp": 1, "tp": 8})
        from adversarial_spec_tpu.engine import generate as G

        prompts = [[1, 5, 9], [2, 6]]
        with pytest.raises(ValueError, match="partitioned"):
            with mesh:
                G.generate(
                    params, cfg, prompts, mesh=mesh,
                    max_new_tokens=2, eos_ids=[], greedy=True,
                    paged=True, speculative=False,
                )
        # The paged eligibility check rejected (and warned) before any
        # pool layout work; the error above came from the dense cache.
        assert "falling back to the dense cache" in capsys.readouterr().err

    @pytest.mark.slow
    def test_paged_sp_long_prompt_multi_page(self, tiny_model):
        """sp paged at a ~1.5k-token prompt: the page table spans ~100
        pages per row and the sp_prefill → reshard → migration handoff
        moves every prompt slot (gather path keeps CPU cost sane; the
        kernel path is pinned at small scale above)."""
        if len(jax.devices()) < 2:
            pytest.skip("requires 2 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        rng = np.random.default_rng(7)
        prompts = [
            rng.integers(3, cfg.vocab_size, 1500).tolist(),
            rng.integers(3, cfg.vocab_size, 900).tolist(),
        ]
        kw = dict(
            max_new_tokens=4, eos_ids=[], greedy=True,
            paged=True, page_size=16, speculative=False,
            share_prefix=False, use_pallas_decode=False,
        )
        ref = generate(params, cfg, prompts, **kw)
        mesh = make_mesh({"sp": 2, "tp": 1}, devices=jax.devices()[:2])
        sharded = shard_params(mesh, params)
        with mesh:
            out = generate(sharded, cfg, prompts, mesh=mesh, **kw)
        np.testing.assert_array_equal(ref.tokens, out.tokens)
